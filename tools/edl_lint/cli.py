"""The `python -m tools.edl_lint` entrypoint.

Runs the selected rules over the shared Project cache, applies inline
suppressions and the checked-in baseline, and reports. Exit 1 on any
non-baselined finding, a parse error, or a STALE baseline entry (a key
that no longer fires — fix the baseline so it only ever lists live,
deliberate debt), 0 otherwise.

Modes:
  (default)            lint everything
  PATH [PATH...]       report only findings under the given path prefixes
  --changed            report only findings in files `git diff` says
                       changed (analysis stays whole-program, so cross-
                       file rules still see the full picture); reuses
                       the cached analysis when the tree is unchanged
  --rules A,B          run only the named rules
  --list-rules         print the rule catalog and exit
  --format=json        machine-readable findings on stdout (stable
                       schema: rule/path/line/message/key/fix_hint);
                       --json is the legacy alias
  --write-baseline     regenerate tools/edl_lint/baseline.txt from the
                       current findings (review the diff!) — also the
                       way stale entries are pruned
  --no-baseline        ignore the baseline (see every finding)
  --write-knob-docs    regenerate docs/KNOBS.md from common/knobs.py
"""

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.edl_lint import core  # noqa: E402
from tools.edl_lint.loader import Project  # noqa: E402
from tools.edl_lint.rules import ALL_RULES, rule_by_name  # noqa: E402

BASELINE_PATH = os.path.join(REPO, "tools", "edl_lint", "baseline.txt")
# Whole-analysis cache (findings + per-rule timings keyed by a content
# digest of every analyzed file AND the lint plane itself). Lives under
# .git so it never dirties the working tree; missing .git disables it.
CACHE_PATH = os.path.join(REPO, ".git", "edl-lint-cache.json")


def _changed_files():
    """Repo-relative paths git considers changed (working tree + index
    vs HEAD, plus untracked); None when git is unavailable."""
    try:
        tracked = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=REPO, capture_output=True, text=True, timeout=30,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=REPO, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if tracked.returncode != 0:
        return None
    paths = set()
    for out in (tracked.stdout, untracked.stdout):
        paths.update(
            os.path.normpath(p) for p in out.splitlines() if p.strip()
        )
    return paths


# -- analysis cache ---------------------------------------------------------


def _tree_digest(project):
    """Content digest of every analyzed source plus the lint plane's own
    sources — editing a rule invalidates the cache even though the rule
    files are excluded from analysis."""
    h = hashlib.sha256()
    for rel in sorted(project.files):
        sf = project.files[rel]
        h.update(rel.encode())
        h.update(hashlib.sha256(sf.source.encode()).digest())
    lint_root = os.path.join(REPO, "tools", "edl_lint")
    for dirpath, dirnames, filenames in os.walk(lint_root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            h.update(os.path.relpath(path, REPO).encode())
            try:
                with open(path, "rb") as f:
                    h.update(hashlib.sha256(f.read()).digest())
            except OSError:
                pass
    return h.hexdigest()


def _load_cache(digest):
    try:
        with open(CACHE_PATH) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if payload.get("digest") != digest:
        return None
    return payload


def _write_cache(digest, findings, suppressed, files_scanned,
                 rule_seconds):
    if not os.path.isdir(os.path.dirname(CACHE_PATH)):
        return
    payload = {
        "digest": digest,
        "findings": [
            {
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                "key": f.key,
                "fix_hint": f.fix_hint,
            }
            for f in findings
        ],
        "suppressed": suppressed,
        "files_scanned": files_scanned,
        "rule_seconds": rule_seconds,
    }
    tmp = CACHE_PATH + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, CACHE_PATH)
    except OSError:
        pass


def _findings_from_cache(payload):
    return [
        core.Finding(
            d["rule"], d["path"], d["line"], d["message"],
            key=d["key"], fix_hint=d.get("fix_hint", ""),
        )
        for d in payload["findings"]
    ]


def _timing_note(rule_seconds):
    parts = " ".join(
        f"{name}={seconds:.2f}s"
        for name, seconds in sorted(
            rule_seconds.items(), key=lambda kv: -kv[1]
        )
    )
    return f"per-rule: {parts}" if parts else ""


def run(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m tools.edl_lint",
        description="elasticdl_tpu static-analysis plane",
    )
    parser.add_argument("paths", nargs="*",
                        help="restrict REPORTING to these path prefixes")
    parser.add_argument("--rules", default="",
                        help="comma-separated rule subset")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", dest="fmt",
                        help="output format (json: stable "
                             "rule/path/line/message/key/fix_hint schema)")
    parser.add_argument("--json", action="store_const", const="json",
                        dest="fmt", help="alias for --format=json")
    parser.add_argument("--changed", action="store_true",
                        help="report only findings in git-changed files")
    parser.add_argument("--write-baseline", action="store_true")
    parser.add_argument("--no-baseline", action="store_true")
    parser.add_argument("--write-knob-docs", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.name:>20}  {' '.join(cls.doc.split())}")
        return 0

    if args.write_knob_docs:
        from tools.edl_lint.rules.env_knobs import render_knob_docs

        path = os.path.join(REPO, "docs", "KNOBS.md")
        with open(path, "w") as f:
            f.write(render_knob_docs())
        print(f"wrote {os.path.relpath(path, REPO)}")
        return 0

    started = time.monotonic()
    if args.rules:
        try:
            selected = [rule_by_name(n.strip())
                        for n in args.rules.split(",") if n.strip()]
        except KeyError as e:
            parser.error(f"unknown rule {e.args[0]!r} "
                         f"(--list-rules shows the catalog)")
    else:
        selected = list(ALL_RULES)
    all_rules = len(selected) == len(ALL_RULES)

    project = Project.load(REPO)
    digest = _tree_digest(project) if all_rules else None
    cache = _load_cache(digest) if (all_rules and args.changed) else None

    if cache is not None:
        findings = _findings_from_cache(cache)
        suppressed = cache["suppressed"]
        rule_seconds = cache["rule_seconds"]
        files_scanned = cache["files_scanned"]
        from_cache = True
    else:
        from_cache = False
        files_scanned = len(project.files)
        findings = []
        rule_seconds = {}
        for cls in selected:
            rule_started = time.monotonic()
            findings.extend(cls().check(project))
            rule_seconds[cls.name] = round(
                time.monotonic() - rule_started, 3
            )
        for rel, lineno, message in project.parse_errors:
            findings.append(core.Finding(
                "parse", rel, lineno, f"syntax error: {message}",
                key="syntax-error",
            ))

        # Inline suppressions.
        kept = []
        suppressed = 0
        for f in findings:
            sf = project.files.get(f.path)
            if sf is not None and core.is_suppressed(f, sf.suppressions):
                suppressed += 1
            else:
                kept.append(f)
        findings = kept
        if all_rules and digest is not None:
            _write_cache(
                digest, findings, suppressed, files_scanned,
                rule_seconds,
            )

    if args.write_baseline:
        keys = core.write_baseline(BASELINE_PATH, findings)
        print(f"wrote {len(keys)} baseline entr"
              f"{'y' if len(keys) == 1 else 'ies'} to "
              f"{os.path.relpath(BASELINE_PATH, REPO)}")
        return 0

    baseline = (
        set() if args.no_baseline else core.load_baseline(BASELINE_PATH)
    )
    fresh = [f for f in findings if f.baseline_key not in baseline]
    grandfathered = len(findings) - len(fresh)
    # A baseline key that no longer fires is stale debt bookkeeping:
    # fail so the file shrinks the moment a grandfathered finding is
    # fixed (--write-baseline prunes). Only meaningful when every rule
    # ran — a subset run can't tell stale from not-checked.
    stale = (
        sorted(baseline - {f.baseline_key for f in findings})
        if all_rules
        else []
    )

    # Reporting filters (analysis already ran whole-program).
    scope_note = ""
    if args.changed:
        changed = _changed_files()
        if changed is not None:
            fresh = [f for f in fresh if os.path.normpath(f.path)
                     in changed]
            scope_note = f" [changed-only: {len(changed)} files]"
    if args.paths:
        prefixes = tuple(os.path.normpath(p) for p in args.paths)
        fresh = [
            f for f in fresh
            if os.path.normpath(f.path).startswith(prefixes)
        ]
        scope_note += f" [paths: {', '.join(prefixes)}]"
    if from_cache:
        scope_note += " [cached analysis]"

    fresh.sort(key=lambda f: (f.path, f.line, f.rule))
    elapsed = time.monotonic() - started
    failed = bool(fresh) or bool(stale)

    if args.fmt == "json":
        print(json.dumps(
            {
                "findings": [f.as_dict() for f in fresh],
                "baselined": grandfathered,
                "stale_baseline": stale,
                "suppressed": suppressed,
                "files_scanned": files_scanned,
                "rules": [cls.name for cls in selected],
                "rule_seconds": rule_seconds,
                "cache": from_cache,
                "seconds": round(elapsed, 3),
            },
            indent=2,
        ))
    else:
        for f in fresh:
            print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
        for key in stale:
            print(
                f"stale baseline entry: {key} (no longer fires — run "
                f"--write-baseline to prune)"
            )
        status = "FAIL" if failed else "OK"
        print(
            f"edl-lint: {status} — {len(fresh)} finding(s), "
            f"{grandfathered} baselined, {len(stale)} stale, "
            f"{suppressed} suppressed; "
            f"{files_scanned} files, "
            f"{len(selected)} rule(s), {elapsed:.1f}s{scope_note}"
        )
        note = _timing_note(rule_seconds)
        if note:
            print(note)
    return 1 if failed else 0


def main():
    sys.exit(run())
