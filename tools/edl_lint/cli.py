"""The `python -m tools.edl_lint` entrypoint.

Runs the selected rules over the shared Project cache, applies inline
suppressions and the checked-in baseline, and reports. Exit 1 on any
non-baselined finding (or a parse error), 0 otherwise.

Modes:
  (default)            lint everything
  PATH [PATH...]       report only findings under the given path prefixes
  --changed            report only findings in files `git diff` says
                       changed (analysis stays whole-program, so cross-
                       file rules still see the full picture)
  --rules A,B          run only the named rules
  --list-rules         print the rule catalog and exit
  --json               machine-readable findings on stdout
  --write-baseline     regenerate tools/edl_lint/baseline.txt from the
                       current findings (review the diff!)
  --no-baseline        ignore the baseline (see every finding)
  --write-knob-docs    regenerate docs/KNOBS.md from common/knobs.py
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.edl_lint import core  # noqa: E402
from tools.edl_lint.loader import Project  # noqa: E402
from tools.edl_lint.rules import ALL_RULES, rule_by_name  # noqa: E402

BASELINE_PATH = os.path.join(REPO, "tools", "edl_lint", "baseline.txt")


def _changed_files():
    """Repo-relative paths git considers changed (working tree + index
    vs HEAD, plus untracked); None when git is unavailable."""
    try:
        tracked = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=REPO, capture_output=True, text=True, timeout=30,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=REPO, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if tracked.returncode != 0:
        return None
    paths = set()
    for out in (tracked.stdout, untracked.stdout):
        paths.update(
            os.path.normpath(p) for p in out.splitlines() if p.strip()
        )
    return paths


def run(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m tools.edl_lint",
        description="elasticdl_tpu static-analysis plane",
    )
    parser.add_argument("paths", nargs="*",
                        help="restrict REPORTING to these path prefixes")
    parser.add_argument("--rules", default="",
                        help="comma-separated rule subset")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument("--changed", action="store_true",
                        help="report only findings in git-changed files")
    parser.add_argument("--write-baseline", action="store_true")
    parser.add_argument("--no-baseline", action="store_true")
    parser.add_argument("--write-knob-docs", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.name:>14}  {' '.join(cls.doc.split())}")
        return 0

    if args.write_knob_docs:
        from tools.edl_lint.rules.env_knobs import render_knob_docs

        path = os.path.join(REPO, "docs", "KNOBS.md")
        with open(path, "w") as f:
            f.write(render_knob_docs())
        print(f"wrote {os.path.relpath(path, REPO)}")
        return 0

    started = time.monotonic()
    if args.rules:
        try:
            selected = [rule_by_name(n.strip())
                        for n in args.rules.split(",") if n.strip()]
        except KeyError as e:
            parser.error(f"unknown rule {e.args[0]!r} "
                         f"(--list-rules shows the catalog)")
    else:
        selected = list(ALL_RULES)

    project = Project.load(REPO)
    findings = []
    for cls in selected:
        findings.extend(cls().check(project))
    for rel, lineno, message in project.parse_errors:
        findings.append(core.Finding(
            "parse", rel, lineno, f"syntax error: {message}",
            key="syntax-error",
        ))

    # Inline suppressions.
    kept = []
    suppressed = 0
    for f in findings:
        sf = project.files.get(f.path)
        if sf is not None and core.is_suppressed(f, sf.suppressions):
            suppressed += 1
        else:
            kept.append(f)
    findings = kept

    if args.write_baseline:
        keys = core.write_baseline(BASELINE_PATH, findings)
        print(f"wrote {len(keys)} baseline entr"
              f"{'y' if len(keys) == 1 else 'ies'} to "
              f"{os.path.relpath(BASELINE_PATH, REPO)}")
        return 0

    baseline = (
        set() if args.no_baseline else core.load_baseline(BASELINE_PATH)
    )
    fresh = [f for f in findings if f.baseline_key not in baseline]
    grandfathered = len(findings) - len(fresh)

    # Reporting filters (analysis already ran whole-program).
    scope_note = ""
    if args.changed:
        changed = _changed_files()
        if changed is not None:
            fresh = [f for f in fresh if os.path.normpath(f.path)
                     in changed]
            scope_note = f" [changed-only: {len(changed)} files]"
    if args.paths:
        prefixes = tuple(os.path.normpath(p) for p in args.paths)
        fresh = [
            f for f in fresh
            if os.path.normpath(f.path).startswith(prefixes)
        ]
        scope_note += f" [paths: {', '.join(prefixes)}]"

    fresh.sort(key=lambda f: (f.path, f.line, f.rule))
    elapsed = time.monotonic() - started

    if args.as_json:
        print(json.dumps(
            {
                "findings": [f.as_dict() for f in fresh],
                "baselined": grandfathered,
                "suppressed": suppressed,
                "files_scanned": len(project.files),
                "rules": [cls.name for cls in selected],
                "seconds": round(elapsed, 3),
            },
            indent=2,
        ))
    else:
        for f in fresh:
            print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
        status = "FAIL" if fresh else "OK"
        print(
            f"edl-lint: {status} — {len(fresh)} finding(s), "
            f"{grandfathered} baselined, {suppressed} suppressed; "
            f"{len(project.files)} files, "
            f"{len(selected)} rule(s), {elapsed:.1f}s{scope_note}"
        )
    return 1 if fresh else 0


def main():
    sys.exit(run())
