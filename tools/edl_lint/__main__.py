from tools.edl_lint.cli import main

main()
