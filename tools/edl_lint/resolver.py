"""Scope/attribute resolution shared by the rules.

ModuleInfo answers "what does this Name/Attribute chain actually refer
to" inside one module: import aliases are expanded to dotted targets
(`jnp.dot` -> `jax.numpy.dot`, a bare `shard_map` imported from
jax_compat -> `elasticdl_tpu.common.jax_compat.shard_map`), module-level
string constants are tracked for env-key resolution, and logger bindings
(`logger = get_logger(...)`) are recognized for the jit-purity pass.

Resolver layers the whole-program view on top: a class index across every
module, dotted-module -> file mapping, and cross-module constant lookup
(`observability.OBS_DIR_ENV` resolved through the import graph).
"""

import ast


class ModuleInfo:
    def __init__(self, sf, package):
        self.sf = sf
        self.package = package  # dotted package for relative imports
        self.imports = {}  # local alias -> dotted target
        self.constants = {}  # NAME -> str value (module-level)
        self.loggers = set()  # names bound to logger factories
        self.classes = {}  # name -> ClassDef
        self.functions = {}  # name -> FunctionDef (module level)
        self._scan()

    def _scan(self):
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else name
                    self.imports[name] = target
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = self.package.split(".") if self.package else []
                    parts = parts[: len(parts) - (node.level - 1)]
                    base = ".".join(parts + ([base] if base else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    name = alias.asname or alias.name
                    self.imports[name] = (
                        f"{base}.{alias.name}" if base else alias.name
                    )
        for node in self.sf.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if not isinstance(target, ast.Name):
                    continue
                if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str
                ):
                    self.constants[target.id] = node.value.value
                elif isinstance(node.value, ast.Call):
                    dotted = self.dotted(node.value.func) or ""
                    if dotted.endswith("get_logger") or dotted.endswith(
                        "logging.getLogger"
                    ):
                        self.loggers.add(target.id)

    def dotted(self, node):
        """Dotted name for a Name/Attribute chain with the leading alias
        expanded through this module's imports; None for anything else."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.imports.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))


class Resolver:
    """Whole-program indexes, built lazily from the Project file cache."""

    def __init__(self, project):
        self.project = project
        self._modules = {}
        self.dotted_to_rel = {}
        self.class_index = {}
        for rel, sf in project.files.items():
            dotted = project.module_name(rel)
            if dotted:
                self.dotted_to_rel[dotted] = rel
        for rel in project.files:
            minfo = self.module(rel)
            for name in minfo.classes:
                self.class_index.setdefault(name, []).append(rel)

    def module(self, rel):
        minfo = self._modules.get(rel)
        if minfo is None:
            dotted = self.project.module_name(rel) or ""
            package = dotted.rsplit(".", 1)[0] if "." in dotted else ""
            if rel.endswith("__init__.py"):
                package = dotted
            minfo = ModuleInfo(self.project.files[rel], package)
            self._modules[rel] = minfo
        return minfo

    def resolve_constant(self, dotted):
        """The string value of a fully-dotted module constant
        (`elasticdl_tpu.observability.OBS_DIR_ENV` -> "ELASTICDL_OBS_DIR"),
        or None."""
        if not dotted or "." not in dotted:
            return None
        module_part, attr = dotted.rsplit(".", 1)
        rel = self.dotted_to_rel.get(module_part)
        if rel is None:
            return None
        return self.module(rel).constants.get(attr)

    def resolve_str(self, node, minfo):
        """Static string value of an expression: literal, same-module
        constant, or imported-module constant. None when unknown."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            value = minfo.constants.get(node.id)
            if value is not None:
                return value
            return self.resolve_constant(minfo.imports.get(node.id, ""))
        if isinstance(node, ast.Attribute):
            return self.resolve_constant(minfo.dotted(node))
        return None

    def find_class(self, name):
        """[(rel, ClassDef)] for every definition of a class name."""
        return [
            (rel, self.module(rel).classes[name])
            for rel in self.class_index.get(name, ())
        ]
