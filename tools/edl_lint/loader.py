"""Shared module loader: walk the repo ONCE, parse every Python file
ONCE, and hand the same AST/source/suppression cache to every rule.
Rules never touch the filesystem themselves — per-file passes iterate
`project.files`, whole-program passes use the cross-file indexes built
lazily by resolver.Resolver."""

import ast
import os

from tools.edl_lint.core import parse_suppressions

# The lint plane itself hosts pattern literals (forbidden-call regexes,
# fixture snippets) that would self-trigger textual rules.
_SKIP_DIRS = {"__pycache__"}
_SKIP_PREFIXES = (os.path.join("tools", "edl_lint"),)


class SourceFile:
    __slots__ = ("rel", "path", "source", "lines", "tree", "suppressions")

    def __init__(self, rel, path, source, tree):
        self.rel = rel
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.suppressions = parse_suppressions(self.lines)


class Project:
    """Every parsed source file plus repo metadata, shared by all rules."""

    def __init__(self, root, files, parse_errors):
        self.root = root
        self.files = files  # rel -> SourceFile
        self.parse_errors = parse_errors  # [(rel, lineno, message)]
        self._resolver = None

    @classmethod
    def load(cls, root, roots=("elasticdl_tpu", "tools"),
             extra_files=("bench.py", "__graft_entry__.py")):
        files = {}
        parse_errors = []

        def add(path):
            rel = os.path.relpath(path, root)
            if rel.startswith(_SKIP_PREFIXES):
                return
            try:
                with open(path) as f:
                    source = f.read()
            except OSError:
                return
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError as e:
                parse_errors.append((rel, e.lineno or 0, str(e)))
                return
            files[rel] = SourceFile(rel, path, source, tree)

        for top in roots:
            for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, top)
            ):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        add(os.path.join(dirpath, name))
        for name in extra_files:
            path = os.path.join(root, name)
            if os.path.exists(path):
                add(path)
        return cls(root, files, parse_errors)

    @property
    def resolver(self):
        if self._resolver is None:
            from tools.edl_lint.resolver import Resolver

            self._resolver = Resolver(self)
        return self._resolver

    def iter_files(self, prefix=None):
        for rel in sorted(self.files):
            if prefix is None or rel.startswith(prefix):
                yield self.files[rel]

    def module_name(self, rel):
        """Dotted module name for a repo-relative path, or None for
        scripts outside an importable package."""
        if not rel.endswith(".py"):
            return None
        parts = rel[:-3].split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)
