"""edl-lint: the unified static-analysis plane for elasticdl_tpu.

One AST framework (shared module loader, scope/attribute resolver,
per-file and whole-program passes, inline suppressions, a checked-in
baseline) hosting every repo invariant that can be enforced without
running the code — and without importing jax, so `make lint` stays in
the seconds range on any box:

  concurrency    lock-guard consistency + lock-ordering cycles
  jit-purity     Python side effects / host syncs inside traced fns
  env-knobs      ELASTICDL_* reads go through common/knobs.py
  proto-drift    hand-regenerated pb2 matches the .proto
  rpc-deadlines  every RPC method has a deadline; no raw grpc
  metric-names   coherent metric namespace
  dead-code      unused imports, unreferenced module-level symbols

Run `python -m tools.edl_lint --list-rules` for the catalog and
docs/STATIC_ANALYSIS.md for the suppression/baseline workflow.
"""
