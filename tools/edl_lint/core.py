"""Core types of the edl-lint plane: findings, rules, suppressions,
baseline. Stdlib-only; nothing here may import jax (enforced by
tests/test_edl_lint.py)."""

import re


class Finding:
    """One violation.

    `key` is the STABLE identity used for suppression baselines — it must
    not contain line numbers (so a baseline survives unrelated edits).
    Rules pass a symbol-ish key ("Class.attr", "ELASTICDL_FOO", ...); the
    full baseline key is "<rule>|<path>|<key>".
    """

    __slots__ = ("rule", "path", "line", "message", "key", "fix_hint")

    def __init__(self, rule, path, line, message, key=None, fix_hint=""):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message
        self.key = key if key is not None else message
        # One-line remediation note carried into --format=json (stable
        # schema: file/line/rule/message/key/fix_hint).
        self.fix_hint = fix_hint

    @property
    def baseline_key(self):
        return f"{self.rule}|{self.path}|{self.key}"

    def __repr__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self):
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "key": self.baseline_key,
            "fix_hint": self.fix_hint,
        }


class Rule:
    """A named analysis. Subclasses set `name`/`doc` and implement
    check(project) -> iterable of Finding."""

    name = ""
    doc = ""

    def check(self, project):
        raise NotImplementedError


_SUPPRESS_RE = re.compile(
    r"#\s*edl-lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)


def parse_suppressions(lines):
    """{lineno: frozenset(rule names or 'all')} from source lines.

    A `# edl-lint: disable=<rule>[,<rule>...]` comment suppresses matching
    findings on its own line; when the comment stands alone on the line,
    it also covers the following line (so long flagged statements keep
    the annotation above them).
    """
    out = {}
    for lineno, line in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = frozenset(r.strip() for r in m.group(1).split(","))
        out[lineno] = out.get(lineno, frozenset()) | rules
        if line.lstrip().startswith("#"):
            out[lineno + 1] = out.get(lineno + 1, frozenset()) | rules
    return out


def is_suppressed(finding, suppressions):
    rules = suppressions.get(finding.line)
    return bool(rules) and (finding.rule in rules or "all" in rules)


def load_baseline(path):
    """The grandfathered-finding keys, one per line; '#' comments and
    blank lines ignored. Missing file = empty baseline."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except FileNotFoundError:
        return set()
    return {
        line.strip()
        for line in lines
        if line.strip() and not line.lstrip().startswith("#")
    }


BASELINE_HEADER = """\
# edl-lint baseline: grandfathered findings, one stable key per line
# (rule|path|symbol). A finding whose key appears here is reported as
# "baselined" and does not fail the run. Regenerate with
#   python -m tools.edl_lint --write-baseline
# after REVIEWING that every new entry is a deliberate grandfather, not
# a fresh regression. Shrink this file whenever you fix an entry.
"""


def write_baseline(path, findings):
    keys = sorted({f.baseline_key for f in findings})
    with open(path, "w") as f:
        f.write(BASELINE_HEADER)
        for key in keys:
            f.write(key + "\n")
    return keys
