from elasticdl_tpu.proto import elasticdl_tpu_pb2

__all__ = ["elasticdl_tpu_pb2"]
