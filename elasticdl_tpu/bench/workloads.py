"""The model benchmarks (imports jax; only the runner loads this).

Moved from the old repo-root ``bench.py`` with one methodological
change: instead of a single timed loop per benchmark, the step loop
runs as REPEATED TIMED WINDOWS (same total step count, split into
``windows`` chunks), so every benchmark yields a sample set —
examples/s per window — that ``stats.summarize`` can put a bootstrap
CI around and ``stats.significance_verdict`` can compare across runs.
A run-to-run drift claim needs within-run variance to stand on.

Budget awareness: each workload takes an optional BudgetClock and stops
opening new windows when the budget is gone — degrading the sample
count (marked ``truncated``) instead of dying with nothing.

Method is otherwise unchanged: the batch is placed on device once and
the jitted train step runs with donated buffers (synthetic-data-
resident mode) — measuring the training step, not host dataloading.
"""

import json
import os
import time

import jax
import numpy as np

from elasticdl_tpu.bench import matrix as _matrix
from elasticdl_tpu.bench import stats

# Peak dense bf16 FLOP/s by device kind (public spec sheets), for the MFU
# denominator. Override with EDL_PEAK_TFLOPS for unlisted hardware.
PEAK_TFLOPS_BY_KIND = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 459.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}

DEFAULT_WINDOWS = 5


def _peak_flops():
    env = os.environ.get("EDL_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = jax.devices()[0].device_kind
    tflops = PEAK_TFLOPS_BY_KIND.get(kind)
    return tflops * 1e12 if tflops else None


def _timed_windows(trainer, features, labels, steps_per_window, windows,
                   warmup, clock=None):
    """Build the trainer's jitted step, park the batch on device, run
    ``windows`` timed windows of ``steps_per_window`` steps each with
    donated buffers. Returns (per-window elapsed list, flops_per_step or
    None, truncated). At least one window always runs — a blown budget
    degrades evidence, it doesn't zero it (the hard watchdog above this
    owns the truly-wedged case)."""
    trainer.init_variables_if_needed(features)
    step = trainer._train_step
    variables, opt_state = trainer._variables, trainer._opt_state
    rng = jax.random.PRNGKey(0)
    dev_f = jax.device_put(features)
    dev_l = jax.device_put(labels)

    flops = None
    try:
        cost = step.lower(
            variables, opt_state, rng, dev_f, dev_l
        ).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass

    loss = None
    for _ in range(warmup):
        variables, opt_state, loss = step(
            variables, opt_state, rng, dev_f, dev_l
        )
    # On tunneled device platforms block_until_ready can return at
    # dispatch; a scalar host read is the only sync that provably waits
    # for execution. (warmup=0 skips the sync: the first window then
    # absorbs the compile, which is what asking for no warmup means.)
    if loss is not None:
        float(loss)

    elapsed = []
    truncated = False
    for w in range(windows):
        if w > 0 and clock is not None and clock.expired:
            truncated = True
            break
        start = time.perf_counter()
        for _ in range(steps_per_window):
            variables, opt_state, loss = step(
                variables, opt_state, rng, dev_f, dev_l
            )
        float(loss)  # force completion of the window's chain
        elapsed.append(time.perf_counter() - start)
    return elapsed, flops, truncated


def _window_result(elapsed, batch_size, steps_per_window, truncated,
                   flops=None):
    """Per-window elapsed -> the benchmark's reported dict: median
    examples/s with samples + CI, step time, optional TFLOP/s + MFU."""
    samples = [
        batch_size * steps_per_window / e for e in elapsed
    ]
    summary = stats.summarize(samples)
    total = sum(elapsed)
    steps = steps_per_window * len(elapsed)
    out = {
        "examples_per_sec": summary["median"],
        "samples": [round(s, 1) for s in samples],
        "step_time_ms": total / steps * 1e3,
        "windows": len(elapsed),
        "steps_per_window": steps_per_window,
    }
    if "ci95" in summary:
        out["examples_per_sec_ci95"] = [
            round(summary["ci95"][0], 1),
            round(summary["ci95"][1], 1),
        ]
    if truncated:
        out["truncated"] = True
    if flops:
        out["model_tflops_per_sec"] = flops * steps / total / 1e12
        peak = _peak_flops()
        if peak:
            out["mfu"] = flops * steps / total / peak
    return out


def _bench_image_model(model_def, batch_size, steps_per_window, windows,
                       warmup, clock=None):
    """Shared ImageNet-shape image benchmark: examples/sec with CI, step
    time, and (when XLA cost analysis yields flops) TFLOP/s + MFU."""
    from elasticdl_tpu.common.model_utils import get_model_spec
    from elasticdl_tpu.worker.trainer import LocalTrainer

    spec = get_model_spec(model_def)
    trainer = LocalTrainer(
        spec.build_model(), spec.loss, spec.build_optimizer_spec()
    )
    rng = np.random.default_rng(0)
    features = rng.normal(size=(batch_size, 224, 224, 3)).astype(np.float32)
    labels = rng.integers(0, 1000, batch_size).astype(np.int64)
    elapsed, flops, truncated = _timed_windows(
        trainer, features, labels, steps_per_window, windows, warmup,
        clock,
    )
    return _window_result(
        elapsed, batch_size, steps_per_window, truncated, flops
    )


def bench_resnet50(batch_size=128, steps_per_window=6,
                   windows=DEFAULT_WINDOWS, warmup=5, clock=None):
    return _bench_image_model(
        "elasticdl_tpu.models.resnet50.resnet50", batch_size,
        steps_per_window, windows, warmup, clock,
    )


def bench_mobilenetv2(batch_size=256, steps_per_window=6,
                      windows=DEFAULT_WINDOWS, warmup=5, clock=None):
    """Second image benchmark of the reference's table: MobileNetV2 at
    150 img/s on one P100 (ftlib_benchmark.md:138-156)."""
    out = _bench_image_model(
        "elasticdl_tpu.models.mobilenetv2.mobilenetv2", batch_size,
        steps_per_window, windows, warmup, clock,
    )
    out["vs_p100_150img_s"] = out["examples_per_sec"] / 150.0
    return out


def bench_deepfm_criteo(batch_size=32768, steps_per_window=6,
                        windows=DEFAULT_WINDOWS, warmup=5, clock=None):
    """Batch 32768: measured sweep on TPU v5e — 197k ex/s @8192, 199k
    @16384, 211k @32768 (embedding gathers amortize better at width);
    large batches are the normal recsys regime on TPU."""
    from elasticdl_tpu.common.model_utils import get_model_spec
    from elasticdl_tpu.models.dac_ctr.transform import NUM_FIELDS, TOTAL_IDS
    from elasticdl_tpu.worker.trainer import LocalTrainer

    spec = get_model_spec("elasticdl_tpu.models.dac_ctr.deepfm")
    trainer = LocalTrainer(
        spec.build_model(), spec.loss, spec.build_optimizer_spec()
    )
    rng = np.random.default_rng(0)
    features = {
        "dense": rng.normal(size=(batch_size, 13)).astype(np.float32),
        "ids": rng.integers(
            0, TOTAL_IDS, size=(batch_size, NUM_FIELDS)
        ).astype(np.int32),
    }
    labels = rng.integers(0, 2, batch_size).astype(np.int64)
    elapsed, _, truncated = _timed_windows(
        trainer, features, labels, steps_per_window, windows, warmup,
        clock,
    )
    return _window_result(
        elapsed, batch_size, steps_per_window, truncated
    )


def _device_transfer_mb_per_s(mb=8):
    """One d2h round of `mb` MB: the PS bench's measured limiter on
    tunnel-attached chips (PERF_SNAPSHOT ps_push_decomposition). Recorded
    as session context so a flagged/slow PS result can be attributed to
    the environment; None off-device."""
    try:
        import jax.numpy as jnp

        if jax.default_backend() == "cpu":
            return None
        n = mb * (1 << 20) // 4
        best = float("inf")
        for i in range(2):
            x = jax.block_until_ready(
                jnp.ones((n,), jnp.float32) * (i + 1)
            )
            t0 = time.perf_counter()
            np.asarray(x)  # forced host materialization
            best = min(best, time.perf_counter() - t0)
        return round(mb / best, 1)
    except Exception:
        return None


def bench_deepfm_ps(batch_size=16384, steps=6, warmup=4, num_ps=2,
                    repeats=3, clock=None):
    # warmup=4 covers each of the 4 distinct id batches once, so measured
    # steps hit warm PS rows (the r4 run-to-run spread — 3.6k vs 7.2k on
    # identical configs — was cold-row lazy init landing inside the timed
    # window of whichever run compiled first). Batch 16384: the
    # push-thread overlap needs enough per-step RPC work to amortize its
    # contention with prefetch on a single-core host.
    """The other half of the DeepFM north star (BASELINE.json: "large
    embedding_service + elastic worker preemption"): DeepFM with its
    wide/deep tables PS-RESIDENT on real localhost PS shards, one worker
    pulling rows / pushing IndexedSlices per step. The four legacy
    configs — (serial | overlapped push) x (f32 | bf16 wire) at
    ``num_ps`` shards — are the fixed-shard slice of the full
    ``matrix.bench_ps_matrix``; the matrix adds the shard-count axis.
    Each config's headline is the median over ``repeats`` runs with the
    phase breakdown (now including the serialize/wire/apply split inside
    push_gradients) from the run closest to the median."""
    batches = _matrix.make_batches(batch_size)
    configs = (
        ("serialized", False, "float32"),
        ("serialized_bf16_wire", False, "bfloat16"),
        ("pipelined", True, "float32"),
        ("pipelined_bf16_wire", True, "bfloat16"),
        # The quantized wire: int8 block-scaled dense grads (error
        # feedback) + bf16 embedding legs, on the packed transport.
        ("pipelined_int8_wire", True, "int8"),
    )
    out = {
        "repeats": repeats,
        "loadavg_start": os.getloadavg()[0],
        # Context for flagged runs: this bench's limiter is the
        # host<->device hop, and on tunnel-attached chips its bandwidth
        # fluctuates session to session — record it like loadavg.
        "device_transfer_mb_per_s": _device_transfer_mb_per_s(),
    }
    for name, pipelined, wire in configs:
        if clock is not None and clock.expired and name != "serialized":
            out[name] = {"skipped": "budget"}
            continue
        out[name] = _matrix._run_cell(
            batches, steps, warmup, num_ps, pipelined, wire, repeats,
            clock,
        )
    out["loadavg_end"] = os.getloadavg()[0]
    if out.get("serialized", {}).get("examples_per_sec"):
        # Derived ratios inherit contamination: a flagged/truncated
        # median must not silently feed a clean-looking headline
        # speedup.
        def ratio(num, den):
            if not out.get(num, {}).get("examples_per_sec"):
                return None, False
            value = (
                out[num]["examples_per_sec"]
                / out[den]["examples_per_sec"]
            )
            flagged = any(
                out[c].get("truncated") or out[c].get("run_spread", 1)
                > 1.25
                for c in (num, den)
            )
            return value, flagged

        speedup, flagged = ratio("pipelined", "serialized")
        if speedup:
            out["overlap_speedup"] = speedup
            if flagged:
                out["overlap_speedup_contaminated"] = True
        speedup, flagged = ratio("serialized_bf16_wire", "serialized")
        if speedup:
            out["bf16_wire_speedup"] = speedup
            if flagged:
                out["bf16_wire_speedup_contaminated"] = True
        speedup, flagged = ratio("pipelined_int8_wire", "pipelined")
        if speedup:
            out["int8_wire_speedup"] = speedup
            if flagged:
                out["int8_wire_speedup_contaminated"] = True
    return out


def bench_elastic_rejoin():
    """The third north-star metric (BASELINE.json): seconds for a job that
    loses a worker to SIGKILL to have its replacement back in the job
    (detection + task recovery + relaunch + re-init + first RPC).
    Runs the real CLI cluster on the CPU platform so it never contends
    with the TPU benchmarks; rejoin time is control-plane latency.

    Cells (the recompile-free-elasticity additions):
      rejoin_s              cold relaunch, best-of-2, no compile cache —
                            comparable with every earlier round;
      rejoin_warm_cache_s   one more drill with ELASTICDL_COMPILE_CACHE_DIR
                            armed: the replacement worker rehydrates its
                            step from the disk entries its first
                            incarnation wrote, so the rejoin no longer
                            contains an XLA compile;
      regroup_cold_s /      in-process world-RESHAPE latency (see
      regroup_warm_s        bench/regroup.py): what a SURVIVOR pays to
                            step in a changed world, with and without a
                            speculatively prebuilt executable.
    """
    import subprocess
    import sys
    import tempfile

    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        sys.path.insert(0, os.path.join(repo, "tools"))
        sys.path.insert(0, os.path.join(repo, "tests"))
        import test_module
        from elastic_drill import run_drill

        from elasticdl_tpu.data.recordfile import RecordFileWriter

        out = {}
        with tempfile.TemporaryDirectory() as d:
            data = os.path.join(d, "linear.edlr")
            with RecordFileWriter(data) as w:
                for r in test_module.make_linear_records(256):
                    w.write(r)
            # Best-of-2: rejoin time is control-plane latency on a shared
            # single-core host; one run can absorb seconds of unrelated
            # load (VERDICT r3 asked every host-bound bench for best-of-N).
            results = [
                run_drill(
                    data,
                    model_zoo=os.path.join(repo, "tests"),
                    model_def="test_module",
                    num_workers=2,
                    num_ps=1,
                    num_epochs=300,
                    # Cold must be COLD even when the operator exports
                    # the cache knob globally (empty string = disabled):
                    # rejoin_s is the historical cold series.
                    env_overrides={
                        "JAX_PLATFORMS": "cpu",
                        "ELASTICDL_COMPILE_CACHE_DIR": "",
                    },
                    timeout=600,
                )
                for _ in range(2)
            ]
            ok = [r for r in results if r.get("rejoin_s") is not None]
            best = (
                min(ok, key=lambda r: r["rejoin_s"]) if ok else results[0]
            )
            out.update(
                {
                    "rejoin_s": best.get("rejoin_s"),
                    "rejoin_s_runs": [
                        r.get("rejoin_s") for r in results
                    ],
                    "best_of_n": 2,
                    "completed": best.get("completed"),
                    "relaunched": best.get("relaunched"),
                }
            )
            # Warm-cache drill: the job's own pre-kill compiles populate
            # the cache; the SIGKILLed worker's replacement rehydrates.
            warm = run_drill(
                data,
                model_zoo=os.path.join(repo, "tests"),
                model_def="test_module",
                num_workers=2,
                num_ps=1,
                num_epochs=300,
                env_overrides={
                    "JAX_PLATFORMS": "cpu",
                    "ELASTICDL_COMPILE_CACHE_DIR": os.path.join(
                        d, "compile_cache"
                    ),
                },
                timeout=600,
            )
            out["rejoin_warm_cache_s"] = warm.get("rejoin_s")
            out["rejoin_warm_completed"] = warm.get("completed")
        # In-process regroup cells, in their own virtual-8-device
        # subprocess so this process's backend stays untouched.
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        # Cold must be COLD: no persistent cache for the subprocess.
        env.pop("ELASTICDL_COMPILE_CACHE_DIR", None)
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "elasticdl_tpu.bench.regroup"],
                capture_output=True,
                text=True,
                env=env,
                cwd=repo,
                timeout=300,
            )
            line = next(
                (
                    ln
                    for ln in proc.stdout.splitlines()
                    if ln.startswith("REGROUP_RESULT ")
                ),
                None,
            )
            if line:
                regroup = json.loads(line[len("REGROUP_RESULT "):])
                for key in (
                    "regroup_cold_s",
                    "regroup_warm_s",
                    "speculative_consumed",
                    "error",
                ):
                    if key in regroup:
                        out[key] = regroup[key]
            else:
                out["regroup_error"] = (proc.stderr or "no output")[
                    -200:
                ]
        except Exception as e:
            out["regroup_error"] = str(e)[:200]
        return out
    except Exception as e:  # never let the drill sink the whole bench
        return {"rejoin_s": None, "error": str(e)[:200]}
