"""CLI: ``python -m elasticdl_tpu.bench [--smoke] [...]``.

The repo-root ``bench.py`` (what the driver invokes) is a thin shim
onto this entrypoint; ``--gate`` forwards to the regression gate so
one module answers both "measure" and "judge".
"""

import argparse
import sys

from elasticdl_tpu.common import knobs


def main(argv=None):
    parser = argparse.ArgumentParser("bench")
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="tiny shapes, CPU-safe, exits < 60 s (harness self-check)",
    )
    parser.add_argument(
        "--watchdog_s", "--watchdog-s",
        dest="watchdog_s",
        type=float,
        default=None,
        help="per-benchmark wall-clock bound (default "
        "ELASTICDL_BENCH_WATCHDOG_S, 50 with --smoke; 0 disables): one "
        "wedged config cannot eat the run",
    )
    parser.add_argument(
        "--budget-s",
        type=float,
        default=None,
        help="soft total budget: workloads stop opening timed windows "
        "when it runs out, degrading sample counts instead of dying "
        "(default ELASTICDL_BENCH_BUDGET_S; 0 disables)",
    )
    parser.add_argument(
        "--no-matrix",
        action="store_true",
        help="skip the PS microbench matrix in the full run",
    )
    parser.add_argument(
        "--out",
        default=None,
        help="also write the result line to this file (atomic)",
    )
    parser.add_argument(
        "--gate",
        action="store_true",
        help="run the regression gate instead of benchmarks "
        "(see python -m elasticdl_tpu.bench.gate --help for options)",
    )
    args, rest = parser.parse_known_args(argv)

    if args.gate:
        from elasticdl_tpu.bench.gate import main as gate_main

        return gate_main(rest)
    if rest:
        parser.error(f"unrecognized arguments: {' '.join(rest)}")

    from elasticdl_tpu.bench import runner

    if args.smoke:
        return runner.run_smoke(
            watchdog_s=(
                args.watchdog_s if args.watchdog_s is not None else 50.0
            ),
            budget_s=args.budget_s,
            out_path=args.out,
        )
    return runner.run_full(
        watchdog_s=(
            args.watchdog_s
            if args.watchdog_s is not None
            else knobs.get_float("ELASTICDL_BENCH_WATCHDOG_S")
        ),
        budget_s=args.budget_s,
        with_matrix=not args.no_matrix,
        out_path=args.out,
    )


if __name__ == "__main__":
    sys.exit(main())
