"""PS-mode microbench matrix: wire codec x push pipelining x shard count.

The denominator for the quantized-transport work: BENCH_r04 showed
``push_gradients`` eating ~79% of the PS-mode DeepFM step, but one
number for the whole push can't say whether a codec change helped the
serialize leg, the wire leg, or the PS-side apply. Every cell here
reports examples/s (median over repeats, bootstrap CI when enough
windows fit the budget) AND the push decomposed into
serialize / wire / apply sub-spans:

- serialize: worker-side host work — device_get + dedup + proto build
  (``push_serialize`` in the trainer's Timing, recorded by PSClient);
- apply:     PS-side optimizer apply, reported back per push on
  ``PushGradientsResponse.apply_seconds`` (max over shards — shards
  apply concurrently, so the slowest shard gates the RPC);
- wire:      the remainder of the RPC wait after subtracting the
  reported apply — TCP + proto decode on both ends.

Cells run the same hot loop as the headline ``deepfm_ps`` bench (real
localhost gRPC shards, native id-map kernels), so a matrix cell and the
headline number are directly comparable.
"""

import time

import numpy as np

from elasticdl_tpu.bench import stats
from elasticdl_tpu.observability import flightrec

DEFAULT_SHARD_COUNTS = (1, 2)
DEFAULT_CODECS = ("float32", "bfloat16", "int8")
DEFAULT_PIPELINING = (False, True)

_CODEC_SHORT = {"float32": "f32", "bfloat16": "bf16", "int8": "int8"}

# Sub-phases PSClient records inside push_gradients (see worker/
# ps_client.py); the matrix folds them into each cell's breakdown.
PUSH_SUBPHASES = ("push_serialize", "push_wire", "push_apply")


def make_batches(batch_size, n_batches=4, seed=0):
    """Distinct id sets so embedding pulls stay realistic run to run."""
    from elasticdl_tpu.models.dac_ctr.transform import (
        NUM_FIELDS,
        TOTAL_IDS,
    )

    rng = np.random.default_rng(seed)
    batches = []
    for _ in range(n_batches):
        features = {
            "dense": rng.normal(size=(batch_size, 13)).astype(np.float32),
            "ids": rng.integers(
                0, TOTAL_IDS, size=(batch_size, NUM_FIELDS)
            ).astype(np.int32),
        }
        labels = rng.integers(0, 2, batch_size).astype(np.int64)
        batches.append((features, labels))
    return batches


def run_ps_config(batches, steps, warmup, num_ps, pipelined, wire_dtype,
                  prefetch=True):
    """One timed run of the PS hot loop under one matrix cell's config.

    Returns {"examples_per_sec", "step_time_ms", "phase_mean_ms",
    "push_breakdown_ms"}. warmup should cover every distinct batch once
    (cold-row lazy init inside the timed window was the old r4 spread).
    ``prefetch`` toggles the prefetch-overlap plane (lookahead pulls +
    versioned row cache); the hot loop passes each step's NEXT batch as
    the lookahead hint, exactly like a real data loader with one batch
    of readahead.
    """
    from elasticdl_tpu.common.model_utils import get_model_spec
    from elasticdl_tpu.ps.parameter_server import ParameterServer
    from elasticdl_tpu.worker.ps_client import PSClient
    from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer

    spec = get_model_spec("elasticdl_tpu.models.dac_ctr.deepfm_ps")
    batch_size = len(batches[0][1])
    servers = [
        ParameterServer(
            i, num_ps, optimizer_spec=spec.build_optimizer_spec()
        )
        for i in range(num_ps)
    ]
    client = None
    trainer = None
    try:
        client = PSClient(
            [s.addr for s in servers], worker_id=0, wire_dtype=wire_dtype
        )
        trainer = ParameterServerTrainer(
            spec.build_model(),
            spec.loss,
            spec.build_optimizer_spec(),
            client,
            embedding_inputs=spec.module.embedding_inputs,
            pipeline_pushes=pipelined,
            prefetch_overlap=prefetch,
        )
        n_batches = len(batches)
        for i in range(warmup):
            f, l = batches[i % n_batches]
            trainer.train_minibatch(
                f, l, next_features=batches[(i + 1) % n_batches][0]
            )
        trainer._flush_pushes()
        trainer.timing.reset()
        start = time.perf_counter()
        loss = None
        for i in range(steps):
            f, l = batches[i % n_batches]
            _, _, loss = trainer.train_minibatch(
                f, l, next_features=batches[(i + 1) % n_batches][0]
            )
        float(loss)
        trainer._flush_pushes()
        elapsed = time.perf_counter() - start
        phases = {
            phase: round(s["mean_s"] * 1e3, 2)
            for phase, s in trainer.timing.summary().items()
        }
        breakdown = {
            p[len("push_"):]: phases[p]
            for p in PUSH_SUBPHASES
            if p in phases
        }
        return {
            "examples_per_sec": batch_size * steps / elapsed,
            "step_time_ms": elapsed / steps * 1e3,
            "phase_mean_ms": phases,
            "push_breakdown_ms": breakdown,
        }
    finally:
        if trainer is not None:
            trainer.close()
        if client is not None:
            client.close()
        for s in servers:
            s.stop()


def cell_name(num_ps, pipelined, wire_dtype, prefetch=True):
    codec = _CODEC_SHORT.get(wire_dtype, wire_dtype)
    base = f"ps{num_ps}-{'overlapped' if pipelined else 'serial'}-{codec}"
    return base if prefetch else f"{base}-nopf"


def bench_ps_matrix(batch_size=16384, steps=6, warmup=4, repeats=3,
                    shard_counts=DEFAULT_SHARD_COUNTS,
                    codecs=DEFAULT_CODECS,
                    pipelining=DEFAULT_PIPELINING,
                    prefetch_controls=None,
                    clock=None, seed=0):
    """The full matrix (prefetch overlap ON everywhere), plus
    ``prefetch_controls`` cells — (shards, pipelined, codec) configs
    re-run with the prefetch-overlap plane off ("-nopf" suffix), so the
    lookahead+cache win is a measured ratio, not an assumption. The
    default control mirrors the strongest main-axis config. Budget-aware
    at two grains: a cell that no longer fits is skipped (recorded as
    {"skipped": "budget"}), and a cell mid-repeats stops early with the
    samples it has (marked truncated). The cells that did run always
    report."""
    if prefetch_controls is None:
        prefetch_controls = (
            (max(shard_counts), True in pipelining, codecs[-1]),
        )
    batches = make_batches(batch_size, seed=seed)
    cells = {}
    cell_cost_s = None
    configs = [
        (num_ps, pipelined, wire_dtype, True)
        for num_ps in shard_counts
        for pipelined in pipelining
        for wire_dtype in codecs
    ] + [
        (num_ps, pipelined, wire_dtype, False)
        for num_ps, pipelined, wire_dtype in prefetch_controls
    ]
    for num_ps, pipelined, wire_dtype, prefetch in configs:
        name = cell_name(num_ps, pipelined, wire_dtype, prefetch)
        if clock is not None and (
            clock.expired
            or (cell_cost_s and not clock.fits(cell_cost_s))
        ):
            cells[name] = {"skipped": "budget"}
            continue
        cell_start = time.perf_counter()
        with flightrec.phase(f"ps_matrix:{name}"):
            cells[name] = _run_cell(
                batches, steps, warmup, num_ps, pipelined,
                wire_dtype, repeats, clock, prefetch,
            )
        # One completed cell calibrates the skip estimate for
        # the rest (cells are roughly the same size).
        cell_cost_s = time.perf_counter() - cell_start
    return {
        "axes": {
            "shards": list(shard_counts),
            "pipelining": [
                "overlapped" if p else "serial" for p in pipelining
            ],
            "codec": list(codecs),
            "prefetch_controls": [
                cell_name(n, p, c, False)
                for n, p, c in prefetch_controls
            ],
        },
        "batch_size": batch_size,
        "steps_per_run": steps,
        "repeats": repeats,
        "cells": cells,
    }


def _run_cell(batches, steps, warmup, num_ps, pipelined, wire_dtype,
              repeats, clock, prefetch=True):
    runs = []
    truncated = False
    for i in range(repeats):
        if i > 0 and clock is not None and clock.expired:
            truncated = True
            break
        runs.append(
            run_ps_config(
                batches, steps, warmup, num_ps, pipelined, wire_dtype,
                prefetch,
            )
        )
    samples = [r["examples_per_sec"] for r in runs]
    summary = stats.summarize(samples)
    # The reported phase breakdown is the run closest to the median so
    # phases and headline describe the same execution.
    rep, _ = stats.representative_run(runs)
    out = {
        "examples_per_sec": summary["median"],
        "samples": [round(s, 1) for s in samples],
        "step_time_ms": rep["step_time_ms"],
        "phase_mean_ms": rep["phase_mean_ms"],
        "push_breakdown_ms": rep["push_breakdown_ms"],
    }
    if "ci95" in summary:
        out["examples_per_sec_ci95"] = [
            round(summary["ci95"][0], 1),
            round(summary["ci95"][1], 1),
        ]
    if "spread" in summary:
        out["run_spread"] = round(summary["spread"], 3)
    if truncated:
        out["truncated"] = True
    return out
