"""Performance evidence plane: the benchmark subsystem.

What used to be a 575-line ``bench.py`` script is a package whose job is
to make every performance claim in this repo *evidence*: measured in
repeated timed windows, reported with bootstrap confidence intervals,
attributed to phases (down to the serialize/wire/apply split inside
``push_gradients``), bounded by a wall-clock budget that degrades step
counts instead of dying, and gated against the last checked-in
``BENCH_*.json`` so a ±2% drift is labeled "noise" vs "regression"
instead of eyeballed.

Layout (import cost matters — ``stats``, ``budget`` and ``gate`` are
stdlib-only and never import jax, so the regression gate and the stats
tests run in milliseconds):

- ``stats``     bootstrap CIs, significance verdicts, BENCH_*.json
                parsing/comparison. Pure stdlib.
- ``budget``    BudgetClock + the per-benchmark watchdog (the BENCH_r05
                rc=124 fix, now budget-aware). Pure stdlib.
- ``gate``      the regression gate CLI (``make bench-gate``). Stdlib.
- ``workloads`` the model benchmarks (ResNet50 / MobileNetV2 / DeepFM
                dense + PS-mode). Imports jax — only loaded by the
                runner.
- ``matrix``    the PS-mode microbench matrix: wire codec x push
                pipelining x PS shard count, each cell with a
                serialize/wire/apply breakdown. Imports jax.
- ``runner``    orchestrates a full or smoke run, always emits the one
                JSON result line (even when truncated), attaches the
                verdict vs the latest baseline, and keeps a flight
                recorder armed so a killed run leaves evidence.

CLI: ``python -m elasticdl_tpu.bench [--smoke] [--budget-s N] ...``;
the repo-root ``bench.py`` is a thin shim onto it (the driver invokes
``python bench.py``).
"""
