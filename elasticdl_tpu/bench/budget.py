"""Wall-clock budgeting for benchmark runs. Pure stdlib.

Two layers of defense against the failure mode that produced BENCH_r05
(rc=124: the whole run killed by an outer ``timeout``, zero evidence
left behind):

- ``BudgetClock``: a soft, cooperative budget. Workloads check
  ``remaining()`` between timed windows and stop early — degrading the
  sample count instead of dying — and the runner checks it between
  benchmarks, skipping what no longer fits (each skip is recorded, so
  truncation is visible in the JSON, never silent).
- ``run_with_watchdog``: the hard per-benchmark bound (inherited from
  the PR1 fix). The benchmark runs on a daemon thread; on timeout the
  thread is abandoned — it can't be killed, but the run moves on, the
  JSON line still gets emitted, and ``on_timeout`` (the flight-recorder
  dump) fires so the wedged phase is named.
"""

import threading
import time


class BudgetClock:
    """Counts down one shared wall-clock budget. ``total_s=0`` disables
    the budget (remaining() is +inf, expired is never True)."""

    def __init__(self, total_s=0.0):
        self.total_s = float(total_s or 0.0)
        self._start = time.perf_counter()

    def elapsed(self):
        return time.perf_counter() - self._start

    def remaining(self):
        if self.total_s <= 0:
            return float("inf")
        return self.total_s - self.elapsed()

    @property
    def expired(self):
        return self.remaining() <= 0

    def fits(self, estimate_s):
        """Whether ``estimate_s`` more seconds of work fit the budget."""
        return self.remaining() >= estimate_s


def run_with_watchdog(name, fn, timeout_s, on_timeout=None):
    """Run one benchmark with a hard wall-clock bound.

    Returns fn()'s result, or {"error": ...} on exception, or
    {"error": "...timeout", "timed_out": True} on timeout (after calling
    ``on_timeout(name)``, best-effort). A wedged config must surface in
    its own result slot, not eat the whole run's budget as an rc=124.
    """
    if not timeout_s:
        try:
            return fn()
        except Exception as e:
            return {"error": str(e)[:200]}

    box = {}

    def target():
        try:
            box["result"] = fn()
        except Exception as e:
            box["error"] = str(e)[:200]

    thread = threading.Thread(
        target=target, name=f"bench-{name}", daemon=True
    )
    thread.start()
    thread.join(timeout_s)
    if thread.is_alive():
        if on_timeout is not None:
            try:
                on_timeout(name)
            except Exception:
                pass
        return {
            "error": f"watchdog timeout after {timeout_s:g}s",
            "timed_out": True,
        }
    if "error" in box:
        return {"error": box["error"]}
    return box.get("result")
