"""Bench statistics: bootstrap CIs, significance verdicts, baseline IO.

Pure stdlib (no jax, no numpy) so the regression gate and the stats
tests run in milliseconds, and so a broken accelerator stack can never
take the *evidence* machinery down with it.

The estimator of record is the MEDIAN: every timed window shares one
host with the PS shards and the codec threads, so the sample
distribution is right-skewed by load spikes and the median is the
robust center (the same reasoning as bench.py's old median-of-n
reporting, now with an interval around it).

Verdicts compare two sample sets with a bootstrap CI on the *relative*
difference of medians: "regression"/"improvement" only when the CI
excludes zero AND the median effect clears ``min_effect`` (so a
statistically-real-but-tiny drift is still "noise"), "insufficient"
when either side has too few samples to resample meaningfully.
All resampling is seeded — the same inputs always produce the same
verdict.
"""

import glob
import json
import math
import os
import random
import re
import statistics

# Below this many samples a bootstrap over windows is theater: 2 samples
# have 2^2=4 distinct resamples. Point estimates are still reported.
MIN_SAMPLES_FOR_CI = 3

DEFAULT_BOOTSTRAP_N = 2000
DEFAULT_ALPHA = 0.05
# Relative effect below which a statistically significant difference is
# still reported as noise: the r02->r04 ResNet numbers drift ~±2% run to
# run on identical code, so a gate tighter than that would cry wolf.
DEFAULT_MIN_EFFECT = 0.02

VERDICT_REGRESSION = "regression"
VERDICT_IMPROVEMENT = "improvement"
VERDICT_NOISE = "noise"
VERDICT_INSUFFICIENT = "insufficient-data"
VERDICT_INCOMPARABLE = "incomparable"
# Overall-only verdict: isolated per-metric regression flags inside a
# WIDE metric family, demoted by the multiple-comparisons rule in
# compare_records (the flags are preserved per-metric and listed under
# "suspect" — visible, re-measurable, but not a gate failure).
VERDICT_SUSPECT = "suspect"

# Multiple-comparisons control for the overall verdict. The per-metric
# test bootstraps WITHIN-run samples only, so it cannot see between-run
# variance (host day-drift, scheduler luck on a 1-core box): measured
# same-code A/B on this host shows individual 3-repeat PS cells swinging
# +-9% run to run, which at min_effect=2% makes each of the ~19 compared
# metrics a ~5-10% false-positive lottery ticket — a SAME-CODE rerun of
# r07 flags 1-2 random cells nearly every time. Real code regressions
# are coherent instead: the cells share one transport/trainer path, so a
# genuine slowdown moves many of them at once (the r06->r07 improvement
# moved 13/13 shared metrics; a contaminated run moved 5). Hence: when a
# comparison spans at least WIDE_FAMILY_MIN metrics, fewer than
# COHERENT_REGRESSIONS flags demote to "suspect"; narrow comparisons
# (a handful of headline metrics, each its own claim) keep strict
# worst-across-metrics semantics.
WIDE_FAMILY_MIN = 8
COHERENT_REGRESSIONS = 3
# Magnitude escape hatch: the demotion exists for the measured ±9%
# between-run cell lottery, so a flag FAR outside that band (a genuine
# subsystem collapse confined to one or two cells — e.g. a workload
# only one cell measures) is never demoted, however isolated.
SEVERE_REGRESSION_EFFECT = 0.25


def bootstrap_ci(samples, n_boot=DEFAULT_BOOTSTRAP_N, alpha=DEFAULT_ALPHA,
                 seed=0, stat=statistics.median):
    """Percentile-bootstrap CI for ``stat`` over ``samples``.

    Returns (lo, hi), or None when the sample count is below
    MIN_SAMPLES_FOR_CI (an interval from 2 points would look like
    evidence without being any).
    """
    samples = [float(s) for s in samples]
    if len(samples) < MIN_SAMPLES_FOR_CI:
        return None
    rng = random.Random(seed)
    n = len(samples)
    stats_ = sorted(
        stat([samples[rng.randrange(n)] for _ in range(n)])
        for _ in range(n_boot)
    )
    lo = stats_[int(math.floor((alpha / 2) * (n_boot - 1)))]
    hi = stats_[int(math.ceil((1 - alpha / 2) * (n_boot - 1)))]
    return lo, hi


def summarize(samples, seed=0):
    """{"median", "mean", "n", "ci95" | None, "spread"} for a sample set.

    ``spread`` is max/min (the old bench spread gate's statistic);
    ``ci95`` is the bootstrap interval around the median.
    """
    samples = [float(s) for s in samples]
    if not samples:
        return {"n": 0}
    out = {
        "median": statistics.median(samples),
        "mean": statistics.fmean(samples),
        "n": len(samples),
        "spread": max(samples) / max(min(samples), 1e-9),
    }
    ci = bootstrap_ci(samples, seed=seed)
    if ci is not None:
        out["ci95"] = [ci[0], ci[1]]
    return out


def representative_run(runs, key="examples_per_sec"):
    """(run closest to the median of ``key``, the median). The headline
    of a repeated benchmark is the MEDIAN (never the max — a collapsed
    outlier run must drag the spread flag, not vanish), and the phase
    breakdown reported next to it must come from the run nearest that
    median so phases and headline describe the same execution."""
    values = [float(r[key]) for r in runs]
    med = statistics.median(values)
    rep = min(runs, key=lambda r: abs(float(r[key]) - med))
    return rep, med


def significance_verdict(baseline_samples, candidate_samples,
                         min_effect=DEFAULT_MIN_EFFECT,
                         n_boot=DEFAULT_BOOTSTRAP_N, alpha=DEFAULT_ALPHA,
                         seed=0):
    """Compare candidate vs baseline samples of a higher-is-better metric.

    Returns {"verdict", "effect", "effect_ci" | None, "n_base", "n_cand"}.
    ``effect`` is the relative difference of medians
    (cand - base) / base; negative means the candidate is slower.

    The verdict is "regression"/"improvement" only when BOTH hold:
    the bootstrap CI of the effect excludes zero (statistically real)
    and |median effect| >= min_effect (practically real). With too few
    samples on either side to bootstrap, the verdict is
    "insufficient-data" — the point effect is still reported so a
    truncated run leaves a number, just not a claim.
    """
    base = [float(s) for s in baseline_samples]
    cand = [float(s) for s in candidate_samples]
    out = {"n_base": len(base), "n_cand": len(cand)}
    if not base or not cand:
        out["verdict"] = VERDICT_INSUFFICIENT
        return out
    base_med = statistics.median(base)
    cand_med = statistics.median(cand)
    if base_med <= 0:
        out["verdict"] = VERDICT_INSUFFICIENT
        return out
    effect = (cand_med - base_med) / base_med
    out["effect"] = effect
    if (len(base) < MIN_SAMPLES_FOR_CI
            or len(cand) < MIN_SAMPLES_FOR_CI):
        out["verdict"] = VERDICT_INSUFFICIENT
        return out
    rng = random.Random(seed)
    nb, nc = len(base), len(cand)
    effects = sorted(
        (
            statistics.median(
                [cand[rng.randrange(nc)] for _ in range(nc)]
            )
            - (
                bm := statistics.median(
                    [base[rng.randrange(nb)] for _ in range(nb)]
                )
            )
        )
        / max(bm, 1e-12)
        for _ in range(n_boot)
    )
    lo = effects[int(math.floor((alpha / 2) * (n_boot - 1)))]
    hi = effects[int(math.ceil((1 - alpha / 2) * (n_boot - 1)))]
    out["effect_ci"] = [lo, hi]
    significant = lo > 0 or hi < 0
    if significant and effect <= -min_effect:
        out["verdict"] = VERDICT_REGRESSION
    elif significant and effect >= min_effect:
        out["verdict"] = VERDICT_IMPROVEMENT
    else:
        out["verdict"] = VERDICT_NOISE
    return out


# ---------------------------------------------------------------------------
# BENCH_*.json parsing. Two shapes exist on disk:
#  - the driver wrapper {"n": .., "cmd": .., "rc": .., "tail": "...log..."}
#    whose tail *contains* the bench JSON line somewhere (r05's tail does
#    not — it timed out before emitting; that file parses to None);
#  - a raw bench result line {"metric", "value", "unit", "details", ...}
#    (what the runner itself writes).
# ---------------------------------------------------------------------------


def extract_bench_record(obj):
    """The bench result dict from either on-disk shape, or None."""
    if not isinstance(obj, dict):
        return None
    if "metric" in obj and "details" in obj:
        return obj
    tail = obj.get("tail")
    if not isinstance(tail, str):
        return None
    # Last parseable JSON object line wins (logs precede the result).
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not (line.startswith("{") and '"metric"' in line):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "details" in rec:
            return rec
    # The driver may have truncated the tail mid-line; try from the last
    # '{"metric"' to the end.
    m = tail.rfind('{"metric"')
    if m >= 0:
        try:
            rec = json.loads(tail[m:])
            if isinstance(rec, dict) and "details" in rec:
                return rec
        except ValueError:
            pass
    return None


def load_bench_file(path):
    """Parse one BENCH_*.json from disk -> bench record dict or None."""
    try:
        with open(path) as f:
            return extract_bench_record(json.load(f))
    except (OSError, ValueError):
        return None


def find_baselines(root, exclude=None):
    """BENCH_r*.json files under ``root`` that parse to a usable record,
    newest round first. ``exclude`` drops one path (the candidate)."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        if exclude and os.path.abspath(path) == os.path.abspath(exclude):
            continue
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        rec = load_bench_file(path)
        if rec is not None:
            out.append((int(m.group(1)), path, rec))
    out.sort(reverse=True)
    return [(path, rec) for _, path, rec in out]


def _walk_metrics(details, prefix, out):
    for key, value in details.items():
        name = f"{prefix}{key}" if not prefix else f"{prefix}.{key}"
        if isinstance(value, dict):
            _walk_metrics(value, name, out)
        elif key in ("examples_per_sec", "samples") and isinstance(
            value, (int, float, list)
        ):
            out[name] = value


def comparable_metrics(record):
    """Flatten a bench record into {metric_path: samples_list}.

    Every ``examples_per_sec`` found anywhere in ``details`` becomes a
    comparable metric; its samples are (in preference order) the sibling
    ``samples`` list, the legacy ``runs_examples_per_sec`` list, or the
    point value as a 1-sample list. Higher is better for all of them.
    """
    details = record.get("details") or {}
    flat = {}
    _walk_metrics(details, "", flat)
    out = {}
    for name, value in flat.items():
        if not name.endswith(".examples_per_sec") and name != (
            "examples_per_sec"
        ):
            continue
        base = name[: -len("examples_per_sec")]
        parent = _dig(details, base.rstrip(".").split(".")) if base else (
            details
        )
        samples = None
        if isinstance(parent, dict):
            samples = parent.get("samples") or parent.get(
                "runs_examples_per_sec"
            )
        if not isinstance(samples, list) or not samples:
            samples = [value] if isinstance(value, (int, float)) else None
        if samples:
            out[base.rstrip(".") or "headline"] = [
                float(s) for s in samples
            ]
    return out


def _dig(d, path):
    for p in path:
        if not isinstance(d, dict):
            return None
        d = d.get(p)
    return d


def device_kind(record):
    details = record.get("details") or {}
    return details.get("device_kind") or ""


def select_baseline(pairs, candidate_device):
    """Pick the baseline to compare a candidate against: the NEWEST
    round with a MATCHING device_kind, falling back to the newest
    overall (which yields an honest "incomparable"). Without the device
    preference, one checked-in CPU round would make every later TPU run
    compare against it, auto-pass as incomparable, and silently disable
    regression detection until someone commits a same-device round."""
    if candidate_device:
        for path, rec in pairs:
            if device_kind(rec) == candidate_device:
                return path, rec
    return pairs[0] if pairs else (None, None)


def compare_records(baseline, candidate, min_effect=DEFAULT_MIN_EFFECT,
                    seed=0):
    """Per-metric verdicts of candidate vs baseline bench records.

    Returns {"overall": verdict, "device": {...}, "metrics": {name:
    verdict-dict}}. When the two records ran on different device kinds
    every throughput comparison is apples-to-oranges: the overall
    verdict is "incomparable" and no per-metric claim is made.
    """
    base_kind, cand_kind = device_kind(baseline), device_kind(candidate)
    out = {
        "device": {"baseline": base_kind, "candidate": cand_kind},
        "metrics": {},
    }
    if base_kind != cand_kind:
        out["overall"] = VERDICT_INCOMPARABLE
        return out
    base_metrics = comparable_metrics(baseline)
    cand_metrics = comparable_metrics(candidate)
    worst = VERDICT_INSUFFICIENT
    rank = {
        VERDICT_INSUFFICIENT: 0,
        VERDICT_IMPROVEMENT: 1,
        VERDICT_NOISE: 2,
        VERDICT_REGRESSION: 3,
    }
    for name in sorted(set(base_metrics) & set(cand_metrics)):
        verdict = significance_verdict(
            base_metrics[name], cand_metrics[name],
            min_effect=min_effect, seed=seed,
        )
        out["metrics"][name] = verdict
        if rank[verdict["verdict"]] > rank[worst]:
            worst = verdict["verdict"]
    if not out["metrics"]:
        out["overall"] = VERDICT_INSUFFICIENT
        return out
    regressed = sorted(
        name
        for name, v in out["metrics"].items()
        if v["verdict"] == VERDICT_REGRESSION
    )
    severe = any(
        abs(out["metrics"][m].get("effect", 0.0))
        >= SEVERE_REGRESSION_EFFECT
        for m in regressed
    )
    if (
        worst == VERDICT_REGRESSION
        and not severe
        and len(out["metrics"]) >= WIDE_FAMILY_MIN
        and len(regressed) < COHERENT_REGRESSIONS
    ):
        # Isolated flags in a wide family: statistically indistinguishable
        # from the per-metric test's between-run false-positive rate (see
        # the constants above). Kept visible for follow-up, not a failure.
        out["overall"] = VERDICT_SUSPECT
        out["suspect"] = regressed
    else:
        out["overall"] = worst
    return out
