"""In-process elastic-regroup microbench: cold vs warm (speculative AOT).

`python -m elasticdl_tpu.bench.regroup` — run by the rejoin benchmark
in a SUBPROCESS with a virtual 8-device CPU platform, so the main bench
process's backend (and its single-device view) is untouched.

What it measures (the tentpole claim of the recompile-free-elasticity
work): the wall time for a LIVE trainer to absorb a world change and
complete its first step in the new world —

  regroup_cold_s   the world reshapes (8 -> 7 devices) with speculation
                   off and a cold compilation cache: the regroup pays a
                   full re-lower + XLA compile, the pre-PR price of
                   every elastic epoch;
  regroup_warm_s   the world reshapes back (7 -> 8) after the
                   speculator prebuilt that world's step in the
                   background: the regroup installs the executable and
                   steps immediately.

The membership epoch is driven through a real in-process master
(membership service), and the device-count change stands in for the
process-count change of a production multi-host regroup — the world
spec resolution is identical (parallel/mesh.py), only the topology
source differs. Same-spec epoch bumps (the single-host common case) are
not measured here because they cost ~nothing by construction — the
worker-kill drill asserts that path's counters instead.
"""

import json
import os
import sys
import time


def _ensure_test_paths():
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    for sub in ("tests", "tools"):
        p = os.path.join(repo, sub)
        if p not in sys.path:
            sys.path.insert(0, p)
    if repo not in sys.path:
        sys.path.insert(0, repo)


def run_regroup_bench(batch=16):
    _ensure_test_paths()
    # Speculation off for the cold cell; flipped on (live knob read) for
    # the warm cell below.
    os.environ["ELASTICDL_AOT_SPECULATE"] = "0"
    import jax
    import numpy as np

    from test_utils import start_master

    from elasticdl_tpu.models.transformer import transformer_lm as tlm
    from elasticdl_tpu.parallel.mesh import WorldTopology
    from elasticdl_tpu.worker.allreduce_trainer import AllReduceTrainer
    from elasticdl_tpu.worker.master_client import MasterClient

    n_dev = len(jax.devices())
    # A small-but-real transformer, not the linear toy: the cold cell
    # must contain a representative re-lower + XLA compile, which for a
    # few-layer attention stack is O(seconds) on a CPU host — the same
    # order the compile tracker measured for elastic regroups in r06.
    cfg = tlm.LMConfig(
        vocab=256, d_model=64, n_heads=4, n_layers=2, max_len=64,
        activation_dtype="float32",
    )
    tokens = (
        np.arange(batch * (cfg.max_len + 1)).reshape(
            batch, cfg.max_len + 1
        )
        * 7
    ) % cfg.vocab
    x, y = tokens[:, :-1], tokens[:, 1:]

    out = {"n_devices": n_dev, "batch": batch}
    fake_host = 2

    def bump_membership(m):
        nonlocal fake_host
        m["membership"].add_worker_host(f"10.0.0.{fake_host}:9999")
        fake_host += 1

    with start_master(
        training_shards={"f": (0, 100)}, with_membership=True
    ) as m:
        mc = MasterClient(
            m["addr"], worker_id=0, worker_host="127.0.0.1"
        )
        t = AllReduceTrainer(
            tlm.custom_model(cfg),
            tlm.loss,
            tlm.optimizer(),
            mc,
            steps_per_world_check=1,
        )
        try:
            # Settle in the full-device world (first compile excluded —
            # it is cold-start, not regroup).
            for _ in range(2):
                jax.block_until_ready(t.train_minibatch(x, y)[2])

            # COLD: the world reshapes to n-1 devices; the regroup
            # re-lowers and XLA-compiles synchronously.
            t._topo_override = WorldTopology(n_dev - 1, n_dev - 1, 1)
            bump_membership(m)
            t0 = time.perf_counter()
            jax.block_until_ready(t.train_minibatch(x, y)[2])
            out["regroup_cold_s"] = round(time.perf_counter() - t0, 4)

            # WARM: speculate the full-device world from inside the
            # shrunk one, then regroup back into the guess.
            os.environ["ELASTICDL_AOT_SPECULATE"] = "1"
            t._topo_candidates = [WorldTopology(n_dev, n_dev, 1)]
            jax.block_until_ready(t.train_minibatch(x, y)[2])
            if not t._speculator.drain(120):
                out["error"] = "speculator never drained"
                return out
            t._topo_override = WorldTopology(n_dev, n_dev, 1)
            bump_membership(m)
            t0 = time.perf_counter()
            jax.block_until_ready(t.train_minibatch(x, y)[2])
            out["regroup_warm_s"] = round(time.perf_counter() - t0, 4)
            out["speculative_consumed"] = t._speculator.stats[
                "consumed"
            ]
        finally:
            t.close()
            mc.close()
    return out


def main():
    try:
        result = run_regroup_bench()
    except Exception as e:  # the parent bench records the error cell
        result = {"error": str(e)[:300]}
    print("REGROUP_RESULT " + json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
