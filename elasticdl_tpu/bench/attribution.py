"""Per-workload step-time attribution for bench runs (jax-free).

Answers "where did this step go" with one row per measured workload (or
per cell, for cell-bearing workloads like the PS matrix): the fraction
of step time spent in

    compute     the jitted device step (train_step / windowed loop)
    serialize   gradient dedup + proto build before the push RPC
    ps_wire     waiting on the PS over the wire (push wait net of the
                shard-reported apply, plus apply itself — the far side
                of the push — and the dense pull)
    input_wait  embedding prefetch / data feed ahead of the step
    recompile   tracked lowerings that fired during the workload's
                wall-clock window (the compile tracker's delta)
    other       the un-attributed remainder (host glue, GC, ...)

Fractions are measured against each row's step time and OVERLAP-
NORMALIZED: pipelined configs run the push concurrently with the next
step's pull/compute, so raw phase means can sum past the step — when
they do, every fraction is scaled by 1/sum so the row reads as shares
of the step and sums to <= 1.0 by construction. Rows whose phases were
measured serially keep their true remainder in `other`.

The runner feeds `build_all` with each workload's result dict, its
wall-clock seconds, and the compile-seconds delta the tracker observed
around it; `render_table` prints the human table `make bench-smoke`
ships to stderr (stdout stays the single JSON result line).
"""

# Result-dict phase keys -> attribution buckets. phase_mean_ms comes
# from the trainer Timing (matrix.run_ps_config); push_breakdown_ms is
# the serialize/wire/apply split inside push_gradients.
_PHASE_BUCKETS = {
    "train_step": "compute",
    "train_step_dispatch": "compute",
    "pull_model": "ps_wire",
    # With prefetch overlap, "prefetch_embeddings" is only the harvest
    # wait (the pulls were issued a step ahead); "prefetch_issue" is the
    # host-side dedup + cache lookup + RPC fire that stays on the
    # critical path.
    "prefetch_embeddings": "input_wait",
    "prefetch_issue": "input_wait",
    # Data-plane stages (observability/datapath.py): the same feed path
    # decomposed — task-lease wait, record read, decode/parse, row
    # collate, host-to-device copy, and empty-queue starvation.
    "input_task": "input_wait",
    "input_read": "input_wait",
    "input_decode": "input_wait",
    "input_collate": "input_wait",
    "input_h2d": "input_wait",
    "input_starve": "input_wait",
}
_BREAKDOWN_BUCKETS = {
    "serialize": "serialize",
    "wire": "ps_wire",
    "apply": "ps_wire",
}

# input_wait sub-attribution: phase -> sub-key. `input_collate` folds
# into input_decode (both are host-side batch-build work); the legacy
# embedding-prefetch phases keep contributing so PS-mode rows split even
# where only the trainer-side phases exist — the issue path is host-side
# id crunching (decode-shaped), the harvest is the device-copy wait
# (h2d-shaped).
_INPUT_SUB = {
    "input_task": "input_task",
    "input_read": "input_read",
    "input_decode": "input_decode",
    "input_collate": "input_decode",
    "input_h2d": "input_h2d",
    "input_starve": "input_starve",
    "prefetch_issue": "input_decode",
    "prefetch_embeddings": "input_h2d",
}

FRACTION_KEYS = (
    "compute", "ps_wire", "serialize", "input_wait", "recompile", "other"
)

# Rendered/tested order of the input_wait sub-fractions.
INPUT_SUBKEYS = (
    "input_task", "input_read", "input_decode", "input_h2d",
    "input_starve",
)


def _normalize(fractions):
    """Clamp negatives, overlap-normalize past 1.0, derive `other`.
    The sum<=1.0 invariant holds on the ROUNDED values too (rounding
    each share up by half an ulp must not break what normalization just
    established): any rounding excess is shaved off the largest share."""
    out = {k: max(0.0, v) for k, v in fractions.items() if v}
    total = sum(out.values())
    if total > 1.0:
        out = {k: v / total for k, v in out.items()}
        out["overlapped"] = True
        total = 1.0
    out["other"] = max(0.0, round(1.0 - total, 4))
    out = {
        k: (round(v, 4) if isinstance(v, float) else v)
        for k, v in out.items()
    }
    numeric = [k for k, v in out.items() if isinstance(v, float)]
    excess = round(sum(out[k] for k in numeric) - 1.0, 4)
    if excess > 0:
        biggest = max(numeric, key=lambda k: out[k])
        out[biggest] = round(out[biggest] - excess, 4)
    return out


def _split_input(target, subs):
    """Scale the raw per-sub fractions so they sum EXACTLY to the row's
    normalized input_wait share (the sub-split must agree with the
    undecomposed bucket it refines): proportional rescale, round to the
    table's precision, shave the rounding residue off the largest sub."""
    raw_total = sum(subs.values())
    if raw_total <= 0:
        return {}
    scale = target / raw_total
    out = {k: round(v * scale, 4) for k, v in subs.items()}
    residue = round(target - sum(out.values()), 4)
    if residue:
        biggest = max(out, key=lambda k: out[k])
        out[biggest] = max(0.0, round(out[biggest] + residue, 4))
    return out


def from_phases(step_time_ms, phase_mean_ms, push_breakdown_ms=None,
                recompile_fraction=0.0):
    """Attribution for one PS-mode cell from its per-step phase means."""
    if not step_time_ms:
        return None
    fractions = {"recompile": recompile_fraction}
    input_subs = {}
    for phase, bucket in _PHASE_BUCKETS.items():
        ms = (phase_mean_ms or {}).get(phase)
        if ms:
            frac = ms / step_time_ms
            fractions[bucket] = fractions.get(bucket, 0.0) + frac
            sub = _INPUT_SUB.get(phase)
            if sub:
                input_subs[sub] = input_subs.get(sub, 0.0) + frac
    breakdown = push_breakdown_ms or {}
    for part, bucket in _BREAKDOWN_BUCKETS.items():
        ms = breakdown.get(part)
        if ms:
            fractions[bucket] = fractions.get(bucket, 0.0) + (
                ms / step_time_ms
            )
    # push_gradients minus its breakdown is serialize-path glue
    # (device_get, partitioning); fold the un-split remainder into
    # serialize so serial cells don't under-report the push.
    push_ms = (phase_mean_ms or {}).get("push_gradients")
    if push_ms:
        split = sum(breakdown.values())
        if push_ms > split:
            fractions["serialize"] = fractions.get(
                "serialize", 0.0
            ) + (push_ms - split) / step_time_ms
    out = _normalize(fractions)
    if input_subs and out.get("input_wait"):
        breakdown = _split_input(out["input_wait"], input_subs)
        if breakdown:
            out["input_breakdown"] = breakdown
    return out


def from_windows(result, wall_s, compile_s):
    """Attribution for a windowed jitted-loop bench: the timed windows
    are pure device compute; everything else in the wall is compile +
    harness."""
    step_ms = result.get("step_time_ms")
    windows = result.get("windows")
    steps = result.get("steps_per_window")
    if not (step_ms and windows and steps and wall_s):
        return None
    measured_s = step_ms / 1e3 * windows * steps
    return _normalize(
        {
            "compute": measured_s / wall_s,
            "recompile": min(1.0, compile_s / wall_s),
        }
    )


def build(result, wall_s, compile_s):
    """{row_label: fractions} for one workload result (possibly cell-
    bearing). Empty dict when the result carries nothing attributable
    (errors, skips, drills)."""
    out = {}
    if not isinstance(result, dict) or "error" in result:
        return out
    recompile_fraction = (
        min(1.0, compile_s / wall_s) if wall_s else 0.0
    )
    if "phase_mean_ms" in result:
        row = from_phases(
            result.get("step_time_ms"),
            result.get("phase_mean_ms"),
            result.get("push_breakdown_ms"),
            recompile_fraction,
        )
        if row:
            out[""] = row
        return out
    if "windows" in result:
        row = from_windows(result, wall_s, compile_s)
        if row:
            out[""] = row
        return out
    # Cell-bearing results: bench_deepfm_ps keys its configs at the top
    # level, the PS matrix nests them under "cells". Cell rows get NO
    # share of the workload-level compile seconds: each cell's timed
    # window opens after its own warmup (compiles land outside it), and
    # folding one wall-clock fraction into every cell would count the
    # same compile N times against step-time denominators it never ran
    # in.
    cell_host = result.get("cells") if isinstance(
        result.get("cells"), dict
    ) else result
    for cell, sub in cell_host.items():
        if not isinstance(sub, dict) or "phase_mean_ms" not in sub:
            continue
        row = from_phases(
            sub.get("step_time_ms"),
            sub.get("phase_mean_ms"),
            sub.get("push_breakdown_ms"),
        )
        if row:
            out[cell] = row
    return out


def build_all(measured):
    """measured: {workload: (result, wall_s, compile_s)} ->
    {workload[/cell]: fractions} for every attributable row."""
    table = {}
    for name, (result, wall_s, compile_s) in measured.items():
        for cell, row in build(result, wall_s, compile_s).items():
            table[f"{name}/{cell}" if cell else name] = row
    return table


def render_table(table):
    """Fixed-width human table (stderr companion of the JSON line)."""
    if not table:
        return "attribution: no attributable workloads"
    width = max(len(k) for k in table)
    head = "  ".join(f"{k:>10}" for k in FRACTION_KEYS)
    lines = [
        "step-time attribution (fractions of step time; "
        "rows sum to <= 1.0):",
        f"{'workload':<{width}}  {head}",
    ]
    for name in sorted(table):
        row = table[name]
        cells = "  ".join(
            f"{row.get(k, 0.0):>10.3f}" for k in FRACTION_KEYS
        )
        mark = " *" if row.get("overlapped") else ""
        lines.append(f"{name:<{width}}  {cells}{mark}")
    if any(r.get("overlapped") for r in table.values()):
        lines.append(
            "(* overlap-normalized: pipelined phases measured "
            "concurrently)"
        )
    split_rows = {
        name: row["input_breakdown"]
        for name, row in table.items()
        if row.get("input_breakdown")
    }
    if split_rows:
        sub_head = "  ".join(f"{k:>12}" for k in INPUT_SUBKEYS)
        lines.append("")
        lines.append(
            "input_wait breakdown (sub-fractions of step time; each "
            "row sums to its input_wait above):"
        )
        lines.append(f"{'workload':<{width}}  {sub_head}")
        for name in sorted(split_rows):
            sub = split_rows[name]
            cells = "  ".join(
                f"{sub.get(k, 0.0):>12.3f}" for k in INPUT_SUBKEYS
            )
            lines.append(f"{name:<{width}}  {cells}")
    return "\n".join(lines)


def main(argv=None):
    """Render the attribution table archived inside a bench result file
    (the `--out` JSON): `make bench-smoke` ships the text under
    artifacts/ as the CI-artifact form of the stderr table."""
    import argparse
    import json

    parser = argparse.ArgumentParser("bench.attribution")
    parser.add_argument("result", help="bench result JSON (--out file)")
    args = parser.parse_args(argv)
    with open(args.result) as f:
        data = json.load(f)
    table = (data.get("details") or {}).get("attribution") or {}
    print(render_table(table))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
