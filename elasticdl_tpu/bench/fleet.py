"""Fleet-scale control-plane bench: push-vs-pull A/B at N pods.

Each cell runs the simulated-fleet harness (real gRPC task protocol,
real aggregator, scripted churn) for a fixed window and measures what
the master's control plane costs at that scale:

- ``master_tick_ms``: per-poll_once wall time (summarized with CIs —
  the pull cells pay the scrape fan-out here, the push cells only the
  derive pass),
- ``dispatch_per_s``: get_task+report_task_result round-trips the
  dispatcher sustained while telemetry ran,
- ``freshness``: the fleet telemetry-age rollup the aggregator derived,
- ``summary_render_ms``: /api/summary over real HTTP at that roster
  size.

The A/B is same-run by construction: both modes of one size execute
back-to-back in this process, so host noise hits both sides alike. The
headline ``push_vs_pull`` block compares master-tick medians at the
largest size both modes completed.
"""

import time

from elasticdl_tpu.bench import stats
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)

DEFAULT_SIZES = (50, 200, 500)
# Churn scales with the fleet: ~2% of pods die (and relaunch), ~2%
# straggle, floors of 2 each so small cells still see both paths.
KILL_FRACTION = 0.02
STRAGGLER_FRACTION = 0.02


def _run_cell(n_pods, mode, seconds, seed):
    from elasticdl_tpu.fleet.harness import FleetHarness, churn_schedule

    # Scale the window with the roster: at 500 pods one pull sweep costs
    # seconds of master tick, so a fixed small window yields fewer tick
    # samples than MIN_SAMPLES_FOR_CI and the A/B loses its intervals.
    seconds = max(seconds, n_pods * 0.03)
    kills = max(2, int(n_pods * KILL_FRACTION))
    stragglers = max(2, int(n_pods * STRAGGLER_FRACTION))
    n_ps = max(1, n_pods // 10)
    schedule = churn_schedule(
        n_pods, kills=kills, stragglers=stragglers, seed=seed
    )
    harness = FleetHarness(
        n_workers=n_pods - n_ps,
        n_ps=n_ps,
        mode=mode,
        tick_interval=0.25,
        push_interval=1.0,
        aggregator_interval=0.5,
        schedule=schedule,
        seed=seed,
    )
    t0 = time.perf_counter()
    render_s = []
    try:
        harness.start()
        harness.run(seconds)
        # Render probes at the end, when the roster is fully populated.
        for _ in range(5):
            r0 = time.perf_counter()
            harness.fetch_summary_http()
            render_s.append(time.perf_counter() - r0)
        run_stats = harness.stats()
    finally:
        harness.stop()
    elapsed = time.perf_counter() - t0
    counts = run_stats["counts"]
    fleet = run_stats["fleet"]
    tick_ms = [s * 1000.0 for s in harness.master_tick_seconds]
    # Drop warmup ticks: the first polls land before the roster has
    # ramped (near-empty sweeps cost microseconds), which makes the
    # sample set bimodal and the bootstrap CI uselessly wide.
    if len(tick_ms) > 4:
        tick_ms = tick_ms[2:]
    cell = {
        "pods": n_pods,
        "mode": mode,
        "seconds": round(elapsed, 2),
        "dispatch_per_s": round(
            (counts["dispatched"] + counts["reported"]) / max(elapsed, 1e-9),
            1,
        ),
        "master_tick_ms": stats.summarize(tick_ms),
        "summary_render_ms": stats.summarize(
            [s * 1000.0 for s in render_s]
        ),
        "roles_reporting": fleet.get("roles_reporting"),
        "freshness_max_s": fleet.get("freshness_max_s"),
        "freshness_p99_s": fleet.get("freshness_p99_s"),
        "kills": counts["kills"],
        "relaunches": counts["relaunches"],
        "rpc_errors": counts["rpc_errors"],
    }
    if mode == "push":
        cell["pushes"] = counts["pushes"]
        cell["push_batches"] = counts["push_batches"]
        cell["need_full"] = counts["need_full"]
    return cell


def bench_fleet(sizes=DEFAULT_SIZES, seconds=6.0, seed=0, clock=None):
    """All cells; returns {"cells": {...}, "push_vs_pull": {...}}.

    A spent budget clock skips remaining cells (recorded, per the bench
    truncation-is-visible rule) — sizes run smallest first so the cheap
    cells survive a tight budget and the A/B block degrades to the
    largest size that finished both modes."""
    cells = {}
    completed_both = []
    for n in sizes:
        for mode in ("push", "pull"):
            key = f"n{n}_{mode}"
            if clock is not None and clock.expired:
                cells[key] = {"skipped": "budget"}
                continue
            logger.info("fleet bench cell %s starting", key)
            cells[key] = _run_cell(n, mode, seconds, seed)
        if all(
            "skipped" not in cells[f"n{n}_{m}"] for m in ("push", "pull")
        ):
            completed_both.append(n)
    out = {"cells": cells}
    if completed_both:
        n = max(completed_both)
        push = cells[f"n{n}_push"]["master_tick_ms"]
        pull = cells[f"n{n}_pull"]["master_tick_ms"]
        push_ci = push.get("ci95")
        pull_ci = pull.get("ci95")
        out["push_vs_pull"] = {
            "pods": n,
            "push_tick_ms_median": push.get("median"),
            "pull_tick_ms_median": pull.get("median"),
            "pull_over_push": (
                round(pull["median"] / push["median"], 2)
                if push.get("median")
                else None
            ),
            # Strongest claim the samples support: the CIs themselves
            # are disjoint, not just the medians ordered.
            "ci_separated": bool(
                push_ci and pull_ci and push_ci[1] < pull_ci[0]
            ),
        }
    return out
