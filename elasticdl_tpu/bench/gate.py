"""The regression gate: ``make bench-gate``. Pure stdlib.

Compares a candidate bench result against a baseline with
``stats.compare_records`` and exits nonzero ONLY on a statistically
significant practical regression (bootstrap CI of the relative
median-difference excludes zero AND the effect clears the min-effect
threshold). Noise, improvements, different-device runs
("incomparable"), and missing evidence all pass — the gate exists to
catch real slowdowns, not to punish running on different hardware or
having too few samples to make a claim.

Defaults: candidate = the newest parseable ``BENCH_r*.json`` in the
repo root, baseline = the next newest (r05-style timeout wrappers with
no JSON line in their tail parse to nothing and are skipped
automatically). Both can be pointed anywhere — the tests feed synthetic
pairs.
"""

import argparse
import json
import sys

from elasticdl_tpu.bench import stats
from elasticdl_tpu.common import knobs


def run_gate(baseline_path=None, candidate_path=None, min_effect=None,
             root=None, out=sys.stdout):
    """Returns the process exit code (0 pass, 1 regression, 2 usage)."""
    if root is None:
        from elasticdl_tpu.bench.runner import REPO_ROOT as root
    if min_effect is None:
        min_effect = knobs.get_float("ELASTICDL_BENCH_MIN_EFFECT")

    if candidate_path:
        candidate = stats.load_bench_file(candidate_path)
        if candidate is None:
            print(
                f"bench-gate: candidate {candidate_path} has no "
                "parseable bench record", file=out,
            )
            return 2
    else:
        pairs = stats.find_baselines(root)
        if not pairs:
            print(
                "bench-gate: PASS (no parseable BENCH_*.json to gate)",
                file=out,
            )
            return 0
        candidate_path, candidate = pairs[0]

    if baseline_path:
        baseline = stats.load_bench_file(baseline_path)
        if baseline is None:
            print(
                f"bench-gate: baseline {baseline_path} has no "
                "parseable bench record", file=out,
            )
            return 2
    else:
        pairs = stats.find_baselines(root, exclude=candidate_path)
        if not pairs:
            print(
                "bench-gate: PASS (no baseline to compare "
                f"{candidate_path} against)", file=out,
            )
            return 0
        baseline_path, baseline = stats.select_baseline(
            pairs, stats.device_kind(candidate)
        )

    verdict = stats.compare_records(
        baseline, candidate, min_effect=min_effect
    )
    overall = verdict["overall"]
    print(
        f"bench-gate: {candidate_path} vs {baseline_path} "
        f"(min effect {min_effect:.1%})", file=out,
    )
    for name, v in sorted(verdict["metrics"].items()):
        effect = v.get("effect")
        ci = v.get("effect_ci")
        line = f"  {name}: {v['verdict']}"
        if effect is not None:
            line += f" (effect {effect:+.1%}"
            if ci:
                line += f", 95% CI [{ci[0]:+.1%}, {ci[1]:+.1%}]"
            line += f", n={v['n_base']}v{v['n_cand']})"
        print(line, file=out)
    if overall == stats.VERDICT_INCOMPARABLE:
        d = verdict["device"]
        print(
            "bench-gate: PASS (incomparable — baseline ran on "
            f"{d['baseline']!r}, candidate on {d['candidate']!r})",
            file=out,
        )
        return 0
    if overall == stats.VERDICT_REGRESSION:
        print("bench-gate: FAIL (significant regression)", file=out)
        print(json.dumps(verdict), file=out)
        return 1
    if overall == stats.VERDICT_SUSPECT:
        # Isolated flags in a wide metric family: below the coherence
        # bar real (shared-code-path) regressions clear, and within the
        # per-cell between-run false-positive rate. Loud, not fatal —
        # re-measure the named cells with more repeats to confirm.
        print(
            "bench-gate: PASS (suspect — isolated cell flags, below "
            f"the coherence bar: {', '.join(verdict.get('suspect', []))}"
            "; re-measure those cells before trusting a trend)",
            file=out,
        )
        return 0
    print(f"bench-gate: PASS ({overall})", file=out)
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        "bench-gate",
        description="fail on statistically significant bench regressions",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline BENCH json (default: next-newest parseable "
        "BENCH_r*.json)",
    )
    parser.add_argument(
        "--candidate", default=None,
        help="candidate BENCH json (default: newest parseable "
        "BENCH_r*.json)",
    )
    parser.add_argument(
        "--min-effect", type=float, default=None,
        help="relative effect below which a significant difference is "
        "still noise (default: ELASTICDL_BENCH_MIN_EFFECT)",
    )
    parser.add_argument(
        "--root", default=None,
        help="directory to search for BENCH_r*.json (default: repo root)",
    )
    args = parser.parse_args(argv)
    return run_gate(
        baseline_path=args.baseline,
        candidate_path=args.candidate,
        min_effect=args.min_effect,
        root=args.root,
    )


if __name__ == "__main__":
    sys.exit(main())
