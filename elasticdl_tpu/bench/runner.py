"""Bench orchestration: run the suite, always emit the one JSON line.

Invariants this module owns:

- ONE result line on stdout, always — even when benchmarks time out,
  raise, or the budget truncates the run. The line is assembled
  incrementally and printed in a ``finally``; a wedged benchmark costs
  its own slot ({"error": ..., "timed_out": true}), never the line.
- every benchmark runs under the hard per-benchmark watchdog AND a soft
  shared BudgetClock that workloads consult between timed windows
  (degrading sample counts instead of dying).
- a flight recorder is armed for the whole run (role "bench"): SIGTERM,
  a crash, or a watchdog timeout dumps the last spans + the currently
  open phase to flightrec-bench.json, so a dead run leaves attributable
  evidence instead of an rc=124.
- the result carries a significance verdict vs the newest parseable
  checked-in BENCH_*.json (stats.compare_records): CIs from this run's
  windows vs the baseline's samples, device-kind guarded.

This module itself never imports jax — workloads load lazily — so the
emission/verdict machinery is testable in milliseconds.
"""

import json
import os
import sys
import time

from elasticdl_tpu.bench import attribution, stats
from elasticdl_tpu.bench.budget import BudgetClock, run_with_watchdog
from elasticdl_tpu.common import knobs
from elasticdl_tpu.observability import flightrec, profiling

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# The one-line result schema (docs/BENCHMARKS.md documents it; the
# stats tests validate emitted lines against it).
RESULT_KEYS = ("metric", "value", "unit", "vs_baseline", "details")


def validate_result(obj):
    """Raise ValueError unless ``obj`` is a schema-valid result line."""
    if not isinstance(obj, dict):
        raise ValueError("result line must be a JSON object")
    missing = [k for k in RESULT_KEYS if k not in obj]
    if missing:
        raise ValueError(f"result line missing keys: {missing}")
    if not isinstance(obj["details"], dict):
        raise ValueError("details must be an object")
    return obj


def _round_if_ok(result):
    if not isinstance(result, dict) or "error" in result:
        return result
    return {
        k: (round(v, 4) if isinstance(v, float) else v)
        for k, v in result.items()
    }


def _emit(result, out_path=None):
    line = json.dumps(validate_result(result))
    print(line)
    if out_path:
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        os.replace(tmp, out_path)


def attach_verdict(details, min_effect=None, baseline_path=None):
    """Compare this run against the newest parseable BENCH_*.json and
    fold the verdict into ``details``. Never raises — a broken baseline
    file becomes a recorded note, not a dead run."""
    if min_effect is None:
        min_effect = knobs.get_float("ELASTICDL_BENCH_MIN_EFFECT")
    try:
        if baseline_path is None:
            baseline_path = knobs.get_str("ELASTICDL_BENCH_BASELINE")
        if baseline_path:
            baseline = stats.load_bench_file(baseline_path)
            pairs = [(baseline_path, baseline)] if baseline else []
        else:
            pairs = stats.find_baselines(REPO_ROOT)
        if not pairs:
            details["verdict"] = {"overall": "no-baseline"}
            return details
        path, baseline = stats.select_baseline(
            pairs, details.get("device_kind") or ""
        )
        candidate = {"metric": "candidate", "details": details}
        verdict = stats.compare_records(
            baseline, candidate, min_effect=min_effect
        )
        verdict["baseline_file"] = os.path.basename(path)
        details["verdict"] = verdict
    except Exception as e:  # evidence machinery must not sink the run
        details["verdict"] = {
            "overall": "error", "error": str(e)[:200]
        }
    return details


def _arm_flightrec():
    try:
        flightrec.install("bench")
    except Exception:
        pass


def _watchdog(name, fn, timeout_s):
    with flightrec.phase(name):
        return run_with_watchdog(
            name, fn, timeout_s,
            on_timeout=lambda n: flightrec.dump(f"watchdog-timeout:{n}"),
        )


def _measured(name, fn, timeout_s, measured, key):
    """Run one bench under the watchdog while measuring its wall clock
    and the compile-tracker seconds delta — the inputs the step-time
    attribution table (bench/attribution.py) needs per workload."""
    t0 = time.perf_counter()
    c0 = profiling.tracker().snapshot()[1]
    result = _watchdog(name, fn, timeout_s)
    wall = time.perf_counter() - t0
    compile_s = max(0.0, profiling.tracker().snapshot()[1] - c0)
    measured[key] = (result, wall, compile_s)
    return result


def _attach_attribution(details, measured):
    """Fold the per-workload attribution into the result details and
    print the human table to stderr (stdout stays the one JSON line)."""
    try:
        table = attribution.build_all(measured)
        if table:
            details["attribution"] = table
        print(attribution.render_table(table), file=sys.stderr)
    except Exception as e:  # evidence machinery must not sink the run
        details["attribution_error"] = str(e)[:200]


def run_full(watchdog_s=None, budget_s=None, with_matrix=True,
             out_path=None):
    """The full suite. Returns the process exit code."""
    import jax  # the full suite is meaningless without a backend

    from elasticdl_tpu.bench import fleet as fleet_bench
    from elasticdl_tpu.bench import matrix, workloads

    if watchdog_s is None:
        watchdog_s = knobs.get_float("ELASTICDL_BENCH_WATCHDOG_S")
    if budget_s is None:
        budget_s = knobs.get_float("ELASTICDL_BENCH_BUDGET_S")
    _arm_flightrec()
    clock = BudgetClock(budget_s)
    windows = knobs.get_int("ELASTICDL_BENCH_WINDOWS")
    details = {
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": max(jax.local_device_count(), 1),
    }
    if budget_s:
        details["budget_s"] = budget_s
    # Suite order: recsys + PS benches and the rejoin drill FIRST, the
    # conv backbones LAST. A conv bench that blows its watchdog leaves
    # an unkillable abandoned compile thread burning CPU; on a CPU-only
    # host that thread would contaminate every measurement taken after
    # it — so nothing measurable runs after the convs. (On TPU the
    # order is irrelevant: convs finish in seconds.)
    #
    # The matrix and the rejoin drill get a floored watchdog: both are
    # many-part benchmarks (8 cells x repeats / two full kill-rejoin
    # jobs) that degrade themselves against the budget clock — a
    # watchdog sized for ONE workload would kill them mid-flight and
    # discard the parts that already ran. 0 still disables.
    suite = [
        (
            "deepfm_criteo", "deepfm_criteo",
            lambda: workloads.bench_deepfm_criteo(
                windows=windows, clock=clock
            ),
            watchdog_s, True,
        ),
        (
            "deepfm_ps_mode", "deepfm_ps",
            lambda: workloads.bench_deepfm_ps(clock=clock),
            watchdog_s, False,
        ),
        # Fleet cells run EARLY: jax-free (simulated pods, real control
        # plane), ~2-3 min total, and they must not be squeezed by
        # whatever budget the matrix/rejoin leave over — a mid-A/B
        # watchdog kill discards both sides of the comparison. Still a
        # many-part bench, so the floored watchdog applies.
        (
            "fleet", "fleet",
            lambda: fleet_bench.bench_fleet(clock=clock),
            watchdog_s and max(watchdog_s, 600), False,
        ),
    ]
    if with_matrix:
        suite.append(
            (
                "ps_matrix", "ps_matrix",
                lambda: matrix.bench_ps_matrix(clock=clock),
                watchdog_s and max(watchdog_s, 600), False,
            )
        )
    suite += [
        (
            "elastic_rejoin", "elastic_rejoin",
            workloads.bench_elastic_rejoin,
            watchdog_s and max(watchdog_s, 600), False,
        ),
        (
            "resnet50", "resnet50",
            lambda: workloads.bench_resnet50(
                windows=windows, clock=clock
            ),
            watchdog_s, True,
        ),
        (
            "mobilenetv2", "mobilenetv2",
            lambda: workloads.bench_mobilenetv2(
                windows=windows, clock=clock
            ),
            watchdog_s, True,
        ),
    ]
    measured = {}
    try:
        for key, name, fn, timeout_s, round_result in suite:
            # A spent budget SKIPS remaining benchmarks instead of
            # starting them: the one JSON line must reach stdout before
            # whatever outer wall (the driver's ~870 s timeout that
            # produced the evidence-free BENCH_r05) kills the process.
            # Each skip is recorded — truncation is visible, not silent.
            if clock.expired:
                details[key] = {"skipped": "budget"}
                continue
            # Cap the watchdog by the REMAINING budget: a bench that
            # starts with 90 s of budget left must not get its full
            # 600 s bound — the whole point of the budget is that the
            # result line lands before the outer wall, and one wedged
            # late benchmark running out its uncapped watchdog would
            # overshoot the budget by up to that watchdog. (The 1 s
            # floor keeps the cap from becoming 0 = watchdog disabled.)
            if timeout_s and clock.total_s:
                timeout_s = min(timeout_s, max(clock.remaining(), 1.0))
            result = _measured(name, fn, timeout_s, measured, key)
            details[key] = _round_if_ok(result) if round_result else result
    finally:
        _attach_attribution(details, measured)
        deepfm = details.get("deepfm_criteo") or {}
        if isinstance(deepfm, dict) and "examples_per_sec" in deepfm:
            details["deepfm_examples_per_sec_chip"] = round(
                deepfm["examples_per_sec"], 2
            )
        if budget_s:
            details["budget_elapsed_s"] = round(clock.elapsed(), 2)
        attach_verdict(details)
        # LocalTrainer's jitted step runs on exactly one device, so its
        # examples/sec IS the per-chip figure regardless of how many
        # chips the host exposes.
        resnet = details.get("resnet50") or {}
        per_chip = (
            resnet.get("examples_per_sec", 0.0)
            if isinstance(resnet, dict)
            else 0.0
        )
        baseline_img_per_sec = 145.0  # reference ResNet50, 1x P100
        _emit(
            {
                "metric": (
                    "examples/sec/chip (ResNet50, bf16, 224x224, "
                    "batch 128)"
                ),
                "value": round(per_chip, 2),
                "unit": "examples/sec",
                "vs_baseline": round(
                    per_chip / baseline_img_per_sec, 3
                ),
                "details": details,
            },
            out_path,
        )
    return 0


def run_smoke(watchdog_s=None, budget_s=None, out_path=None,
              benches=None):
    """CPU-safe tiny-shape pass (< 60 s): exercises the bench pipelines —
    windowed jitted loop (with CI fields), PS-resident loop over a real
    localhost shard with the push serialize/wire/apply breakdown —
    without TPU-scale shapes or the elastic drill. This is the CI guard
    for the bench subsystem itself: a hang or crash in the harness shows
    up here in seconds, not at the end of a multi-hour TPU session.

    ``benches`` overrides the registry ({name: fn}) — the truncated-run
    emission tests inject deliberately wedged/raising workloads."""
    if watchdog_s is None:
        watchdog_s = 50.0
    if budget_s is None:
        budget_s = knobs.get_float("ELASTICDL_BENCH_BUDGET_S")
    _arm_flightrec()
    clock = BudgetClock(budget_s)
    if benches is None:
        from elasticdl_tpu.bench import matrix, workloads

        # Conv backbones are out: their CPU compile alone blows the
        # budget. The DeepFM benches still cover both execution
        # pipelines (the windowed jitted loop — 3 windows, so CI fields
        # are present — and the PS pull/train/push loop with the push
        # sub-span breakdown), and a 2-cell matrix slice proves the
        # shard-count axis plumbing without TPU-scale shapes.
        benches = {
            "deepfm_criteo_b256": lambda: workloads.bench_deepfm_criteo(
                batch_size=256, steps_per_window=2, windows=3, warmup=1,
                clock=clock,
            ),
            "deepfm_ps_b128": lambda: workloads.bench_deepfm_ps(
                batch_size=128, steps=2, warmup=1, num_ps=1, repeats=1,
                clock=clock,
            ),
            # float32 + int8 codecs: the int8 cell keeps the quantized
            # packed wire (block codec + error feedback) covered in the
            # <60 s path; no prefetch-off control at smoke scale.
            "ps_matrix_tiny": lambda: matrix.bench_ps_matrix(
                batch_size=128, steps=2, warmup=1, repeats=1,
                shard_counts=(1, 2), codecs=("float32", "int8"),
                pipelining=(False,), prefetch_controls=(), clock=clock,
            ),
        }
    details = {}
    failures = 0
    measured = {}
    start = time.perf_counter()
    try:
        for name, fn in benches.items():
            if clock.expired:
                details[name] = {"skipped": "budget"}
                continue
            timeout_s = watchdog_s
            if timeout_s and clock.total_s:
                timeout_s = min(timeout_s, max(clock.remaining(), 1.0))
            result = _measured(name, fn, timeout_s, measured, name)
            details[name] = _round_if_ok(result)
            if not isinstance(result, dict) or "error" in result:
                failures += 1
    finally:
        _attach_attribution(details, measured)
        elapsed = time.perf_counter() - start
        details["elapsed_s"] = round(elapsed, 2)
        details["failures"] = failures
        _emit(
            {
                "metric": "bench smoke (tiny shapes, CPU-safe)",
                "value": round(elapsed, 2),
                "unit": "seconds",
                "vs_baseline": None,
                "details": details,
            },
            out_path,
        )
    return 1 if failures else 0
