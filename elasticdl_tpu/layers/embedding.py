"""PS-resident embedding layer — the TPU-first redesign of the reference's
`elasticdl.layers.Embedding` + EmbeddingDelegate
(/root/reference/elasticdl/python/elasticdl/layers/embedding.py:20-163,
python/elasticdl/embedding_delegate.py:26-310).

The reference RPCs the parameter server *mid-forward-pass* through a
tf.py_function and tape-watches the fetched rows so backprop yields sparse
grads. Under XLA that host round-trip would sit inside the compiled step and
stall the TPU, so the design is split instead:

  OUTSIDE jit (ps_trainer):  ids -> unique -> PSClient.pull_embedding_vectors
                             -> per-position rows [n_positions, dim]
  INSIDE jit (this layer):   rows arrive via the `edl_embedding` flax
                             collection; the layer reshapes/combines them —
                             pure gathers and reductions XLA fuses into the
                             surrounding graph.

Gradients: the trainer differentiates the loss wrt the provided collection,
giving per-position row grads, deduplicates them by id
(tensor_utils.deduplicate_indexed_slices) and pushes IndexedSlices to the PS
— the same wire contract as the reference, with the tape trick replaced by
explicit differentiation wrt an input.
"""

import flax.linen as nn
import jax.numpy as jnp

# Collection name under which the PS trainer provides looked-up rows.
EMBEDDING_COLLECTION = "edl_embedding"


class DistributedEmbedding(nn.Module):
    """Embedding whose table lives in the parameter server, not in params.

    table_name: PS table key (shared across workers).
    dim: embedding dimension.
    combiner: None -> return per-id embeddings [*ids.shape, dim];
              "sum" | "mean" | "sqrtn" -> reduce the LAST id axis, the
              multivalent-feature combiners of the reference layer
              (embedding.py:20-163).

    In LOCAL/AllReduce strategies (no PS), the layer degrades to an ordinary
    trainable table of `vocab_size` rows held in params — set vocab_size for
    that; under the PS strategy the collection entry overrides it.
    """

    table_name: str
    dim: int
    combiner: str = None
    vocab_size: int = 0

    @nn.compact
    def __call__(self, ids):
        ids = jnp.asarray(ids)
        n_positions = 1
        for s in ids.shape:
            n_positions *= s

        if self.vocab_size:
            # Local/AllReduce fallback: an ordinary trainable table.
            table = self.param(
                "table",
                nn.initializers.uniform(scale=0.05),
                (self.vocab_size, self.dim),
            )
            batch_embeddings = jnp.take(
                table, ids.astype(jnp.int32), axis=0
            )
        else:
            # PS strategy: per-position rows provided by the trainer. At
            # model.init time the collection is mutable and the zeros
            # init_fn runs (shapes flow, values don't matter); at apply
            # time self.variable returns the trainer-provided rows.
            rows = self.variable(
                EMBEDDING_COLLECTION,
                self.table_name,
                lambda: jnp.zeros((n_positions, self.dim), jnp.float32),
            )
            batch_embeddings = rows.value.reshape(ids.shape + (self.dim,))

        if self.combiner is None:
            return batch_embeddings
        if self.combiner == "sum":
            return jnp.sum(batch_embeddings, axis=-2)
        if self.combiner == "mean":
            return jnp.mean(batch_embeddings, axis=-2)
        if self.combiner == "sqrtn":
            n = batch_embeddings.shape[-2]
            return jnp.sum(batch_embeddings, axis=-2) / jnp.sqrt(
                jnp.asarray(n, batch_embeddings.dtype)
            )
        raise ValueError(f"unknown combiner {self.combiner!r}")
