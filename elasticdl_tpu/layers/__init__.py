"""Model-facing layers backed by the distributed runtime."""

from elasticdl_tpu.layers.embedding import (  # noqa: F401
    EMBEDDING_COLLECTION,
    DistributedEmbedding,
)
