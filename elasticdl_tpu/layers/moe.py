"""Switch-style mixture-of-experts FFN with expert parallelism.

No reference counterpart (the reference is DP-only, SURVEY.md §2.10); this
extends the parallel story with EP. TPU-first design: top-1 routing with
FIXED capacity so every shape is static under jit — dispatch and combine
are one-hot einsums (MXU work, no scatter), and the expert weight tensors
[E, ...] shard over an "expert" mesh axis via plain PartitionSpecs, with
XLA inserting the all-to-alls. Dropped tokens (over capacity) pass through
the residual unchanged, the standard Switch behavior.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.parallel.mesh import MODEL_AXIS


class SwitchMoE(nn.Module):
    """Top-1 routed expert FFN. Returns (output [B, S, D], aux_loss) —
    aux_loss is the Switch load-balancing term, add it to the task loss
    scaled by ~1e-2."""

    num_experts: int
    d_hidden: int
    capacity_factor: float = 1.25
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x):
        dtype = jnp.dtype(self.dtype)
        b, s, d = x.shape
        tokens = x.reshape(-1, d)
        n_tokens = b * s
        capacity = max(
            1,
            int(self.capacity_factor * n_tokens / self.num_experts),
        )

        # Router in float32: tiny matmul, numerically sensitive.
        logits = nn.Dense(
            self.num_experts, dtype=jnp.float32, name="router"
        )(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
        expert = jnp.argmax(probs, axis=-1)  # [T]
        onehot = jax.nn.one_hot(
            expert, self.num_experts, dtype=jnp.float32
        )

        # Load-balancing aux loss (Switch eq. 4): fraction of tokens per
        # expert dotted with mean router prob per expert, scaled by E.
        density = jnp.mean(onehot, axis=0)
        density_proxy = jnp.mean(probs, axis=0)
        aux_loss = self.num_experts * jnp.sum(density * density_proxy)

        # Position of each token within its expert; beyond-capacity tokens
        # drop (contribute zero; the caller's residual carries them).
        position = jnp.cumsum(onehot, axis=0) * onehot  # 1-based
        keep = (position <= capacity).astype(jnp.float32) * onehot
        gate = jnp.sum(probs * keep, axis=-1)  # [T]
        pos_idx = jnp.sum((position - 1.0) * keep, axis=-1).astype(
            jnp.int32
        )
        # [T, E, C] one-hot dispatch mask.
        dispatch = (
            keep[:, :, None]
            * jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)[
                :, None, :
            ]
        )

        w_in = self.param(
            "w_in",
            nn.initializers.lecun_normal(),
            (self.num_experts, d, self.d_hidden),
        )
        w_out = self.param(
            "w_out",
            nn.initializers.lecun_normal(),
            (self.num_experts, self.d_hidden, d),
        )

        # Dispatch -> expert FFN -> combine, all einsums (the all-to-alls
        # appear here when w_*/expert axes are sharded).
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(dtype), tokens.astype(dtype)
        )
        h = nn.gelu(
            jnp.einsum("ecd,edh->ech", expert_in, w_in.astype(dtype))
        )
        expert_out = jnp.einsum(
            "ech,ehd->ecd", h, w_out.astype(dtype)
        )
        combined = jnp.einsum(
            "tec,ecd->td",
            (dispatch * gate[:, None, None]).astype(dtype),
            expert_out,
        )
        return combined.reshape(b, s, d).astype(x.dtype), aux_loss


def moe_param_specs(params, expert_axis=MODEL_AXIS):
    """PartitionSpecs for a SwitchMoE param subtree, built by walking the
    actual tree so structure changes can't silently diverge: expert
    weight tensors (leading dim E) shard over `expert_axis`, everything
    else (the router) replicates.

    The default is the trainer meshes' model axis: no production mesh
    declares a dedicated "expert" axis, so the old "expert" default
    produced specs that could never match the mesh they flowed into
    (the drift class the mesh-spec-consistency lint rule rejects)."""
    from elasticdl_tpu.common.pytree_utils import nest_at, walk_dict

    specs = {}
    for path, leaf in walk_dict(params):
        if path[-1] in ("w_in", "w_out"):
            specs[path] = P(
                expert_axis, *([None] * (leaf.ndim - 1))
            )
        else:
            specs[path] = P()
    return nest_at(specs)
