"""ODPS/MaxCompute table writer — prediction outputs back to a table.

Reference counterpart: /root/reference/elasticdl/python/data/odps_io.py:
336-407 (`ODPSWriter`: lazily create/open the output table, then stream a
worker's prediction rows into its own `worker=<id>` partition, used by the
cifar10 zoo model's PredictionOutputsProcessor,
model_zoo/cifar10/cifar10_functional_api.py:164-185). Same SDK gating as
OdpsReader (data/odps_reader.py): all orchestration — table
creation/reuse, per-worker partitions, chunked writes, bounded retries —
is plain tested Python against a narrow injected client surface; in
production that client is `odps.ODPS(...)` (pyodps), in tests a fake.

Client surface used:
  exist_table(name) -> bool
  create_table(name, (cols_ddl, partition_ddl)) -> table
  get_table(name) -> table with
      open_writer(partition=..., create_partition=True) context manager
      yielding an object with .write(rows)

The two-string schema form ("c0 double, c1 double", "worker string") is
pyodps' documented lightweight create_table signature — no SDK Schema
class import needed on either side of the gate.

Delivery semantics are AT-LEAST-ONCE, like the reference's: a chunk
retry after a commit-ack timeout (the server applied the upload but the
ack was lost) re-writes the whole chunk into the partition, and a failed
prediction task that re-runs appends its rows again. Downstream
consumers that need exactly-once should dedup on a row key or truncate
the `worker=<id>` partition before re-running a job. (The reference has
no write retry at all — its failure mode is the task-level re-run, which
duplicates identically.)
"""

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.odps_reader import _default_client, retrying
from elasticdl_tpu.worker.prediction_outputs_processor import (
    BasePredictionOutputsProcessor,
)

logger = get_logger("data.odps_writer")

DEFAULT_WRITE_CHUNK_ROWS = 4096
DEFAULT_MAX_RETRIES = 3


class OdpsWriter:
    """Writes rows (lists of column values) into one ODPS table, one
    partition per worker — so N prediction workers stream concurrently
    without write conflicts (the reference's layout)."""

    def __init__(
        self,
        project=None,
        access_id=None,
        access_key=None,
        endpoint=None,
        table=None,
        columns=None,
        column_types=None,
        chunk_rows=DEFAULT_WRITE_CHUNK_ROWS,
        max_retries=DEFAULT_MAX_RETRIES,
        retry_base_seconds=0.5,
        client=None,
    ):
        if not table:
            raise ValueError("OdpsWriter requires a table name")
        if "." in table:
            # "project.table" shorthand, as the reference accepted.
            project, table = table.split(".", 1)
        self._project = project
        self._table_name = table
        self._columns = list(columns) if columns else None
        self._column_types = list(column_types) if column_types else None
        self._chunk_rows = max(1, int(chunk_rows))
        self._max_retries = max(1, int(max_retries))
        self._retry_base_seconds = retry_base_seconds
        self._client = client or _default_client(
            project, access_id, access_key, endpoint
        )
        self._table = None

    def _retrying(self, fn, what):
        return retrying(
            fn, what, self._max_retries, self._retry_base_seconds,
            log=logger,
        )

    def _ensure_table(self):
        """Reuse the table when it exists; otherwise create it partitioned
        by worker (string), which requires explicit columns/types
        (reference odps_io.py:381-397). Creation is raced by concurrent
        workers starting against a missing table: on ANY create failure,
        re-check existence and fall back to get_table — the winner's
        table is what everyone wanted (blindly retrying create_table
        would keep failing with already-exists until retries exhaust)."""
        if self._table is not None:
            return self._table
        if self._client.exist_table(self._table_name):
            self._table = self._client.get_table(self._table_name)
            return self._table
        if not self._columns or not self._column_types:
            raise ValueError(
                f"table {self._table_name!r} does not exist; creating it "
                "requires columns and column_types"
            )
        if len(self._columns) != len(self._column_types):
            raise ValueError(
                f"{len(self._columns)} columns vs "
                f"{len(self._column_types)} column_types"
            )
        cols_ddl = ", ".join(
            f"{c} {t}" for c, t in zip(self._columns, self._column_types)
        )

        def create_or_adopt():
            try:
                return self._client.create_table(
                    self._table_name, (cols_ddl, "worker string")
                )
            except Exception:
                if self._client.exist_table(self._table_name):
                    logger.info(
                        "Table %s appeared while creating it (peer "
                        "worker won the race); using it",
                        self._table_name,
                    )
                    return self._client.get_table(self._table_name)
                raise

        self._table = self._retrying(create_or_adopt, "create table")
        logger.info(
            "Created ODPS table %s (%s) partitioned by worker",
            self._table_name,
            cols_ddl,
        )
        return self._table

    def from_iterator(self, rows_iter, worker_index):
        """Stream rows into partition worker=<worker_index>. Rows are
        buffered into chunks so one upload call covers thousands of rows
        (per-row tunnel writes are the slow path), each chunk retried
        independently (at-least-once — see the module docstring).
        Returns the number of rows written."""
        partition = f"worker={worker_index}"
        written = 0
        chunk = []
        for row in rows_iter:
            chunk.append(list(row))
            if len(chunk) >= self._chunk_rows:
                self._write_chunk(partition, chunk)
                written += len(chunk)
                chunk = []
        if chunk:
            self._write_chunk(partition, chunk)
            written += len(chunk)
        logger.info(
            "Wrote %d rows to %s/%s", written, self._table_name, partition
        )
        return written

    def _write_chunk(self, partition, chunk):
        table = self._ensure_table()

        # A fresh writer session per attempt: like the reader, an
        # expired/broken tunnel session is the common failure, and
        # re-entering open_writer mints a new one.
        def attempt():
            with table.open_writer(
                partition=partition, create_partition=True
            ) as w:
                w.write(chunk)

        self._retrying(attempt, f"write {len(chunk)} rows")


class OdpsPredictionOutputsProcessor(BasePredictionOutputsProcessor):
    """Prediction-outputs processor writing each worker's model outputs to
    an ODPS table (the reference cifar10 zoo's processor,
    cifar10_functional_api.py:164-185, as a reusable class).

    The worker calls process() once per MINIBATCH (worker.py
    _process_predict_batch), so rows are buffered here across calls and
    flushed in writer-chunk-sized uploads — without this, a 1M-row job
    at minibatch 16 would open ~62k tunnel sessions. The worker calls
    close() when the prediction task stream ends; anything still
    buffered flushes then. `columns` default to f0..f{n-1} doubles
    inferred from the first batch's width when the table must be
    created."""

    def __init__(self, writer=None, table=None, columns=None,
                 column_types=None, client=None, **writer_kwargs):
        if writer is not None:
            self._writer = writer
        else:
            self._writer = OdpsWriter(
                table=table,
                columns=columns,
                column_types=column_types,
                client=client,
                **writer_kwargs,
            )
        self._buffer = []
        self._worker_id = None

    def process(self, predictions, worker_id):
        import numpy as np

        arr = np.asarray(predictions)
        if arr.ndim == 1:
            arr = arr[:, None]
        arr = arr.reshape(arr.shape[0], -1)
        w = self._writer
        if w._columns is None:
            w._columns = [f"f{i}" for i in range(arr.shape[1])]
            w._column_types = ["double"] * arr.shape[1]
        self._worker_id = worker_id
        self._buffer.extend(arr.tolist())
        if len(self._buffer) >= w._chunk_rows:
            self.flush()

    def flush(self):
        if not self._buffer:
            return 0
        rows, self._buffer = self._buffer, []
        try:
            return self._writer.from_iterator(iter(rows), self._worker_id)
        except Exception:
            # The buffer holds rows from tasks already reported done;
            # dropping them on a failed write would be at-most-once (the
            # master only re-dispatches the CURRENT task). Restore so the
            # next flush/close retries them — at-least-once as documented.
            self._buffer = rows + self._buffer
            raise

    def close(self):
        """Flush any buffered rows; the worker calls this after its last
        prediction task."""
        return self.flush()
