"""Example codec: dict[str, np.ndarray] <-> bytes via the Example proto.

The framework's stable on-disk training-example format (replaces the
reference's TF Example usage in its dataset converters,
/root/reference/elasticdl/python/data/recordio_gen/).
"""

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb


def encode_example(features: dict) -> bytes:
    ex = pb.Example()
    for name, value in features.items():
        ex.features[name].CopyFrom(
            tensor_utils.ndarray_to_tensor_pb(np.asarray(value), name)
        )
    return ex.SerializeToString()


def decode_example(data: bytes) -> dict:
    ex = pb.Example()
    ex.ParseFromString(data)
    return {
        name: tensor_utils.tensor_pb_to_ndarray(t)
        for name, t in ex.features.items()
    }


def batch_examples(records):
    """Decode and stack a list of serialized Examples into a feature batch:
    {name: array of shape [batch, ...]}."""
    decoded = [decode_example(r) for r in records]
    if not decoded:
        return {}
    return {
        name: np.stack([d[name] for d in decoded]) for name in decoded[0]
    }
