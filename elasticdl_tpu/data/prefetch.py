"""Background-thread record prefetch: overlap reader IO with training.

Reference counterpart: ParallelODPSDataReader's thread-pooled download
(/root/reference/elasticdl/python/data/reader/odps_reader.py:26-251,
odps_io.py:71-407 — sharded download queue feeding the training loop).
Generalized here to ANY reader: `read_records(task)` runs the wrapped
reader's generator on a producer thread that fills a bounded queue, so
disk reads + CRC checks + proto decode overlap the accelerator's work on
the previous minibatches instead of serializing with it. Record order is
preserved (single producer per task); producer exceptions re-raise in the
consumer at the position they occurred; closing/abandoning the consumer
generator stops the producer instead of leaking a thread blocked on a
full queue.
"""

import queue
import sys
import threading
import time

from elasticdl_tpu.chaos import injection
from elasticdl_tpu.observability import datapath

_END = object()


DEFAULT_BUFFER_BYTES = 64 << 20


class PrefetchReader:
    """Wrap a data reader so its per-task record stream is produced ahead
    of consumption on a background thread. The buffer is bounded BOTH by
    record count (`buffer_records`) and by total buffered payload bytes
    (`buffer_bytes`) — the byte bound is what keeps host RAM flat when
    records are large (a 1024-record bound alone would hold ~150 MB of
    224x224 image Examples)."""

    # Data-plane attribution marker: the producer thread below accounts
    # record reads as the `read` stage, so downstream consumers
    # (TaskDataService.read_batches) must book their queue waits as
    # `starve`, not `read` — otherwise read time would count twice.
    datapath_starve_waits = True

    def __init__(self, reader, buffer_records=1024,
                 buffer_bytes=DEFAULT_BUFFER_BYTES):
        if buffer_records < 1:
            raise ValueError("buffer_records must be >= 1")
        if buffer_bytes < 1:
            raise ValueError("buffer_bytes must be >= 1")
        self._reader = reader
        self._buffer_records = buffer_records
        self._buffer_bytes = buffer_bytes

    def read_records(self, task):
        q = queue.Queue(maxsize=self._buffer_records)
        stop = threading.Event()
        dp = datapath.get()
        # Hand-off queue occupancy/backpressure telemetry; per-task is
        # fine (one producer per task), and re-arming the watermark edge
        # per task keeps excursions attributable to a task id.
        q_telemetry = datapath.QueueTelemetry(
            "prefetch", capacity=self._buffer_records, datapath=dp
        )
        # Outstanding payload bytes, guarded by its own lock; the producer
        # parks while over budget (at least one record is always allowed
        # through so a single huge record can't deadlock).
        state = {"bytes": 0}
        cond = threading.Condition()

        def _sizeof(item):
            if isinstance(item, (bytes, bytearray, memoryview)):
                return len(item)
            # Parsed rows/objects (e.g. CSV tuples) have no byte length
            # (len() would count fields, not bytes); approximate with the
            # interpreter's shallow size so the byte budget still bounds
            # host RAM rather than silently degrading to the record-count
            # bound alone.
            return sys.getsizeof(item)

        def _put(item, nbytes=0):
            """put() that gives up when the consumer is gone."""
            with cond:
                while (
                    not stop.is_set()
                    and state["bytes"] > 0
                    and state["bytes"] + nbytes > self._buffer_bytes
                ):
                    cond.wait(timeout=0.1)
                if stop.is_set():
                    return False
                state["bytes"] += nbytes
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            # The producer owns the `read` stage: each pull from the
            # wrapped reader is timed off the training thread (overlap
            # means this cost only surfaces downstream as `starve` when
            # the queue runs dry). Records are NOT counted here — the
            # consumer's delivery boundary counts them exactly once.
            it = iter(self._reader.read_records(task))
            try:
                while True:
                    if dp.enabled:
                        # The chaos hook sits INSIDE the timed window so
                        # an injected slow reader shows up as `read`
                        # seconds, exactly like a genuinely slow one.
                        start = time.time()
                        injection.inject_local("datapath.read")
                        record = next(it, _END)
                        dp.add("read", time.time() - start)
                    else:
                        record = next(it, _END)
                    if record is _END:
                        break
                    if not _put(record, _sizeof(record)):
                        return
                    q_telemetry.depth(q.qsize())
            except BaseException as e:  # re-raised on the consumer side
                _put((_END, e))
                return
            _put((_END, None))

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                # Records pass through untouched; only the producer's own
                # (_END, err) pair terminates (readers can yield tuples —
                # _END is module-private, so no user tuple can match).
                if (
                    isinstance(item, tuple)
                    and len(item) == 2
                    and item[0] is _END
                ):
                    err = item[1]
                    if err is not None:
                        raise err
                    return
                with cond:
                    state["bytes"] -= _sizeof(item)
                    cond.notify()
                yield item
        finally:
            # Runs on exhaustion AND on generator close/GC (task failure
            # mid-batch): release the producer and wait for it, so no
            # stale thread is still reading the (possibly shared) file
            # handle when the next task's producer starts.
            stop.set()
            t.join(timeout=5.0)
            if t.is_alive():  # pragma: no cover - stuck in a blocked read
                import logging

                logging.getLogger("data.prefetch").warning(
                    "prefetch producer for task %s did not exit within 5s",
                    getattr(task, "task_id", "?"),
                )

    def __getattr__(self, name):
        # Everything else (create_shards, metadata, ...) delegates to the
        # wrapped reader.
        return getattr(self._reader, name)
