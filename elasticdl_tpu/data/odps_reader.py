"""ODPS/MaxCompute table reader.

Reference counterpart: /root/reference/elasticdl/python/data/reader/
odps_reader.py:26-251 and data/odps_io.py:71-407 (table-tunnel download
sessions, a parallel page-fetch pool, bounded retries, shard creation from
the table's row count). This rebuild keeps that orchestration — shard
creation, ordered parallel page prefetch, per-page retry with backoff —
as plain tested Python, and gates only the vendor SDK: the reader talks to
any client exposing the narrow pyodps surface it needs
(`get_table(name).open_reader(partition=...)` -> object with `.count` and
`.read(start=, count=)` yielding records with `.values`). In production
that client is `odps.ODPS(...)` (pyodps); in this air-gapped repo the unit
tests inject a fake, which is exactly how the k8s layer covers its live
paths against a stub API server.

Origin URI (create_data_reader): odps://<project>/tables/<table>[/<part>]
with credentials from the environment (ODPS_ACCESS_ID, ODPS_ACCESS_KEY,
ODPS_ENDPOINT — the reference's MaxComputeConfig env contract).
"""

import concurrent.futures
import os
import time

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.reader import AbstractDataReader, Metadata

logger = get_logger("data.odps_reader")

DEFAULT_PAGE_RECORDS = 4096
DEFAULT_MAX_RETRIES = 3


def retrying(fn, what, max_retries, base_seconds, log=logger):
    """Run fn() up to max_retries times with exponential backoff — the
    one retry policy shared by the ODPS reader and writer."""
    for attempt in range(max_retries):
        try:
            return fn()
        except Exception:
            if attempt == max_retries - 1:
                raise
            delay = base_seconds * (2 ** attempt)
            log.warning(
                "ODPS %s failed (attempt %d/%d); retrying in %.1fs",
                what, attempt + 1, max_retries, delay,
                exc_info=True,
            )
            time.sleep(delay)


def _default_client(project, access_id, access_key, endpoint):
    try:
        from odps import ODPS  # pyodps, not baked into this image
    except ImportError as e:
        raise ImportError(
            "ODPS reading needs the pyodps package (`pip install pyodps`) "
            "or an injected client object"
        ) from e
    return ODPS(access_id, access_key, project=project, endpoint=endpoint)


class OdpsReader(AbstractDataReader):
    """Reads one ODPS table (optionally one partition) as record tuples.

    Records are yielded in table order as lists of column values — the
    same shape CSVDataReader yields — with column names in `metadata`,
    so a model's `feed` is reader-agnostic.
    """

    def __init__(
        self,
        project=None,
        access_id=None,
        access_key=None,
        endpoint=None,
        table=None,
        partition=None,
        columns=None,
        num_parallel=4,
        page_records=DEFAULT_PAGE_RECORDS,
        max_retries=DEFAULT_MAX_RETRIES,
        retry_base_seconds=0.5,
        client=None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if not table:
            raise ValueError("OdpsReader requires a table name")
        self._project = project
        self._table_name = table
        self._partition = partition or None
        self._columns = list(columns) if columns else None
        self._num_parallel = max(1, int(num_parallel))
        self._page_records = max(1, int(page_records))
        self._max_retries = max(1, int(max_retries))
        self._retry_base_seconds = retry_base_seconds
        self._client = client or _default_client(
            project, access_id, access_key, endpoint
        )
        self._metadata = None

    # ---------- shard creation (master side) ----------

    def _open_reader(self):
        table = self._client.get_table(self._table_name)
        if self._partition:
            return table.open_reader(partition=self._partition)
        return table.open_reader()

    def create_shards(self):
        """One logical shard spanning the table/partition; the master's
        task dispatcher cuts it into records_per_task ranges exactly as
        it does for record files (the reference pre-chunked here AND in
        the dispatcher; one authority is enough)."""
        count = self._retrying(
            lambda: int(self._open_reader().count), "row count"
        )
        name = self._table_name + (
            f"/{self._partition}" if self._partition else ""
        )
        return {name: (0, count)}

    # ---------- record reading (worker side) ----------

    @property
    def metadata(self):
        if self._metadata is None:
            columns = self._columns
            if columns is None:
                try:
                    columns = self._retrying(
                        lambda: [
                            c.name
                            for c in self._client.get_table(
                                self._table_name
                            ).schema.columns
                        ],
                        "schema",
                    )
                except Exception:
                    # Schema introspection is best-effort (a client may
                    # not expose it at all) — but do NOT cache the empty
                    # answer: a transient failure here would otherwise
                    # poison every later feed that maps columns by name.
                    logger.warning(
                        "ODPS schema introspection failed; column names "
                        "unavailable this time", exc_info=True,
                    )
                    return Metadata(column_names=[])
            self._metadata = Metadata(column_names=columns)
        return self._metadata

    def read_records(self, task):
        """Yield the task's [start, end) rows in order. Pages of
        `page_records` rows are fetched by a small thread pool with a
        bounded look-ahead (the reference's parallel tunnel downloads,
        odps_io.py:214-301), each page independently retried."""
        start, end = int(task.start), int(task.end)
        if end <= start:
            return
        pages = [
            (s, min(self._page_records, end - s))
            for s in range(start, end, self._page_records)
        ]
        if len(pages) == 1 or self._num_parallel == 1:
            for s, n in pages:
                yield from self._read_page(s, n)
            return
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=self._num_parallel
        ) as pool:
            # Ordered delivery with bounded look-ahead: keep up to
            # num_parallel pages in flight, always yielding the oldest.
            futures = {}
            next_submit = 0
            for next_yield in range(len(pages)):
                while (
                    next_submit < len(pages)
                    and next_submit - next_yield < self._num_parallel
                ):
                    futures[next_submit] = pool.submit(
                        self._read_page, *pages[next_submit]
                    )
                    next_submit += 1
                yield from futures.pop(next_yield).result()

    def _read_page(self, start, count):
        def fetch():
            # A fresh download session per attempt: expired/broken tunnel
            # sessions are the common ODPS failure mode.
            reader = self._open_reader()
            rows = []
            for record in reader.read(start=start, count=count):
                values = getattr(record, "values", record)
                rows.append(list(values))
            if len(rows) != count:
                raise IOError(
                    f"short page at {start}: got {len(rows)}/{count}"
                )
            return rows

        return self._retrying(fetch, f"page@{start}")

    def _retrying(self, fn, what):
        return retrying(
            fn, what, self._max_retries, self._retry_base_seconds
        )


def parse_odps_origin(origin):
    """odps://<project>/tables/<table>[/<partition>] -> kwargs dict with
    credentials resolved from the environment."""
    rest = origin[len("odps://"):]
    parts = rest.split("/")
    if len(parts) < 3 or parts[1] != "tables" or not parts[2]:
        raise ValueError(
            f"bad ODPS origin {origin!r}; expected "
            "odps://<project>/tables/<table>[/<partition>]"
        )
    return {
        "project": parts[0],
        "table": parts[2],
        "partition": "/".join(parts[3:]) or None,
        "access_id": os.environ.get("ODPS_ACCESS_ID"),
        "access_key": os.environ.get("ODPS_ACCESS_KEY"),
        "endpoint": os.environ.get("ODPS_ENDPOINT"),
    }
