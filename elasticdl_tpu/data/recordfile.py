"""Seekable record file format (.edlr): the framework's RecordIO equivalent.

The reference reads RecordIO shards by (file, start, count) range
(/root/reference/elasticdl/python/data/reader/recordio_reader.py:27-62).
This format supports the same access pattern with O(1) seeks:

    [magic "EDLR"][u32 version]
    [u32 len][record bytes] ...          # the records
    [u64 offset] * num_records           # footer: offset of each record
    [u64 num_records][u64 index_offset][magic "EDLI"]

Written records are opaque bytes; the framework stores Example protos in them
but any payload works.
"""

import os
import struct

_MAGIC = b"EDLR"
_FOOTER_MAGIC = b"EDLI"
_VERSION = 1
_FOOTER_TAIL = struct.Struct("<QQ4s")  # num_records, index_offset, magic
_LEN = struct.Struct("<I")
_OFF = struct.Struct("<Q")


class RecordFileWriter:
    def __init__(self, path):
        self._f = open(path, "wb")
        self._f.write(_MAGIC)
        self._f.write(struct.pack("<I", _VERSION))
        self._offsets = []
        self._closed = False

    def write(self, record: bytes):
        self._offsets.append(self._f.tell())
        self._f.write(_LEN.pack(len(record)))
        self._f.write(record)

    def close(self):
        if self._closed:
            return
        index_offset = self._f.tell()
        for off in self._offsets:
            self._f.write(_OFF.pack(off))
        self._f.write(
            _FOOTER_TAIL.pack(len(self._offsets), index_offset, _FOOTER_MAGIC)
        )
        self._f.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordFile:
    """Random-access reader over a .edlr file."""

    def __init__(self, path):
        self.path = path
        self._f = open(path, "rb")
        if self._f.read(4) != _MAGIC:
            raise ValueError(f"{path} is not a record file (bad magic)")
        (version,) = struct.unpack("<I", self._f.read(4))
        if version != _VERSION:
            raise ValueError(f"{path}: unsupported record file version {version}")
        self._f.seek(-_FOOTER_TAIL.size, os.SEEK_END)
        num, index_offset, magic = _FOOTER_TAIL.unpack(
            self._f.read(_FOOTER_TAIL.size)
        )
        if magic != _FOOTER_MAGIC:
            raise ValueError(
                f"{path}: truncated or corrupt record file (bad footer)"
            )
        self.num_records = num
        self._index_offset = index_offset

    def _record_offset(self, i):
        self._f.seek(self._index_offset + i * _OFF.size)
        (off,) = _OFF.unpack(self._f.read(_OFF.size))
        return off

    def read(self, start: int, count: int):
        """Yield `count` records beginning at record index `start`.

        Records are contiguous on disk, so after one seek the range is a
        sequential scan — the access pattern task dispatch relies on.
        """
        if start < 0 or start + count > self.num_records:
            raise IndexError(
                f"range [{start}, {start + count}) out of bounds "
                f"for {self.num_records} records"
            )
        if count == 0:
            return
        self._f.seek(self._record_offset(start))
        for _ in range(count):
            (length,) = _LEN.unpack(self._f.read(_LEN.size))
            yield self._f.read(length)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path, records):
    with RecordFileWriter(path) as w:
        for r in records:
            w.write(r)
