"""Seekable record file format (.edlr): the framework's RecordIO equivalent.

The reference reads RecordIO shards by (file, start, count) range
(/root/reference/elasticdl/python/data/reader/recordio_reader.py:27-62)
through a native RecordIO library. This format supports the same access
pattern with O(1) seeks, and range reads take a native fast path
(native/recordio.cc: one mmap + sequential scan + CRC checks in C) when
the shared library is available, with this pure-Python reader as the
fallback.

    [magic "EDLR"][u32 version]
    v2 record: [u32 len][u32 crc32(payload)][payload] ...
    [u64 offset] * num_records           # footer: offset of each record
    [u64 num_records][u64 index_offset][magic "EDLI"]

Version 2 adds a per-record CRC32 (zlib polynomial) so disk/transport
corruption is detected at read time instead of surfacing as a garbled
Example proto; v1 files (no CRC) remain readable.

Written records are opaque bytes; the framework stores Example protos in
them but any payload works.
"""

import os
import struct
import zlib

import numpy as np

_MAGIC = b"EDLR"
_FOOTER_MAGIC = b"EDLI"
_VERSION = 2
_READABLE_VERSIONS = (1, 2)
_FOOTER_TAIL = struct.Struct("<QQ4s")  # num_records, index_offset, magic
_LEN = struct.Struct("<I")
_LEN_CRC = struct.Struct("<II")
_OFF = struct.Struct("<Q")


class RecordFileWriter:
    def __init__(self, path):
        self._f = open(path, "wb")
        self._f.write(_MAGIC)
        self._f.write(struct.pack("<I", _VERSION))
        self._offsets = []
        self._closed = False

    def write(self, record: bytes):
        self._offsets.append(self._f.tell())
        self._f.write(_LEN_CRC.pack(len(record), zlib.crc32(record)))
        self._f.write(record)

    def close(self):
        if self._closed:
            return
        index_offset = self._f.tell()
        for off in self._offsets:
            self._f.write(_OFF.pack(off))
        self._f.write(
            _FOOTER_TAIL.pack(len(self._offsets), index_offset, _FOOTER_MAGIC)
        )
        self._f.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RecordFile:
    """Random-access reader over a .edlr file."""

    def __init__(self, path):
        self.path = path
        self._f = open(path, "rb")
        if self._f.read(4) != _MAGIC:
            raise ValueError(f"{path} is not a record file (bad magic)")
        (self._version,) = struct.unpack("<I", self._f.read(4))
        if self._version not in _READABLE_VERSIONS:
            raise ValueError(
                f"{path}: unsupported record file version {self._version}"
            )
        self._f.seek(-_FOOTER_TAIL.size, os.SEEK_END)
        num, index_offset, magic = _FOOTER_TAIL.unpack(
            self._f.read(_FOOTER_TAIL.size)
        )
        if magic != _FOOTER_MAGIC:
            raise ValueError(
                f"{path}: truncated or corrupt record file (bad footer)"
            )
        self.num_records = num
        self._index_offset = index_offset

    def _record_offset(self, i, f):
        """Index lookup on an explicit handle — callers each open their
        own so concurrent range scans never share a seek cursor."""
        f.seek(self._index_offset + i * _OFF.size)
        (off,) = _OFF.unpack(f.read(_OFF.size))
        return off

    def read(self, start: int, count: int):
        """Yield `count` records beginning at record index `start`.

        Records are contiguous on disk, so after one seek the range is a
        sequential scan — the access pattern task dispatch relies on.
        Dispatches to the native scanner (mmap + C loop + CRC) when the
        shared library is loadable; EDL_NO_NATIVE=1 forces this Python
        path.
        """
        if start < 0 or start + count > self.num_records:
            raise IndexError(
                f"range [{start}, {start + count}) out of bounds "
                f"for {self.num_records} records"
            )
        if count == 0:
            return
        native = _native_lib()
        if native is not None:
            yield from self._read_native(native, start, count)
            return
        # Per-call handle: readers cache RecordFile objects, and with the
        # prefetch reader a range scan runs on a producer thread — a
        # shared seek/read cursor would interleave across threads.
        with open(self.path, "rb") as f:
            f.seek(self._record_offset(start, f))
            for i in range(count):
                if self._version >= 2:
                    length, want = _LEN_CRC.unpack(f.read(_LEN_CRC.size))
                    payload = f.read(length)
                    if zlib.crc32(payload) != want:
                        raise ValueError(
                            f"{self.path}: CRC mismatch in record "
                            f"{start + i} (corrupt file)"
                        )
                else:
                    (length,) = _LEN.unpack(f.read(_LEN.size))
                    payload = f.read(length)
                yield payload

    def _read_native(self, native, start, count):
        # Payload span upper bound: distance between the first record's
        # offset and the end of the range (headers included — slack, not
        # waste: the buffer is transient). Own handle for the index reads
        # (thread-safety, same reason as the scan path).
        with open(self.path, "rb") as f:
            first = self._record_offset(start, f)
            end = (
                self._index_offset
                if start + count == self.num_records
                else self._record_offset(start + count, f)
            )
        # The two offsets come from untrusted on-disk index entries; clamp
        # before allocating so a flipped bit raises the same corrupt-file
        # error the scanner would, not a negative-size ValueError or a
        # pathological multi-GB np.empty.
        if not 0 <= first <= end <= self._index_offset:
            raise ValueError(
                f"{self.path}: index entries out of bounds for records "
                f"[{start}, {start + count}) (corrupt file)"
            )
        buf = np.empty(end - first, dtype=np.uint8)
        lens = np.empty(count, dtype=np.int64)
        import ctypes

        total = native.edl_records_read(
            self.path.encode(),
            start,
            count,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            buf.nbytes,
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if total == -5:
            raise ValueError(
                f"{self.path}: CRC mismatch in range [{start}, "
                f"{start + count}) (corrupt file)"
            )
        if total < 0:
            raise ValueError(
                f"{self.path}: native record read failed (code {total})"
            )
        pos = 0
        view = memoryview(buf)
        for n in lens:
            n = int(n)
            yield bytes(view[pos:pos + n])
            pos += n

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _native_lib():
    if os.environ.get("EDL_NO_NATIVE"):
        return None
    from elasticdl_tpu import native

    return native.lib()


def write_records(path, records):
    with RecordFileWriter(path) as w:
        for r in records:
            w.write(r)
