"""Synthetic Criteo-DAC-shaped CTR data: 13 heavy-tailed integer features,
26 categorical ids, a clicked/not label with real signal in both parts.

Counterpart of the reference's Criteo converter
(/root/reference/model_zoo/dac_ctr/convert_to_recordio.py), adapted for an
air-gapped environment: instead of reading the Kaggle DAC dump, draw from
the distribution family described in models/dac_ctr/feature_config.py. The
label depends on (a) a linear score over the log-dense features and (b)
per-id propensities derived from a splitmix-style integer mix of the raw
categorical ids — so embeddings have something genuine to learn and AUC
rises above 0.5 within a few hundred steps.
"""

import numpy as np

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.models.dac_ctr import feature_config as fc


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer: uint64 -> uint64, decorrelates consecutive ids."""
    z = x.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def _id_propensity(ids: np.ndarray, feature_idx: int) -> np.ndarray:
    """Deterministic per-id weight in [-0.5, 0.5): works for 10M-sized id
    spaces without materializing a weight table."""
    salted = _mix64(ids.astype(np.uint64) ^ np.uint64(0xC1 + feature_idx))
    return (salted >> np.uint64(40)).astype(np.float64) / 2**24 - 0.5


def synthetic_criteo_arrays(num_examples, seed=0):
    """Returns (dense [N,13] float32 with -1 missing, cats [N,26] int64,
    labels [N] int64)."""
    rng = np.random.default_rng(seed)
    dense = np.round(
        rng.lognormal(
            mean=fc.DENSE_LOG_MU,
            sigma=fc.DENSE_LOG_SIGMA,
            size=(num_examples, fc.NUM_DENSE),
        )
    ).astype(np.float32) - 1.0
    # ~4% missing entries, encoded -1 as in the raw DAC dump.
    dense[rng.random(dense.shape) < 0.04] = -1.0

    cards = np.array(
        [fc.CATEGORICAL_CARDINALITY[c] for c in fc.CATEGORICAL_FEATURES],
        dtype=np.int64,
    )
    # Zipf-ish skew: squaring a uniform concentrates mass on low ids, the
    # shape real id frequency tables have.
    u = rng.random((num_examples, fc.NUM_CATEGORICAL))
    cats = np.minimum((u * u * cards).astype(np.int64), cards - 1)

    # Label logit: linear in log1p(dense) + id propensities on every
    # categorical field, temperature-scaled to a ~25% positive rate. The
    # dense weights are a FIXED dataset property (independent of `seed`):
    # iter_criteo_records re-seeds per chunk, and per-chunk weights would
    # average the dense signal to inter-chunk noise.
    log_dense = np.log1p(np.maximum(dense, 0.0))
    w = np.random.default_rng(0xDAC).normal(scale=0.5, size=fc.NUM_DENSE)
    logit = (log_dense - log_dense.mean(axis=0)) @ w
    for j in range(fc.NUM_CATEGORICAL):
        logit += 2.0 * _id_propensity(cats[:, j], j)
    logit = logit - np.percentile(logit, 75)
    labels = (rng.random(num_examples) < 1 / (1 + np.exp(-logit))).astype(
        np.int64
    )
    return dense, cats, labels


def iter_criteo_records(num_examples, seed=0, chunk=4096):
    """Yields serialized Example records with I1..I13, C1..C26, label."""
    remaining, part = num_examples, 0
    while remaining > 0:
        n = min(chunk, remaining)
        dense, cats, labels = synthetic_criteo_arrays(
            n, seed=seed * 1_000_003 + part
        )
        for i in range(n):
            features = {"label": labels[i]}
            for k, name in enumerate(fc.DENSE_FEATURES):
                features[name] = dense[i, k]
            for k, name in enumerate(fc.CATEGORICAL_FEATURES):
                features[name] = cats[i, k]
            yield encode_example(features)
        remaining -= n
        part += 1
