"""Synthetic dataset generators -> .edlr record files.

Counterpart of the reference's dataset converters
(/root/reference/elasticdl/python/data/recordio_gen/) adapted for an
air-gapped environment: instead of downloading MNIST/CIFAR, generate
learnable synthetic data (class-dependent template + noise) with the same
shapes, so end-to-end training demonstrably reduces loss.
"""

import os

import numpy as np

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordfile import RecordFileWriter


def synthetic_classification_arrays(
    num_examples,
    image_shape=(28, 28),
    num_classes=10,
    noise=0.3,
    seed=0,
    feature_name="image",
    label_name="label",
):
    """Per-class random template + gaussian noise: linearly separable enough
    that a small model's loss visibly drops within a few steps."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(size=(num_classes,) + image_shape).astype(
        np.float32
    )
    labels = rng.integers(0, num_classes, num_examples)
    images = templates[labels] + noise * rng.normal(
        size=(num_examples,) + image_shape
    ).astype(np.float32)
    return images.astype(np.float32), labels.astype(np.int64)


def synthetic_lm_tokens(
    num_sequences, seq_len, vocab=256, branching=4, seed=0
):
    """Order-1 Markov sequences where each token has `branching` equally
    likely successors: a trained LM's token CE floor is log(branching)
    (~1.386 nats for 4), well below the log(vocab) of random guessing —
    convergence is measurable without real text."""
    rng = np.random.default_rng(seed)
    successors = rng.integers(0, vocab, size=(vocab, branching))
    seqs = np.empty((num_sequences, seq_len + 1), np.int32)
    state = rng.integers(0, vocab, num_sequences)
    for t in range(seq_len + 1):
        seqs[:, t] = state
        choice = rng.integers(0, branching, num_sequences)
        state = successors[state, choice]
    return seqs


def write_synthetic_lm(
    output_dir,
    num_sequences=256,
    seq_len=128,
    vocab=256,
    num_shards=2,
    seed=0,
):
    """`num_shards` .edlr files of {"tokens": [seq_len+1]} examples."""
    os.makedirs(output_dir, exist_ok=True)
    seqs = synthetic_lm_tokens(num_sequences, seq_len, vocab, seed=seed)
    per_shard = (num_sequences + num_shards - 1) // num_shards
    for s in range(num_shards):
        lo, hi = s * per_shard, min((s + 1) * per_shard, num_sequences)
        path = os.path.join(output_dir, f"lm-shard-{s}.edlr")
        with RecordFileWriter(path) as w:
            for i in range(lo, hi):
                w.write(encode_example({"tokens": seqs[i]}))
    return output_dir


def write_synthetic_mnist(
    output_dir, num_examples=512, num_shards=2, seed=0, **kwargs
):
    """Create `num_shards` .edlr files of synthetic 28x28 examples; returns
    the directory."""
    os.makedirs(output_dir, exist_ok=True)
    images, labels = synthetic_classification_arrays(
        num_examples, seed=seed, **kwargs
    )
    per_shard = (num_examples + num_shards - 1) // num_shards
    for s in range(num_shards):
        lo, hi = s * per_shard, min((s + 1) * per_shard, num_examples)
        path = os.path.join(output_dir, f"shard-{s}.edlr")
        with RecordFileWriter(path) as w:
            for i in range(lo, hi):
                w.write(
                    encode_example(
                        {"image": images[i], "label": labels[i]}
                    )
                )
    return output_dir
