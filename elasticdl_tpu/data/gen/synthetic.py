"""Synthetic dataset generators -> .edlr record files.

Counterpart of the reference's dataset converters
(/root/reference/elasticdl/python/data/recordio_gen/) adapted for an
air-gapped environment: instead of downloading MNIST/CIFAR, generate
learnable synthetic data (class-dependent template + noise) with the same
shapes, so end-to-end training demonstrably reduces loss.
"""

import numpy as np



def synthetic_classification_arrays(
    num_examples,
    image_shape=(28, 28),
    num_classes=10,
    noise=0.3,
    seed=0,
    feature_name="image",
    label_name="label",
):
    """Per-class random template + gaussian noise: linearly separable enough
    that a small model's loss visibly drops within a few steps."""
    rng = np.random.default_rng(seed)
    templates = rng.normal(size=(num_classes,) + image_shape).astype(
        np.float32
    )
    labels = rng.integers(0, num_classes, num_examples)
    images = templates[labels] + noise * rng.normal(
        size=(num_examples,) + image_shape
    ).astype(np.float32)
    return images.astype(np.float32), labels.astype(np.int64)


def synthetic_lm_tokens(
    num_sequences, seq_len, vocab=256, branching=4, seed=0
):
    """Order-1 Markov sequences where each token has `branching` equally
    likely successors: a trained LM's token CE floor is log(branching)
    (~1.386 nats for 4), well below the log(vocab) of random guessing —
    convergence is measurable without real text."""
    rng = np.random.default_rng(seed)
    successors = rng.integers(0, vocab, size=(vocab, branching))
    seqs = np.empty((num_sequences, seq_len + 1), np.int32)
    state = rng.integers(0, vocab, num_sequences)
    for t in range(seq_len + 1):
        seqs[:, t] = state
        choice = rng.integers(0, branching, num_sequences)
        state = successors[state, choice]
    return seqs
