"""MNIST IDX -> .edlr record converter (offline; no network).

Counterpart of the reference's image converter
(/root/reference/elasticdl/python/data/recordio_gen/image_dataset_gen.py),
which pulled the dataset through Keras and wrote TF-Example RecordIO. This
environment is air-gapped, so the converter instead reads the standard
IDX files (the format MNIST/Fashion-MNIST are distributed in — possibly
gzipped) from LOCAL disk and writes Example records the model zoo's
`mnist_model.feed` consumes directly: {"image": uint8 [28, 28],
"label": int64}.

CLI:
    python -m elasticdl_tpu.data.gen.mnist_idx \
        --images train-images-idx3-ubyte[.gz] \
        --labels train-labels-idx1-ubyte[.gz] \
        --output mnist_train.edlr [--limit N]
"""

import argparse
import gzip
import struct

import numpy as np

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordfile import RecordFileWriter

_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}


def read_idx(path):
    """Parse one IDX file (gzipped or raw) into an ndarray.

    IDX layout: 2 zero bytes, dtype code, ndim, then ndim big-endian
    uint32 dims, then the row-major payload."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    zeros, dtype_code, ndim = struct.unpack(">HBB", raw[:4])
    if zeros != 0:
        raise ValueError(f"{path}: not an IDX file (bad magic)")
    dtype = _IDX_DTYPES.get(dtype_code)
    if dtype is None:
        raise ValueError(f"{path}: unknown IDX dtype code {dtype_code:#x}")
    dims = struct.unpack(f">{ndim}I", raw[4:4 + 4 * ndim])
    data = np.frombuffer(raw[4 + 4 * ndim:], dtype=dtype)
    expect = int(np.prod(dims)) if dims else 0
    if data.size < expect:
        raise ValueError(
            f"{path}: truncated IDX payload ({data.size} < {expect})"
        )
    return data[:expect].reshape(dims)


def convert(images_path, labels_path, output_path, limit=None):
    """IDX image+label files -> one .edlr record file. Returns the number
    of examples written."""
    images = read_idx(images_path)
    labels = read_idx(labels_path)
    if images.shape[0] != labels.shape[0]:
        raise ValueError(
            f"image/label count mismatch: {images.shape[0]} vs "
            f"{labels.shape[0]}"
        )
    n = images.shape[0] if limit is None else min(limit, images.shape[0])
    with RecordFileWriter(output_path) as w:
        for i in range(n):
            w.write(
                encode_example(
                    {
                        "image": np.ascontiguousarray(
                            images[i], dtype=np.uint8
                        ),
                        "label": np.int64(labels[i]),
                    }
                )
            )
    return n


def main(argv=None):
    p = argparse.ArgumentParser("mnist_idx")
    p.add_argument("--images", required=True, help="IDX image file (.gz ok)")
    p.add_argument("--labels", required=True, help="IDX label file (.gz ok)")
    p.add_argument("--output", required=True, help=".edlr output path")
    p.add_argument("--limit", type=int, default=None)
    args = p.parse_args(argv)
    n = convert(args.images, args.labels, args.output, args.limit)
    print(f"wrote {n} examples to {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
