"""Criteo DAC raw-TSV -> .edlr record converter (offline).

Counterpart of the reference's Criteo converter
(/root/reference/model_zoo/dac_ctr/convert_to_recordio.py), which parsed
the Kaggle DAC dump. The raw file format (train.txt / day_N): one example
per line, TAB-separated — label, 13 integer features (empty = missing),
26 categorical features as 8-hex-digit strings (empty = missing).

Records come out schema-identical to the synthetic generator
(data/gen/criteo.py: {label, I1..I13 float32, C1..C26 int64}), so the
dac_ctr zoo models' shared `feed`/transform consume either
interchangeably. Missing dense values encode -1.0 (the synthetic/DAC
convention); missing categoricals encode 0; hex categorials parse to
their int64 value (identity-preserving — the transform hashes them into
each field's bin space anyway).

CLI:
    python -m elasticdl_tpu.data.gen.criteo_tsv \
        --input train.txt --output criteo.edlr [--limit N]
"""

import argparse
import gzip

import numpy as np

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordfile import RecordFileWriter
from elasticdl_tpu.models.dac_ctr import feature_config as fc

_NUM_FIELDS = 1 + fc.NUM_DENSE + len(fc.CATEGORICAL_FEATURES)


def parse_line(line):
    """One TSV line -> {label, I1..I13, C1..C26} feature dict."""
    parts = line.rstrip("\n").split("\t")
    if len(parts) != _NUM_FIELDS:
        raise ValueError(
            f"bad DAC line: {len(parts)} fields, expected {_NUM_FIELDS}"
        )
    features = {"label": np.int64(parts[0])}
    for k, name in enumerate(fc.DENSE_FEATURES):
        raw = parts[1 + k]
        features[name] = np.float32(raw) if raw else np.float32(-1.0)
    offset = 1 + fc.NUM_DENSE
    for k, name in enumerate(fc.CATEGORICAL_FEATURES):
        raw = parts[offset + k]
        features[name] = np.int64(int(raw, 16)) if raw else np.int64(0)
    return features


def convert(input_path, output_path, limit=None):
    """DAC TSV (optionally .gz) -> one .edlr file. Returns rows written."""
    opener = gzip.open if str(input_path).endswith(".gz") else open
    n = 0
    with opener(input_path, "rt") as f, RecordFileWriter(output_path) as w:
        for line in f:
            if limit is not None and n >= limit:
                break
            if not line.strip():
                continue
            w.write(encode_example(parse_line(line)))
            n += 1
    return n


def main(argv=None):
    p = argparse.ArgumentParser("criteo_tsv")
    p.add_argument("--input", required=True, help="train.txt[.gz] DAC dump")
    p.add_argument("--output", required=True, help=".edlr output path")
    p.add_argument("--limit", type=int, default=None)
    args = p.parse_args(argv)
    n = convert(args.input, args.output, args.limit)
    print(f"wrote {n} examples to {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
