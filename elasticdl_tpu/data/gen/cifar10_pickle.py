"""CIFAR-10 python-pickle batches -> .edlr record converter (offline).

Counterpart of the reference's image converter family
(/root/reference/elasticdl/python/data/recordio_gen/image_dataset_gen.py),
which pulled CIFAR through Keras. This converter reads the standard
"CIFAR-10 python version" batch files (pickled dicts with b"data" as
uint8 [N, 3072] channel-major rows and b"labels"), possibly inside the
distributed tar.gz, from LOCAL disk and writes Example records the zoo's
`cifar10_cnn.feed` consumes: {"image": uint8 [32, 32, 3] (NHWC),
"label": int64}.

CLI:
    python -m elasticdl_tpu.data.gen.cifar10_pickle \
        --batches data_batch_1 data_batch_2 ... --output train.edlr
    python -m elasticdl_tpu.data.gen.cifar10_pickle \
        --tar cifar-10-python.tar.gz --split train --output train.edlr
"""

import argparse
import pickle
import tarfile

import numpy as np

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordfile import RecordFileWriter


def read_batch_file(path):
    """One pickle batch file -> (images uint8 [N, 32, 32, 3], labels)."""
    with open(path, "rb") as f:
        return _decode_batch(f)


def _decode_batch(fileobj):
    batch = pickle.load(fileobj, encoding="bytes")
    data = np.asarray(batch[b"data"], dtype=np.uint8)
    if b"labels" not in batch:
        raise ValueError(
            "not a CIFAR-10 batch: no b'labels' key (CIFAR-100 files "
            "carry b'fine_labels' and 100 classes — this converter is "
            "CIFAR-10 only)"
        )
    labels = np.asarray(batch[b"labels"], dtype=np.int64)
    if data.ndim != 2 or data.shape[1] != 3072:
        raise ValueError(
            f"not a CIFAR-10 batch: data shape {data.shape}"
        )
    if labels.size and (labels.min() < 0 or labels.max() > 9):
        raise ValueError(
            f"not a CIFAR-10 batch: labels outside [0, 9] "
            f"(min {labels.min()}, max {labels.max()})"
        )
    # Rows are channel-major [3, 32, 32]; the zoo model is NHWC.
    images = data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    return np.ascontiguousarray(images), labels


def read_tar(path, split="train"):
    """(images, labels) concatenated from the official tar.gz — the five
    data_batch_* members for train, test_batch for test."""
    wanted = (
        [f"data_batch_{i}" for i in range(1, 6)]
        if split == "train"
        else ["test_batch"]
    )
    images, labels = [], []
    with tarfile.open(path, "r:*") as tar:
        members = {m.name.rsplit("/", 1)[-1]: m for m in tar.getmembers()}
        for name in wanted:
            m = members.get(name)
            if m is None:
                raise ValueError(f"{path}: member {name!r} not found")
            imgs, lbls = _decode_batch(tar.extractfile(m))
            images.append(imgs)
            labels.append(lbls)
    return np.concatenate(images), np.concatenate(labels)


def convert(images, labels, output_path, limit=None):
    n = images.shape[0] if limit is None else min(limit, images.shape[0])
    with RecordFileWriter(output_path) as w:
        for i in range(n):
            w.write(
                encode_example(
                    {"image": images[i], "label": np.int64(labels[i])}
                )
            )
    return n


def main(argv=None):
    p = argparse.ArgumentParser("cifar10_pickle")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--batches", nargs="+", help="pickle batch files (data_batch_*)"
    )
    src.add_argument("--tar", help="cifar-10-python.tar.gz")
    p.add_argument("--split", choices=["train", "test"], default="train")
    p.add_argument("--output", required=True)
    p.add_argument("--limit", type=int, default=None)
    args = p.parse_args(argv)
    if args.tar:
        images, labels = read_tar(args.tar, args.split)
    else:
        parts = [read_batch_file(b) for b in args.batches]
        images = np.concatenate([x for x, _ in parts])
        labels = np.concatenate([y for _, y in parts])
    n = convert(images, labels, args.output, args.limit)
    print(f"wrote {n} examples to {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
