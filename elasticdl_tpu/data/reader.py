"""Data readers: turn a Task's (shard_name, start, end) into records.

Mirrors the reference reader contract (/root/reference/elasticdl/python/data/
reader/data_reader.py:19-114): `read_records(task)` yields raw records for the
task's range; `create_shards()` returns {shard_name: (start, num_records)} for
the master to partition into tasks.
"""

import csv
import glob
import os
from abc import ABC, abstractmethod

from elasticdl_tpu.data.recordfile import RecordFile


class Metadata:
    def __init__(self, column_names=None):
        self.column_names = column_names or []


class AbstractDataReader(ABC):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    @abstractmethod
    def read_records(self, task):
        """Yield records (bytes or tuples) for task.start..task.end within
        task.shard_name."""

    @abstractmethod
    def create_shards(self):
        """Return {shard_name: (start_index, num_records)}."""

    @property
    def metadata(self):
        return Metadata()


class RecordFileReader(AbstractDataReader):
    """Reads .edlr record files; one shard per file. `data_origin` may be
    a directory (every *.edlr inside becomes a shard) or one .edlr file
    (exactly that file — siblings in the same directory are NOT pulled in,
    they may belong to other datasets)."""

    def __init__(self, data_origin, **kwargs):
        super().__init__(**kwargs)
        self._origin = data_origin
        self._files = {}  # path -> RecordFile, opened lazily and cached

    def _record_file(self, path):
        if path not in self._files:
            self._files[path] = RecordFile(path)
        return self._files[path]

    def read_records(self, task):
        rf = self._record_file(task.shard_name)
        yield from rf.read(task.start, task.end - task.start)

    def create_shards(self):
        if os.path.isdir(self._origin):
            paths = sorted(
                glob.glob(os.path.join(self._origin, "*.edlr"))
            )
        else:
            paths = [self._origin] if os.path.exists(self._origin) else []
        shards = {
            path: (0, RecordFile(path).num_records) for path in paths
        }
        if not shards:
            raise ValueError(f"no .edlr record files at {self._origin}")
        return shards

    def close(self):
        for rf in self._files.values():
            rf.close()
        self._files.clear()


class CSVDataReader(AbstractDataReader):
    """Reads rows of one CSV file by index range (reference
    csv_reader.py:26-75). Records are tuples of strings."""

    def __init__(self, data_path, sep=",", with_header=False, **kwargs):
        super().__init__(**kwargs)
        self._path = data_path
        self._sep = sep
        self._with_header = with_header
        self._columns = None
        if with_header:
            with open(self._path, newline="") as f:
                self._columns = next(csv.reader(f, delimiter=self._sep))

    def read_records(self, task):
        skip = 1 if self._with_header else 0
        with open(self._path, newline="") as f:
            reader = csv.reader(f, delimiter=self._sep)
            for i, row in enumerate(reader):
                idx = i - skip
                if idx < task.start:
                    continue
                if idx >= task.end:
                    break
                yield tuple(row)

    def create_shards(self):
        skip = 1 if self._with_header else 0
        with open(self._path, newline="") as f:
            count = sum(1 for _ in csv.reader(f, delimiter=self._sep)) - skip
        return {self._path: (0, count)}

    @property
    def metadata(self):
        return Metadata(column_names=self._columns)


class InMemoryReader(AbstractDataReader):
    """Serves records from an in-memory list — used by tests and local runs
    the way the reference uses generated RecordIO fixtures
    (/root/reference/elasticdl/python/tests/test_utils.py:103)."""

    def __init__(self, records, shard_name="memory", **kwargs):
        super().__init__(**kwargs)
        self._records = list(records)
        self._shard_name = shard_name

    def read_records(self, task):
        yield from self._records[task.start : task.end]

    def create_shards(self):
        return {self._shard_name: (0, len(self._records))}


class CompositeReader(AbstractDataReader):
    """Routes tasks to the sub-reader owning the task's shard.

    A worker doing training + interleaved evaluation holds one reader, but
    training and validation data are distinct origins: the master names
    shards after each origin's own shard keys, so routing by shard_name
    keeps evaluation tasks reading validation rows (a single-origin reader
    that ignores shard_name would silently evaluate on training data)."""

    def __init__(self, readers, **kwargs):
        super().__init__(**kwargs)
        self._readers = list(readers)
        self._shard_to_reader = {}
        for reader in self._readers:
            for shard_name in reader.create_shards():
                self._shard_to_reader[shard_name] = reader

    def _reader_for(self, shard_name):
        reader = self._shard_to_reader.get(shard_name)
        if reader is None:
            raise ValueError(
                f"no reader owns shard {shard_name!r}; known: "
                f"{sorted(self._shard_to_reader)}"
            )
        return reader

    def read_records(self, task):
        yield from self._reader_for(task.shard_name).read_records(task)

    def create_shards(self):
        shards = {}
        for reader in self._readers:
            shards.update(reader.create_shards())
        return shards

    @property
    def metadata(self):
        return self._readers[0].metadata


def create_data_reader(data_origin, records_per_task=None, **kwargs):
    """Factory sniffing the origin type (reference
    data_reader_factory.py:23-73)."""
    if isinstance(data_origin, AbstractDataReader):
        return data_origin
    if isinstance(data_origin, (list, tuple)):
        return InMemoryReader(data_origin, **kwargs)
    if isinstance(data_origin, str) and data_origin.startswith("odps://"):
        from elasticdl_tpu.data.odps_reader import (
            OdpsReader,
            parse_odps_origin,
        )

        return OdpsReader(**{**parse_odps_origin(data_origin), **kwargs})
    if os.path.isdir(data_origin):
        return RecordFileReader(data_origin, **kwargs)
    if data_origin.endswith(".csv"):
        return CSVDataReader(data_origin, **kwargs)
    if data_origin.endswith(".edlr"):
        return RecordFileReader(data_origin, **kwargs)
    raise ValueError(f"cannot infer a data reader for: {data_origin!r}")
