"""The `edl` command-line client (reference: elasticdl_client/)."""
