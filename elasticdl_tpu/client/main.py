"""`edl` CLI: submit/run elastic training jobs.

Reference counterpart: /root/reference/elasticdl_client/main.py:28-107 and
api.py:116-248. Subcommands:

  edl train    --model_def ... --training_data ...
  edl evaluate --model_def ... --validation_data ... --checkpoint_dir_for_init ...
  edl predict  --model_def ... --prediction_data ... --checkpoint_dir_for_init ...
  edl zoo init / edl zoo list

Submission modes:
  --instance_backend local_process (default): the master runs IN THIS
      process and spawns worker/PS subprocesses on this host — the TPU-VM
      single-host path (no Docker build step; TPU hosts run the package
      directly).
  --instance_backend k8s: the master pod is created via the kubernetes API
      (requires the kubernetes package + cluster credentials); --yaml dumps
      the master pod manifest instead of creating it, mirroring the
      reference's --yaml mode (api.py:217-232).
"""

import argparse
import os
import shutil
import sys

from elasticdl_tpu.common import args as args_mod
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("client.main")


def _job_parser(name):
    p = argparse.ArgumentParser(f"edl {name}", add_help=True)
    args_mod.add_common_arguments(p)
    args_mod.add_data_arguments(p)
    args_mod.add_train_arguments(p)
    args_mod.add_cluster_arguments(p)
    args_mod.add_ps_arguments(p)
    p.add_argument(
        "--yaml",
        default="",
        help="(k8s) write the master pod manifest to this file instead of "
        "creating it",
    )
    return p


def _run_master_in_process(argv):
    from elasticdl_tpu.master.main import main as master_main

    return master_main(argv)


def _submit(job_args, raw_argv):
    args_mod.validate_args(job_args)
    if job_args.instance_backend == "k8s":
        return _submit_k8s(job_args, raw_argv)
    return _run_master_in_process(raw_argv)


def _strip_flag(argv, flag):
    """Drop '--flag value' and '--flag=value' forms from an argv list."""
    out = []
    skip_next = False
    for a in argv:
        if skip_next:
            skip_next = False
            continue
        if a == flag:
            skip_next = True
            continue
        if a.startswith(flag + "="):
            continue
        out.append(a)
    return out


def _master_pod_manifest(job_args, raw_argv):
    command = ["python", "-m", "elasticdl_tpu.master.main"] + _strip_flag(
        raw_argv, "--yaml"
    )
    # The master reads the training data itself (shard creation), so it
    # needs the same --volume mounts the worker/PS pods get.
    from elasticdl_tpu.common.k8s_resource import (
        group_volume_manifests,
        parse_volume_spec,
    )

    volumes, mounts = group_volume_manifests(
        parse_volume_spec(getattr(job_args, "volume", ""))
    )
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"elasticdl-{job_args.job_name}-master",
            "labels": {
                "app": "elasticdl",
                "elasticdl-job-name": job_args.job_name,
                "elasticdl-replica-type": "master",
            },
        },
        "spec": {
            "serviceAccountName": "elasticdl-master",
            "restartPolicy": "Never",
            **({"volumes": volumes} if volumes else {}),
            "containers": [
                {
                    "name": "master",
                    "image": job_args.image_name,
                    "command": command,
                    **(
                        {"volumeMounts": mounts} if mounts else {}
                    ),
                    "env": [
                        {
                            "name": "MY_POD_IP",
                            "valueFrom": {
                                "fieldRef": {"fieldPath": "status.podIP"}
                            },
                        }
                    ],
                }
            ],
        },
    }


def _submit_k8s(job_args, raw_argv):
    manifest = _master_pod_manifest(job_args, raw_argv)
    if job_args.yaml:
        import json

        with open(job_args.yaml, "w") as f:
            json.dump(manifest, f, indent=2)
        logger.info("Wrote master pod manifest to %s", job_args.yaml)
        return 0
    from elasticdl_tpu.common import k8s_client

    k8s_client.require_k8s()
    client = k8s_client.Client(
        job_args.namespace, job_args.job_name, job_args.image_name
    )
    # The manifest goes up verbatim: serviceAccountName (RBAC to spawn
    # worker/PS pods) and the MY_POD_IP fieldRef must survive.
    client.create_pod_from_manifest(manifest)
    logger.info("Submitted master pod for job %s", job_args.job_name)
    return 0


# ---------- zoo ----------

_ZOO_TEMPLATE = '''"""Model definition for elasticdl_tpu.

Export the spec contract: custom_model / loss / optimizer / feed
(+ optional eval_metrics_fn / callbacks / embedding_inputs).
"""

import flax.linen as nn
import jax.numpy as jnp

from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.ops import optimizers


class Model(nn.Module):
    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.Dense(64)(x)
        x = nn.relu(x)
        return nn.Dense(1)(x)


def custom_model():
    return Model()


def loss(labels, predictions):
    return jnp.mean((predictions.reshape(-1) - labels.reshape(-1)) ** 2)


def optimizer():
    return optimizers.sgd(learning_rate=0.1)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    return batch["x"], batch.get("y")
'''


def _zoo_init(args):
    os.makedirs(args.path, exist_ok=True)
    target = os.path.join(args.path, f"{args.name}.py")
    if os.path.exists(target) and not args.force:
        logger.error("%s already exists (use --force)", target)
        return 1
    with open(target, "w") as f:
        f.write(_ZOO_TEMPLATE)
    logger.info("Created model definition scaffold at %s", target)
    return 0


def _zoo_list(args):
    import elasticdl_tpu.models as zoo

    zoo_dir = os.path.dirname(zoo.__file__)
    for entry in sorted(os.listdir(zoo_dir)):
        path = os.path.join(zoo_dir, entry)
        if os.path.isdir(path) and not entry.startswith("__"):
            print(entry)
    return 0


def _zoo_build(args):
    """Copy a model zoo dir next to a Dockerfile for image builds (the
    docker SDK is optional; this prints the build command instead of
    shelling out when docker is unavailable)."""
    os.makedirs(args.build_dir, exist_ok=True)
    dest = os.path.join(
        args.build_dir, os.path.basename(os.path.normpath(args.path))
    )
    if os.path.exists(dest):
        shutil.rmtree(dest)
    shutil.copytree(args.path, dest)
    dockerfile = os.path.join(args.build_dir, "Dockerfile")
    with open(dockerfile, "w") as f:
        f.write(
            f"FROM {args.base_image}\n"
            f"COPY {os.path.basename(dest)} /model_zoo/"
            f"{os.path.basename(dest)}\n"
            "ENV PYTHONPATH=/model_zoo\n"
        )
    print(
        f"docker build -t {args.image} {args.build_dir}",
    )
    return 0


def _zoo_push(args):
    """Push a built model-zoo image to its registry (reference
    elasticdl_client/api.py:93-113 pushes via the docker SDK). Shells out
    to the docker CLI when present; otherwise prints the command so
    air-gapped environments can run it where docker lives."""
    import shutil as _shutil
    import subprocess

    cmd = ["docker", "push", args.image]
    if args.dry_run:
        print(" ".join(cmd))
        return 0
    if _shutil.which("docker") is None:
        # Without docker this command cannot do its job — failing loudly
        # keeps CI from submitting jobs whose image never shipped.
        print(" ".join(cmd))
        logger.error(
            "docker CLI not found; run the printed command where docker "
            "is available (or use --dry_run to silence this error)"
        )
        return 1
    res = subprocess.run(cmd)
    return res.returncode


def _top_summary_line(status, first_records, first_ts, now):
    """The job-end summary: the edl_job_* aggregates a CI log should
    keep — average throughput, straggler flags, abandoned tasks."""
    rate = ""
    if first_ts is not None and now > first_ts:
        avg = (status.records_done - first_records) / (now - first_ts)
        rate = f" avg={avg:.1f} rec/s"
    stragglers = ",".join(status.stragglers) or "none"
    policy = f"policy: actions={status.policy_actions}"
    if status.policy_blacklisted:
        policy += f" blacklist={','.join(status.policy_blacklisted)}"
    if status.backup_wins:
        policy += f" backup_wins={status.backup_wins}"
    if status.backup_tasks_inflight:
        policy += f" backups_inflight={status.backup_tasks_inflight}"
    return (
        f"summary: records={status.records_done}{rate} "
        f"stragglers={stragglers} "
        f"abandoned={status.tasks_abandoned} "
        f"recovered={status.tasks_recovered} "
        f"alerts={status.alerts_fired}"
        + (" FAILED" if status.job_failed else "")
        + "\n"
        + policy
    )


def _dash(args):
    """Live terminal dashboard: job status from the master's RPC plus the
    aggregator's /api/summary (throughput sparkline, per-worker step-time
    bars, straggler flags, PS shard load, active alerts). --once renders
    exactly one frame and exits — the non-interactive/test mode."""
    import time

    from elasticdl_tpu.common import knobs, rpc
    from elasticdl_tpu.observability import dashboard
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    import grpc

    channel = rpc.build_channel(args.master_addr)
    stub = rpc.Stub(channel, rpc.MASTER_SERVICE)
    host = args.master_addr.rsplit(":", 1)[0]
    patience = knobs.get_float("ELASTICDL_MASTER_PATIENCE_SECONDS")
    unreachable_since = None
    retry_delay = 0.0
    incarnation = 0
    banner = ""
    last_status = None
    polls = 0
    iterations = getattr(args, "iterations", 0)

    def _bounded_exit():
        # Bounded probe (same --iterations contract as edl top): a
        # wedged-but-serving master must not hang CI forever — and a
        # master never reached at all is still exit 2, not success.
        if last_status is None:
            print(
                f"master {args.master_addr} unreachable", flush=True
            )
            return 2
        return 1 if last_status.job_failed else 0

    while True:
        if iterations and polls >= iterations:
            return _bounded_exit()
        polls += 1
        try:
            status = stub.get_job_status(pb.GetJobStatusRequest())
            unreachable_since = None
        except grpc.RpcError as e:
            # The master stops serving right after the job ends (same
            # race _top rides): a job last seen FINISHED must exit 0/1,
            # not read as a master crash. Mid-job, an unreachable master
            # is most likely RESTARTING (journal replay takes a moment),
            # so a watch session rides the same patience window the
            # workers do instead of exiting 1 three polls in. --once
            # keeps the strict single-probe contract.
            now = time.time()
            if args.once or (
                last_status is not None and last_status.finished
            ):
                if last_status is not None and last_status.finished:
                    return 1 if last_status.job_failed else 0
                print(
                    f"master {args.master_addr} unreachable "
                    f"({e.code().name})",
                    flush=True,
                )
                return 2
            if unreachable_since is None:
                unreachable_since = now
                retry_delay = min(args.interval, 1.0)
                banner = "master unreachable; reconnecting..."
                print(banner, flush=True)
            if now - unreachable_since > patience:
                print(
                    f"master {args.master_addr} unreachable "
                    f"({e.code().name})",
                    flush=True,
                )
                return 2
            time.sleep(retry_delay)
            retry_delay = min(retry_delay * 1.5, 10.0)
            # A channel that connect-attempted the unbound port of a
            # restarting master can stay wedged in UNAVAILABLE after the
            # port returns — probe, and greet the new master on a FRESH
            # channel (same recovery the workers use).
            if rpc.wait_channel_ready(
                args.master_addr, min(retry_delay, 1.0)
            ):
                channel.close()
                channel = rpc.build_channel(
                    args.master_addr, ready_timeout=0
                )
                stub = rpc.Stub(channel, rpc.MASTER_SERVICE)
            continue
        inc = getattr(status, "master_incarnation", 0)
        if incarnation and inc > incarnation:
            banner = (
                f"master restarting (incarnation {incarnation}->{inc})"
            )
        elif unreachable_since is None:
            banner = ""
        if inc:
            incarnation = inc
        last_status = status
        summary = {}
        if status.metrics_port:
            try:
                summary = dashboard.fetch_summary(
                    host, status.metrics_port
                )
            except (OSError, ValueError):
                summary = {}  # aggregator still warming up
        if getattr(args, "json", False) and args.once:
            # Machine-readable once-mode: the raw /api/summary snapshot
            # (datapath block included) as one JSON object — the CI
            # artifact form of the frame below.
            import json as _json

            print(_json.dumps(summary, sort_keys=True), flush=True)
            return 1 if status.job_failed else 0
        frame = dashboard.render(
            summary, status, top=getattr(args, "top", 0)
        )
        if banner:
            frame = banner + "\n" + frame
        if args.once:
            print(frame, flush=True)
            return 1 if status.job_failed else 0
        print(dashboard.CLEAR + frame, flush=True)
        if status.finished or status.job_failed:
            return 1 if status.job_failed else 0
        if iterations and polls >= iterations:
            return _bounded_exit()  # no dead sleep after the last frame
        time.sleep(args.interval)


def _top(args):
    """Live job monitor: poll the master's job-status RPC and print one
    status line per interval (the in-job analog of the reference's
    pod-polling job monitor, k8s_job_monitor.py:94-207; throughput is
    derived by diffing records_done between polls). --watch renders the
    full dashboard instead of one-line updates."""
    import time

    from elasticdl_tpu.common import knobs, rpc
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    import grpc

    if getattr(args, "watch", False):
        args.once = False
        return _dash(args)
    channel = rpc.build_channel(args.master_addr)
    stub = rpc.Stub(channel, rpc.MASTER_SERVICE)
    prev_records, prev_ts = None, None
    first_records, first_ts = None, None
    last_status = None
    patience = knobs.get_float("ELASTICDL_MASTER_PATIENCE_SECONDS")
    unreachable_since = None
    retry_delay = 0.0
    incarnation = 0
    for _ in range(args.iterations) if args.iterations else iter(int, 1):
        try:
            status = stub.get_job_status(pb.GetJobStatusRequest())
        except grpc.RpcError as e:
            # The master stops its server as soon as the job ends, so an
            # UNAVAILABLE between polls against a FINISHED job means
            # "over", not an error. Mid-job it usually means the master
            # is restarting (journal replay): ride the same patience
            # window the workers do, with backoff, instead of giving up
            # three polls in.
            now = time.time()
            if last_status is not None and last_status.finished:
                print(
                    _top_summary_line(
                        last_status, first_records, first_ts, now
                    ),
                    flush=True,
                )
                return 1 if last_status.job_failed else 0
            if unreachable_since is None:
                unreachable_since = now
                retry_delay = min(args.interval, 1.0)
                print(
                    f"master {args.master_addr} unreachable "
                    f"({e.code().name}); retrying for up to "
                    f"{patience:.0f}s",
                    flush=True,
                )
            if now - unreachable_since > patience:
                if last_status is not None:
                    # Lost the master mid-job for good: distinct exit
                    # code — a dead master and a finished job must not
                    # look alike to CI.
                    print(
                        f"master {args.master_addr} gone mid-job "
                        f"(last: epoch {last_status.epoch}, "
                        f"v{last_status.model_version}, "
                        f"records={last_status.records_done})",
                        flush=True,
                    )
                else:
                    print(
                        f"master {args.master_addr} unreachable "
                        f"({e.code().name})",
                        flush=True,
                    )
                return 2
            time.sleep(retry_delay)
            retry_delay = min(retry_delay * 1.5, 10.0)
            # Same wedged-channel recovery as _dash: a restarted master
            # needs a fresh channel, built only once it accepts TCP.
            if rpc.wait_channel_ready(
                args.master_addr, min(retry_delay, 1.0)
            ):
                channel.close()
                channel = rpc.build_channel(
                    args.master_addr, ready_timeout=0
                )
                stub = rpc.Stub(channel, rpc.MASTER_SERVICE)
            continue
        unreachable_since = None
        inc = getattr(status, "master_incarnation", 0)
        if incarnation and inc > incarnation:
            print(
                f"master restarting (incarnation {incarnation}->{inc})",
                flush=True,
            )
        if inc:
            incarnation = inc
        if first_ts is None:
            first_records, first_ts = status.records_done, time.time()
        if last_status is None and status.metrics_port:
            # One-time pointer at the master's Prometheus endpoint (same
            # host as the gRPC addr, different port).
            host = args.master_addr.rsplit(":", 1)[0]
            print(
                f"metrics: http://{host}:{status.metrics_port}/metrics",
                flush=True,
            )
        last_status = status
        now = time.time()
        rate = ""
        if prev_records is not None and now > prev_ts:
            rps = (status.records_done - prev_records) / (now - prev_ts)
            rate = f" {rps:8.1f} rec/s"
        prev_records, prev_ts = status.records_done, now
        evals = ""
        if status.last_eval_metrics:
            shown = ", ".join(
                f"{k}={v:.4f}"
                for k, v in sorted(status.last_eval_metrics.items())
            )
            evals = f" eval@v{status.last_eval_version}[{shown}]"
        # Elasticity counters from the observability plane: shown only
        # once nonzero so a healthy job's line stays short.
        elastic = ""
        if status.relaunches:
            elastic += f" relaunches={status.relaunches}"
        if status.tasks_recovered:
            elastic += f" recovered={status.tasks_recovered}"
        if status.tasks_abandoned:
            elastic += f" abandoned={status.tasks_abandoned}"
        if status.membership_epoch:
            elastic += f" mepoch={status.membership_epoch}"
        if status.stragglers:
            elastic += f" stragglers={','.join(status.stragglers)}"
        if status.alerts_fired:
            elastic += f" alerts={status.alerts_fired}"
        if status.policy_actions:
            elastic += f" policy={status.policy_actions}"
        if status.policy_blacklisted:
            elastic += (
                f" blacklist={','.join(status.policy_blacklisted)}"
            )
        if status.backup_tasks_inflight:
            elastic += f" backups={status.backup_tasks_inflight}"
        if status.backup_wins:
            elastic += f" backup_wins={status.backup_wins}"
        print(
            f"epoch {status.epoch}/{status.num_epochs} "
            f"v{status.model_version} "
            f"tasks todo={status.todo_tasks} doing={status.doing_tasks} "
            f"workers={status.alive_workers} "
            f"records={status.records_done}{rate}{elastic}{evals}"
            + (" FAILED" if status.job_failed else "")
            + (" FINISHED" if status.finished else ""),
            flush=True,
        )
        if status.finished or status.job_failed:
            print(
                _top_summary_line(
                    status, first_records, first_ts, time.time()
                ),
                flush=True,
            )
            return 1 if status.job_failed else 0
        time.sleep(args.interval)
    # Iterations exhausted mid-job: a job last seen FAILED must still
    # exit nonzero (CI wires `edl top` as the job's oracle).
    if last_status is not None and last_status.job_failed:
        return 1
    return 0


def _profile(args):
    """On-demand deep profiling of a RUNNING job, plus the step-time
    attribution report.

    With --master_addr: ask the master's StartProfile RPC to fan a
    jax.profiler capture out to every role (captures land under the
    job's obs dir, profiles/<role>/) and print each role's capture
    summary. With --obs_dir (no capture): print the step-time
    attribution table tools/step_report.py builds from the traces,
    compile events, and phase spans already on disk. Both flags
    together capture first, then report."""
    import json as _json

    rc = 0
    if args.master_addr:
        import grpc

        from elasticdl_tpu.common import rpc
        from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

        stub = rpc.Stub(
            rpc.build_channel(args.master_addr), rpc.MASTER_SERVICE
        )
        try:
            # Explicit deadline derived from the capture length: the
            # static METHOD_POLICIES deadline (120s) cannot know how
            # long a capture THIS request asks for, and the master
            # blocks for roughly seconds + fan-out margin.
            resp = stub.start_profile(
                pb.StartProfileRequest(
                    seconds=args.seconds, role_prefix=args.role
                ),
                timeout=args.seconds + 90.0,
            )
        except grpc.RpcError as e:
            print(
                f"profile RPC failed: {e.code().name}", flush=True
            )
            return 2
        results = _json.loads(resp.results_json or "{}")
        print(f"captured {resp.captured}/{len(results)} roles:")
        for role in sorted(results):
            r = results[role]
            if "error" in r:
                print(f"  {role}: ERROR {r['error']}")
            else:
                print(
                    f"  {role}: {r.get('bytes', 0)} bytes in "
                    f"{len(r.get('files', []))} files -> {r.get('dir')}"
                )
        if resp.captured == 0:
            rc = 1
    if args.obs_dir:
        try:
            from tools import step_report
        except ImportError:  # tools/ directly on sys.path
            import step_report

        print(step_report.render_report(args.obs_dir))
    if not args.master_addr and not args.obs_dir:
        print("edl profile needs --master_addr and/or --obs_dir")
        return 2
    return rc


def _tensorboard(args):
    """Spawn TensorBoard over a job's metrics directory (reference
    master/tensorboard_service.py:21-62 spawns the CLI the same way; the
    master here only writes event files — serving them is this separate,
    optional process)."""
    import shutil as _shutil
    import subprocess

    if _shutil.which("tensorboard") is None:
        logger.error(
            "tensorboard CLI not found; install tensorboard or point any "
            "TensorBoard at --logdir %s",
            args.metrics_dir,
        )
        return 1
    cmd = [
        "tensorboard",
        "--logdir",
        args.metrics_dir,
        "--port",
        str(args.port),
        "--bind_all",
    ]
    return subprocess.run(cmd).returncode


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    top = argparse.ArgumentParser(
        "edl", description="elastic TPU deep learning"
    )
    top.add_argument(
        "command",
        choices=["train", "evaluate", "predict", "zoo", "top", "dash",
                 "tensorboard", "profile"],
    )
    ns, rest = top.parse_known_args(argv)

    if ns.command == "profile":
        prof = argparse.ArgumentParser("edl profile")
        prof.add_argument(
            "--master_addr",
            default="",
            help="capture: fan a device-profile capture out through the "
            "master's StartProfile RPC",
        )
        prof.add_argument("--seconds", type=float, default=2.0)
        prof.add_argument(
            "--role",
            default="",
            help="only capture roles with this prefix (worker / ps / "
            "master); empty = all",
        )
        prof.add_argument(
            "--obs_dir",
            default="",
            help="report: print the step-time attribution table from "
            "this job obs dir",
        )
        return _profile(prof.parse_args(rest))

    if ns.command == "tensorboard":
        tb = argparse.ArgumentParser("edl tensorboard")
        tb.add_argument("--metrics_dir", required=True)
        tb.add_argument("--port", type=int, default=6006)
        return _tensorboard(tb.parse_args(rest))

    if ns.command == "dash":
        dash = argparse.ArgumentParser("edl dash")
        dash.add_argument("--master_addr", required=True)
        dash.add_argument("--interval", type=float, default=2.0)
        dash.add_argument(
            "--once",
            action="store_true",
            help="render one frame and exit (non-interactive/CI mode)",
        )
        dash.add_argument(
            "--json",
            action="store_true",
            help="with --once: print the raw /api/summary JSON instead "
            "of the rendered frame (CI artifact capture)",
        )
        dash.add_argument(
            "--iterations",
            type=int,
            default=0,
            help="stop after N frames (0 = until the job ends)",
        )
        dash.add_argument(
            "--top",
            type=int,
            default=10,
            help="cap worker/PS sections to the K worst rows "
            "(slowest workers, busiest shards); 0 shows every row",
        )
        return _dash(dash.parse_args(rest))

    if ns.command == "top":
        monitor = argparse.ArgumentParser("edl top")
        monitor.add_argument("--master_addr", required=True)
        monitor.add_argument("--interval", type=float, default=5.0)
        monitor.add_argument(
            "--iterations",
            type=int,
            default=0,
            help="stop after N polls (0 = until the job ends)",
        )
        monitor.add_argument(
            "--watch",
            action="store_true",
            help="render the live dashboard instead of one-line updates",
        )
        return _top(monitor.parse_args(rest))

    if ns.command == "zoo":
        zoo = argparse.ArgumentParser("edl zoo")
        sub = zoo.add_subparsers(dest="zoo_command", required=True)
        init_p = sub.add_parser("init")
        init_p.add_argument("--path", default=".")
        init_p.add_argument("--name", default="my_model")
        init_p.add_argument("--force", action="store_true")
        init_p.set_defaults(func=_zoo_init)
        list_p = sub.add_parser("list")
        list_p.set_defaults(func=_zoo_list)
        build_p = sub.add_parser("build")
        build_p.add_argument("--path", required=True)
        build_p.add_argument("--build_dir", default="./build")
        build_p.add_argument("--image", default="elasticdl_tpu:latest")
        build_p.add_argument(
            "--base_image", default="python:3.12-slim"
        )
        build_p.set_defaults(func=_zoo_build)
        push_p = sub.add_parser("push")
        push_p.add_argument("--image", required=True)
        push_p.add_argument(
            "--dry_run",
            action="store_true",
            help="print the push command instead of running it",
        )
        push_p.set_defaults(func=_zoo_push)
        zargs = zoo.parse_args(rest)
        return zargs.func(zargs)

    parser = _job_parser(ns.command)
    job_args = parser.parse_args(rest)
    # evaluate/predict are the train command with the matching data flags
    # (the reference routes them the same way, main.py:28-88).
    if ns.command == "evaluate" and not job_args.validation_data:
        parser.error("evaluate requires --validation_data")
    if ns.command == "predict" and not job_args.prediction_data:
        parser.error("predict requires --prediction_data")
    if ns.command in ("evaluate", "predict"):
        job_args.training_data = ""
    return _submit(job_args, rest)


if __name__ == "__main__":
    sys.exit(main())
