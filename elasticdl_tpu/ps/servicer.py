"""The Pserver gRPC servicer: async/sync gradient application over the store.

Reference counterparts: Go server (/root/reference/elasticdl/go/pkg/ps/
server.go:144-244) and the Python twin (elasticdl/python/ps/
servicer.py:33-288). Semantics kept:

- async mode: every push applies immediately; stale pushes (worker version <
  PS version) get their LR scaled down by the staleness when
  lr_staleness_modulation is on (Python twin servicer.py:148-154).
- sync mode: pushes buffer until `grads_to_wait` arrive, then dense grads
  average / sparse grads merge and apply once; pushes older than
  `sync_version_tolerance` are rejected (accepted=False → worker re-pulls
  and recomputes, servicer.py:166-236).
- every apply bumps `version`; every `checkpoint_steps` versions the shard
  checkpoints itself; every `report_version_steps` it reports to the master
  (the version-triggered-evaluation trigger, go server.go:196-200).
"""

import threading
import time

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.metrics import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.ps.optimizer import PSOptimizer
from elasticdl_tpu.ps.parameters import Parameters

logger = get_logger("ps.servicer")

DEFAULT_REPORT_VERSION_STEPS = 100

# Process-global so N in-process shards aggregate (one registry per OS
# process; real deployments run one shard per process).
_REG = default_registry()
# Byte counters carry the shard id so the master's telemetry aggregator
# can expose per-shard load imbalance even when several in-process shards
# share one registry (tests) — and so one scrape config covers all shards.
_PUSH_BYTES = _REG.counter(
    "edl_ps_push_bytes_total",
    "Gradient push request bytes received, by shard",
    labelnames=("shard",),
)
_PULL_BYTES = _REG.counter(
    "edl_ps_pull_bytes_total",
    "Parameter/embedding pull response bytes sent",
    labelnames=("rpc", "shard"),
)
_PUSHES = _REG.counter(
    "edl_ps_push_total",
    "Gradient pushes by outcome",
    labelnames=("outcome",),
)
_PS_VERSION = _REG.gauge(
    "edl_ps_model_version", "Latest model version applied by this PS"
)
_APPLY_SECONDS = _REG.histogram(
    "edl_ps_apply_seconds", "Optimizer apply latency per push"
)


class PserverServicer:
    def __init__(
        self,
        parameters: Parameters,
        optimizer: PSOptimizer,
        use_async=True,
        grads_to_wait=1,
        sync_version_tolerance=0,
        sync_window_timeout=30.0,
        lr_staleness_modulation=False,
        checkpoint_saver=None,
        checkpoint_steps=0,
        master_client=None,
        report_version_steps=DEFAULT_REPORT_VERSION_STEPS,
        shard_id=0,
    ):
        self._params = parameters
        self._opt = optimizer
        self._shard = str(shard_id)
        self._use_async = use_async
        self._grads_to_wait = grads_to_wait
        self._sync_version_tolerance = sync_version_tolerance
        self._lr_staleness_modulation = lr_staleness_modulation
        self._checkpoint_saver = checkpoint_saver
        self._checkpoint_steps = checkpoint_steps
        self._mc = master_client
        self._report_version_steps = report_version_steps
        self._version_lock = threading.Lock()
        # sync-mode accumulation state (guarded by _version_lock)
        self._grad_sum = {}  # dense name -> np array
        self._grad_n = 0
        self._sparse_acc = {}  # table name -> ([values...], [ids...])
        # Quorum counts DISTINCT workers, not raw pushes: one fast worker
        # pushing twice in a window must not satisfy grads_to_wait alone
        # (its second push still contributes to the average). Anonymous
        # sync pushes are rejected outright — counting each as a fresh
        # worker (the reference's coarse push counter,
        # python/ps/servicer.py:166-236) would let an old client silently
        # weaken the quorum back to raw push counting. Liveness escape
        # hatch: if the quorum hasn't filled within sync_window_timeout of
        # the window's first push (survivors of an elastic shrink keep
        # re-pushing), the next push applies whatever has accumulated
        # rather than hanging the job forever.
        self._sync_window_timeout = sync_window_timeout
        self._push_workers = set()
        self._window_start = None
        # Chunked packed pushes mid-reassembly: (worker, push_id) ->
        # _PendingPush. Entries whose worker died mid-push are GC'd by
        # age on the next packed push (CHUNK_GC_SECONDS).
        self._chunk_lock = threading.Lock()
        self._pending_chunks = {}

    # ---------- rpc methods (names match rpc.PSERVER_SERVICE) ----------

    def push_model(self, request, context):
        did_init = self._params.init_from_model_pb(request)
        if did_init:
            logger.info(
                "Model initialized from worker push: %d dense, %d tables, "
                "version %d",
                len(self._params.dense),
                len(self._params.embedding_tables),
                self._params.version,
            )
        return pb.Empty()

    def push_embedding_table_infos(self, request, context):
        with self._params.init_lock:
            self._params.init_embedding_infos(
                request.embedding_table_infos
            )
        return pb.Empty()

    def pull_dense_parameters(self, request, context):
        if not self._params.initialized:
            return pb.PullDenseParametersResponse(initialized=False)
        # Under async SGD workers poll with their current version and only
        # need deltas; we return everything newer-or-equal (the reference
        # returns all when version lags, go server.go:144-160).
        res = pb.PullDenseParametersResponse(
            initialized=True, version=self._params.version
        )
        if request.version < self._params.version or request.version == 0:
            for name in sorted(self._params.dense):
                res.dense_parameters.append(
                    tensor_utils.ndarray_to_tensor_pb(
                        self._params.dense[name], name
                    )
                )
        _PULL_BYTES.labels(rpc="pull_dense_parameters", shard=self._shard).inc(
            res.ByteSize()
        )
        return res

    def pull_embedding_vectors(self, request, context):
        table = self._params.embedding_tables.get(request.name)
        if table is None:
            raise ValueError(f"unknown embedding table {request.name!r}")
        if request.ids_bytes:
            ids = tensor_utils.ids_from_bytes(request.ids_bytes)
        elif request.ids:
            ids = np.asarray(request.ids, dtype=np.int64)
        else:
            return pb.Tensor(name=request.name)
        values = table.lookup(ids)
        if request.value_dtype == pb.DT_BFLOAT16:
            values = values.astype(tensor_utils.bfloat16)
        res = tensor_utils.ndarray_to_tensor_pb(values, request.name)
        _PULL_BYTES.labels(rpc="pull_embedding_vectors", shard=self._shard).inc(
            res.ByteSize()
        )
        return res

    def pull_embedding_table(self, request, context):
        """One page of a table's materialized rows — the export
        reverse-swap (model export stuffs these back into a plain
        embedding param). Paged so CTR-scale tables fit the message cap."""
        table = self._params.embedding_tables.get(request.name)
        if table is None:
            raise ValueError(f"unknown embedding table {request.name!r}")
        ids, values = table.export_rows(
            start=request.start_row,
            count=request.max_rows or None,
        )
        res = tensor_utils.ndarray_to_indexed_slices_pb(
            values, ids, request.name
        )
        _PULL_BYTES.labels(rpc="pull_embedding_table", shard=self._shard).inc(
            res.ByteSize()
        )
        return res

    def push_gradients(self, request, context):
        _PUSH_BYTES.labels(shard=self._shard).inc(request.ByteSize())
        dense, sparse = self._decode_model_pb(request.gradients)
        return self._push_decoded(
            dense,
            sparse,
            version=request.gradients.version,
            worker_id_plus_one=request.worker_id_plus_one,
            batch_size=request.batch_size,
        )

    def push_gradients_packed(self, request, context):
        """Out-of-band push: spans decode as numpy views into the received
        payload bytes — nothing is copied until the optimizer apply (int8
        spans dequantize at decode, which IS their apply-side
        materialization). Multi-chunk pushes buffer until every payload
        byte arrived, then apply once."""
        _PUSH_BYTES.labels(shard=self._shard).inc(request.ByteSize())
        # Age-GC abandoned reassemblies on EVERY packed push: a worker
        # that died mid-chunked-push must not pin its payload buffer
        # until another CHUNKED push happens to arrive (single-chunk
        # pushes are the common case). The sweep is O(pending), which
        # is almost always zero.
        self._gc_pending_chunks()
        if request.chunk_count > 1:
            assembled = self._absorb_chunk(request)
            if assembled is None:
                # Buffered; the reassembly-completing chunk reports the
                # apply. accepted=True: the chunk itself was taken.
                return pb.PushGradientsResponse(
                    accepted=True, version=self._params.version
                )
            header, payload = assembled
        else:
            header, payload = request, request.payload
            if len(payload) != request.payload_total_bytes:
                raise ValueError(
                    f"packed push payload {len(payload)} bytes != "
                    f"declared {request.payload_total_bytes} (truncated)"
                )
        dense, sparse = self._decode_packed(header, payload)
        return self._push_decoded(
            dense,
            sparse,
            version=header.version,
            worker_id_plus_one=header.worker_id_plus_one,
            batch_size=header.batch_size,
        )

    # ---------- packed decode / chunk reassembly ----------

    def _decode_model_pb(self, model_pb):
        """Legacy per-tensor proto model -> ({name: grad}, {table:
        (values, ids)}) — the same decoded shape the packed path
        produces, so both wire formats share one apply path."""
        dense = {
            t.name: tensor_utils.tensor_pb_to_ndarray(t)
            for t in model_pb.dense_parameters
        }
        sparse = {
            name: tensor_utils.indexed_slices_pb_to_ndarrays(slices)
            for name, slices in model_pb.embedding_tables.items()
        }
        return dense, sparse

    def _decode_packed(self, header, payload):
        dense = {
            span.name: tensor_utils.unpack_tensor_span(span, payload)
            for span in header.dense
        }
        sparse = {
            span.values.name: tensor_utils.unpack_slices_span(
                span, payload
            )
            for span in header.sparse
        }
        return dense, sparse

    CHUNK_GC_SECONDS = 120.0

    def _gc_pending_chunks(self):
        """Drop partial reassemblies older than CHUNK_GC_SECONDS (their
        worker died mid-push); called on every packed push."""
        now = time.monotonic()
        with self._chunk_lock:
            for k, entry in list(self._pending_chunks.items()):
                if now - entry["created"] > self.CHUNK_GC_SECONDS:
                    del self._pending_chunks[k]

    def _absorb_chunk(self, request):
        """Buffer one chunk; returns (header, payload) once the push is
        complete, else None. Chunks may arrive in any order (each carries
        its own payload_offset); headers ride chunk 0. Duplicate chunk
        indexes (an UNAVAILABLE-retried sub-request whose first attempt
        landed) are ignored rather than double-counted."""
        key = (request.worker_id_plus_one, request.push_id)
        now = time.monotonic()
        with self._chunk_lock:
            entry = self._pending_chunks.get(key)
            if entry is None:
                entry = self._pending_chunks[key] = {
                    "buf": bytearray(request.payload_total_bytes),
                    "received": 0,
                    "seen": set(),
                    "header": None,
                    "created": now,
                }
            if request.chunk_index == 0:
                entry["header"] = request
            if request.chunk_index not in entry["seen"]:
                entry["seen"].add(request.chunk_index)
                start = request.payload_offset
                end = start + len(request.payload)
                if end > len(entry["buf"]):
                    del self._pending_chunks[key]
                    raise ValueError(
                        f"packed chunk [{start}, {end}) outside the "
                        f"declared {len(entry['buf'])}-byte payload"
                    )
                entry["buf"][start:end] = request.payload
                entry["received"] += len(request.payload)
            complete = (
                entry["header"] is not None
                and len(entry["seen"]) == request.chunk_count
            )
            if not complete:
                return None
            del self._pending_chunks[key]
        if entry["received"] != len(entry["buf"]):
            raise ValueError(
                f"packed push reassembled {entry['received']} of "
                f"{len(entry['buf'])} payload bytes (overlapping or "
                f"truncated chunks)"
            )
        # The bytearray itself backs the decoded views (no final copy);
        # it just left the pending map, so nothing mutates it anymore.
        return entry["header"], entry["buf"]

    # ---------- shared push entry ----------

    def _push_decoded(self, dense, sparse, version, worker_id_plus_one,
                      batch_size):
        if self._use_async:
            res = self._push_async(dense, sparse, version, batch_size)
        else:
            res = self._push_sync(
                dense, sparse, version, worker_id_plus_one, batch_size
            )
        _PUSHES.labels(
            outcome="accepted" if res.accepted else "rejected"
        ).inc()
        return res

    # ---------- async path ----------

    def _push_async(self, dense, sparse, version, batch_size):
        staleness = max(1, self._params.version - version)
        if self._lr_staleness_modulation:
            self._opt.lr_modulator.set_multiplier(1.0 / staleness)
        # Applies serialize on the version lock: ctypes releases the GIL, so
        # unsynchronized concurrent native updates of one buffer would race
        # (the reference Go server likewise applies under its mutex,
        # go/pkg/ps/server.go:67-68,176-206).
        with self._version_lock:
            start = time.perf_counter()
            with tracing.span("ps_apply_async"):
                self._apply_decoded(dense, sparse)
            apply_seconds = time.perf_counter() - start
            _APPLY_SECONDS.observe(apply_seconds)
            self._params.total_records += batch_size
            self._params.version += 1
            version = self._params.version
            snapshot = self._snapshot_if_due(version)
        _PS_VERSION.set(version)
        self._post_apply(version, snapshot)
        # apply_seconds lets the pushing worker split its RPC wait into
        # wire vs apply time (the microbench matrix's breakdown).
        return pb.PushGradientsResponse(
            accepted=True, version=version, apply_seconds=apply_seconds
        )

    # ---------- sync path ----------

    def _push_sync(self, dense, sparse, version, worker_id_plus_one,
                   batch_size):
        if worker_id_plus_one <= 0:
            raise ValueError(
                "sync-mode gradient pushes must carry a worker_id; the "
                "distinct-worker quorum cannot count anonymous pushes"
            )
        with self._version_lock:
            if (
                version
                < self._params.version - self._sync_version_tolerance
            ):
                return pb.PushGradientsResponse(
                    accepted=False, version=self._params.version
                )
            for name, g in dense.items():
                if name in self._grad_sum:
                    # += upcasts a bf16 addend; the accumulator is f32.
                    self._grad_sum[name] += g
                else:
                    # Forced copy: packed-path grads are read-only views
                    # into the received payload; the accumulator must own
                    # a mutable f32 buffer.
                    self._grad_sum[name] = np.array(g, dtype=np.float32)
            for name, (values, ids) in sparse.items():
                # bf16 wire payloads accumulate in f32 (precision of the
                # merge must not depend on the wire dtype).
                values = values.astype(np.float32, copy=False)
                acc = self._sparse_acc.setdefault(name, ([], []))
                acc[0].append(values)
                acc[1].append(ids)
            self._grad_n += 1
            self._params.total_records += batch_size
            if self._window_start is None:
                self._window_start = time.monotonic()
            self._push_workers.add(worker_id_plus_one - 1)
            quorum = len(self._push_workers)
            window_expired = (
                time.monotonic() - self._window_start
                > self._sync_window_timeout
            )
            if quorum < self._grads_to_wait and not window_expired:
                return pb.PushGradientsResponse(
                    accepted=True, version=self._params.version
                )
            if window_expired and quorum < self._grads_to_wait:
                logger.warning(
                    "Sync window timed out with %d/%d workers; applying "
                    "%d buffered pushes",
                    quorum, self._grads_to_wait, self._grad_n,
                )
            # Quorum reached: average dense, merge sparse, apply once.
            apply_start = time.perf_counter()
            self._opt.begin_apply()
            try:
                for name, g in self._grad_sum.items():
                    self._opt.apply_dense(
                        name, self._params.dense[name], g / self._grad_n
                    )
                for name, (values_list, ids_list) in self._sparse_acc.items():
                    values, ids = tensor_utils.merge_indexed_slices(
                        values_list, ids_list
                    )
                    values /= self._grad_n
                    self._opt.apply_sparse(
                        self._params.embedding_tables[name], ids, values
                    )
            finally:
                self._opt.end_apply()
            apply_seconds = time.perf_counter() - apply_start
            _APPLY_SECONDS.observe(apply_seconds)
            self._grad_sum.clear()
            self._sparse_acc.clear()
            self._grad_n = 0
            self._push_workers.clear()
            self._window_start = None
            self._params.version += 1
            version = self._params.version
            snapshot = self._snapshot_if_due(version)
        _PS_VERSION.set(version)
        self._post_apply(version, snapshot)
        # Only the quorum-completing push reports the apply cost (the
        # buffered ones above return without applying anything).
        return pb.PushGradientsResponse(
            accepted=True, version=version, apply_seconds=apply_seconds
        )

    # ---------- shared ----------

    def _apply_decoded(self, dense, sparse):
        # One optimizer step for the whole push: all params share the same
        # Adam bias-correction step (reference go/pkg/ps/optimizer.go:44).
        self._opt.begin_apply()
        try:
            for name, grad in dense.items():
                param = self._params.dense.get(name)
                if param is None:
                    raise ValueError(
                        f"gradient for unknown parameter {name!r}"
                    )
                self._opt.apply_dense(name, param, grad)
            for name, (values, ids) in sparse.items():
                table = self._params.embedding_tables.get(name)
                if table is None:
                    raise ValueError(f"gradient for unknown table {name!r}")
                self._opt.apply_sparse(table, ids, values)
        finally:
            self._opt.end_apply()

    def _snapshot_if_due(self, version):
        """Call under _version_lock. Serializes a consistent snapshot of the
        store when a checkpoint is due; concurrent pushes mutate the dense
        numpy arrays in place through GIL-releasing native kernels, so
        snapshotting outside the lock could serialize torn, mixed-version
        tensors (the reference saves inside the version lock,
        python/ps/servicer.py:157-159). The (slow) file write itself happens
        after the lock is released, in _post_apply."""
        if (
            self._checkpoint_saver is not None
            and self._checkpoint_steps
            and version % self._checkpoint_steps == 0
        ):
            try:
                return self._checkpoint_saver.snapshot(version, self._params)
            except Exception:
                logger.error(
                    "Checkpoint snapshot at version %d failed",
                    version, exc_info=True,
                )
        return None

    def _post_apply(self, version, snapshot=None):
        if snapshot is not None:
            try:
                self._checkpoint_saver.save_snapshot(version, snapshot)
            except Exception:
                logger.error(
                    "Checkpoint at version %d failed", version, exc_info=True
                )
        if (
            self._mc is not None
            and version % self._report_version_steps == 0
        ):
            try:
                self._mc.report_version(version)
            except Exception:
                logger.warning(
                    "report_version(%d) to master failed", version
                )
