"""PS-side optimizer: applies gradients to the host-resident store through
the native C++ kernels.

Reference counterparts: the Go optimizer interface with its
Dense/Sparse/Indexed kernel triples (/root/reference/elasticdl/go/pkg/ps/
optimizer.go:43-73,329-390) and the Python OptimizerWrapper that injected
temp tf.Variables into Keras optimizer slots for embedding rows
(elasticdl/python/ps/optimizer_wrapper.py:70-351). The slab design makes the
wrapper dance unnecessary: optimizer slots ARE companion slabs with the same
row mapping, so sparse updates call one indexed kernel — no variable
materialization, no slot injection, no writeback.

A thread-safe LR modulator supports the staleness-based learning-rate
scaling of async SGD (reference python/ps/learning_rate_modulator.py:17-73).
"""

import ctypes
import threading

import numpy as np

from elasticdl_tpu import native
from elasticdl_tpu.ops.optimizers import OptimizerSpec

_NULL_F32 = ctypes.POINTER(ctypes.c_float)()


class LearningRateModulator:
    """Per-call LR multiplier, set by the servicer thread handling a push
    (thread-local, so concurrent pushes with different staleness don't race).
    """

    def __init__(self):
        self._local = threading.local()

    def set_multiplier(self, m):
        self._local.multiplier = m

    def get(self, base_lr):
        return base_lr * getattr(self._local, "multiplier", 1.0)


class PSOptimizer:
    """Applies dense and sparse (indexed) gradients in place.

    Dense state lives in `self._dense_slots[param_name][slot]` numpy arrays;
    sparse state lives as companion slabs inside each EmbeddingTable.
    """

    # slot name -> initial value, per optimizer family
    _SLOTS = {
        "sgd": {},
        "momentum": {"velocity": 0.0},
        "adam": {"m": 0.0, "v": 0.0},
        "adagrad": {"accumulator": None},  # filled from hyperparam
    }

    def __init__(self, spec: OptimizerSpec):
        self._spec = spec
        self._h = spec.hyperparams
        self._name = spec.name
        self._dense_slots = {}
        self._step = 0  # global step for Adam bias correction
        self._apply_step = None  # step shared by all params of one push
        self._step_lock = threading.Lock()
        self.lr_modulator = LearningRateModulator()
        slots = dict(self._SLOTS[self._name])
        if self._name == "adagrad":
            slots["accumulator"] = self._h["initial_accumulator_value"]
        if self._name == "adam" and self._h["amsgrad"]:
            slots["max_sq"] = 0.0
        self._slot_inits = slots

    @property
    def spec(self):
        return self._spec

    def begin_apply(self):
        """Advance the global step once per gradient push; every parameter
        applied in that push shares it (the reference increments once per
        push with all params sharing the step, go/pkg/ps/optimizer.go:44).
        Callers (the servicer) hold the version lock across the whole push,
        so a plain attribute is race-free."""
        with self._step_lock:
            self._step += 1
            self._apply_step = self._step
            return self._apply_step

    def end_apply(self):
        """Close the push opened by begin_apply; standalone apply_* calls
        (unit tests) return to bump-per-call stepping. Takes the step
        lock like begin_apply: without it, a concurrent push's shared
        step can be cleared mid-apply, silently degrading that push to
        bump-per-call stepping."""
        with self._step_lock:
            self._apply_step = None

    def _cur_step(self):
        if self._apply_step is not None:
            return self._apply_step
        # Standalone apply_* call without begin_apply (unit tests): keep the
        # old bump-per-call behavior.
        with self._step_lock:
            self._step += 1
            return self._step

    def _lr(self):
        return self.lr_modulator.get(self._h["learning_rate"])

    # ---------- dense ----------

    def _dense_slot(self, name, slot, shape):
        slots = self._dense_slots.setdefault(name, {})
        if slot not in slots:
            slots[slot] = np.full(
                shape, self._slot_inits[slot], dtype=np.float32
            )
        return slots[slot]

    def apply_dense(self, name, param, grad):
        """In-place update of `param` (numpy float32) with `grad`."""
        grad = np.ascontiguousarray(grad, dtype=np.float32)
        if grad.shape != param.shape:
            raise ValueError(
                f"gradient shape {grad.shape} != param shape "
                f"{param.shape} for {name!r}"
            )
        lr = self._lr()
        n = param.size
        lib = native.lib()
        if lib is None:
            return self._apply_dense_numpy(name, param, grad, lr)
        g, p = native._f32p(grad), native._f32p(param)
        if self._name == "sgd":
            lib.edl_sgd(g, p, lr, n)
        elif self._name == "momentum":
            vel = self._dense_slot(name, "velocity", param.shape)
            lib.edl_momentum(
                g, p, native._f32p(vel), lr, self._h["momentum"],
                int(self._h["nesterov"]), n,
            )
        elif self._name == "adam":
            m = self._dense_slot(name, "m", param.shape)
            v = self._dense_slot(name, "v", param.shape)
            ms = (
                native._f32p(self._dense_slot(name, "max_sq", param.shape))
                if self._h["amsgrad"] else _NULL_F32
            )
            lib.edl_adam(
                g, p, native._f32p(m), native._f32p(v), ms, lr,
                self._cur_step(), self._h["beta_1"], self._h["beta_2"],
                self._h["epsilon"], n,
            )
        elif self._name == "adagrad":
            accum = self._dense_slot(name, "accumulator", param.shape)
            lib.edl_adagrad(
                g, p, native._f32p(accum), lr, self._h["epsilon"], n
            )
        else:
            raise AssertionError(self._name)

    # ---------- sparse (embedding tables) ----------

    def apply_sparse(self, table, ids, grads):
        """Indexed update of embedding `table` rows for `ids` with
        [len(ids), dim] `grads`. Ids are deduplicated by the caller
        (ps client merges before pushing; servicer merges in sync mode)."""
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        ids = np.asarray(ids, dtype=np.int64)
        lr = self._lr()
        lib = native.lib()
        with table.lock:
            rows = table.rows_for_ids(ids)
            if lib is None:
                return self._apply_sparse_numpy(table, rows, grads, lr)
            k, dim = grads.shape
            g, r = native._f32p(grads), native._i64p(rows)
            slab = native._f32p(table.slab)
            if self._name == "sgd":
                lib.edl_sgd_indexed(g, r, k, dim, slab, lr)
            elif self._name == "momentum":
                vel = table.create_slot("velocity", 0.0)
                lib.edl_momentum_indexed(
                    g, r, k, dim, slab, native._f32p(vel), lr,
                    self._h["momentum"], int(self._h["nesterov"]),
                )
            elif self._name == "adam":
                m = table.create_slot("m", 0.0)
                v = table.create_slot("v", 0.0)
                ms = (
                    native._f32p(table.create_slot("max_sq", 0.0))
                    if self._h["amsgrad"] else _NULL_F32
                )
                lib.edl_adam_indexed(
                    g, r, k, dim, slab, native._f32p(m), native._f32p(v),
                    ms, lr, self._cur_step(), self._h["beta_1"],
                    self._h["beta_2"], self._h["epsilon"],
                )
            elif self._name == "adagrad":
                accum = table.create_slot(
                    "accumulator", self._h["initial_accumulator_value"]
                )
                lib.edl_adagrad_indexed(
                    g, r, k, dim, slab, native._f32p(accum), lr,
                    self._h["epsilon"],
                )
            else:
                raise AssertionError(self._name)

    # ---------- numpy fallbacks (EDL_NO_NATIVE=1 or no toolchain) ----------

    def _apply_dense_numpy(self, name, param, grad, lr):
        step = self._cur_step() if self._name == "adam" else 0
        self._numpy_rule(
            param.reshape(-1), grad.reshape(-1), lr, step,
            lambda slot, init: self._dense_slot(
                name, slot, param.shape
            ).reshape(-1),
        )

    def _apply_sparse_numpy(self, table, rows, grads, lr):
        # One global Adam step per push, matching the native indexed kernel.
        step = self._cur_step() if self._name == "adam" else 0
        for j, row in enumerate(rows):
            self._numpy_rule(
                table.slab[row], grads[j], lr, step,
                lambda slot, init: table.create_slot(slot, init)[row],
            )

    def _numpy_rule(self, p, g, lr, step, slot_of):
        h = self._h
        if self._name == "sgd":
            p -= lr * g
        elif self._name == "momentum":
            vel = slot_of("velocity", 0.0)
            vel *= h["momentum"]
            vel += g
            p -= lr * (g + h["momentum"] * vel) if h["nesterov"] else lr * vel
        elif self._name == "adam":
            m, v = slot_of("m", 0.0), slot_of("v", 0.0)
            m *= h["beta_1"]
            m += (1 - h["beta_1"]) * g
            v *= h["beta_2"]
            v += (1 - h["beta_2"]) * g * g
            lr_t = lr * np.sqrt(1 - h["beta_2"] ** step) / (
                1 - h["beta_1"] ** step
            )
            if h["amsgrad"]:
                ms = slot_of("max_sq", 0.0)
                np.maximum(ms, v, out=ms)
                p -= lr_t * m / (np.sqrt(ms) + h["epsilon"])
            else:
                p -= lr_t * m / (np.sqrt(v) + h["epsilon"])
        elif self._name == "adagrad":
            accum = slot_of(
                "accumulator", h["initial_accumulator_value"]
            )
            accum += g * g
            p -= lr * g / (np.sqrt(accum) + h["epsilon"])
        else:
            raise AssertionError(self._name)
