"""Row-initializer library for PS-resident embedding tables.

Reference counterpart: /root/reference/elasticdl/go/pkg/common/
initializer.go (Zero/Constant/RandomUniform/RandomNorm/TruncatedNormal).
Initializers are named by a spec string carried in EmbeddingTableInfo —
either a bare name ("uniform", "normal", "truncated_normal", "zeros") or a
parameterized form ("uniform(-0.05,0.05)", "normal(0,0.01)",
"constant(0.3)"). Each call fills one row deterministically from a per-row
seed so a resharded restore that re-initializes unseen ids stays
reproducible across PS replacements.
"""

import re

import numpy as np

_SPEC_RE = re.compile(r"^\s*([a-zA-Z_]+)\s*(?:\(([^)]*)\))?\s*$")

DEFAULT_UNIFORM_LOW, DEFAULT_UNIFORM_HIGH = -0.05, 0.05
DEFAULT_NORMAL_MEAN, DEFAULT_NORMAL_STD = 0.0, 0.05


def parse_initializer_spec(spec):
    """'name' or 'name(a,b,...)' -> (name, [float args])."""
    m = _SPEC_RE.match(spec or "uniform")
    if not m:
        raise ValueError(f"bad initializer spec {spec!r}")
    name = m.group(1).lower()
    args = []
    if m.group(2):
        args = [float(a) for a in m.group(2).split(",") if a.strip()]
    return name, args


def _truncated_normal(rng, mean, std, n):
    """Resample values outside mean +/- 2*std (the usual truncation rule the
    reference's TruncatedNormal implements via rejection)."""
    out = rng.normal(mean, std, n)
    bad = np.abs(out - mean) > 2.0 * std
    while bad.any():
        out[bad] = rng.normal(mean, std, int(bad.sum()))
        bad = np.abs(out - mean) > 2.0 * std
    return out


def resolve_native_init(spec):
    """spec string -> a flat descriptor the native bulk-init kernels
    understand, or None when only the numpy closure can produce it.

    ("uniform", low, high) | ("normal", mean, std, truncated) |
    ("constant", value) | ("zeros",)
    """
    name, args = parse_initializer_spec(spec)
    if name in ("zero", "zeros"):
        return ("zeros",)
    if name == "constant":
        return ("constant", args[0] if args else 0.0)
    if name in ("uniform", "random_uniform"):
        low = args[0] if args else DEFAULT_UNIFORM_LOW
        high = args[1] if len(args) > 1 else DEFAULT_UNIFORM_HIGH
        return ("uniform", low, high)
    if name in ("normal", "random_normal", "truncated_normal"):
        mean = args[0] if args else DEFAULT_NORMAL_MEAN
        std = args[1] if len(args) > 1 else DEFAULT_NORMAL_STD
        return ("normal", mean, std, name == "truncated_normal")
    return None


def make_row_initializer(spec, dim, dtype=np.float32):
    """spec string -> fn(dst_row, seed) filling one [dim] row in place.

    Returns (fn, uniform_range): uniform_range is the resolved (low, high)
    for uniform specs and None otherwise. (The native bulk-init path
    resolves specs through resolve_native_init instead; fn is the
    pure-numpy fallback stream.)
    """
    name, args = parse_initializer_spec(spec)
    if name in ("zero", "zeros"):
        def init(dst, seed):
            dst[:] = 0.0
        return init, None
    if name == "constant":
        value = args[0] if args else 0.0

        def init(dst, seed):
            dst[:] = value
        return init, None
    if name == "uniform" or name == "random_uniform":
        low = args[0] if args else DEFAULT_UNIFORM_LOW
        high = args[1] if len(args) > 1 else DEFAULT_UNIFORM_HIGH

        def init(dst, seed):
            rng = np.random.default_rng(seed)
            dst[:] = rng.uniform(low, high, dim).astype(dtype)
        return init, (low, high)
    if name in ("normal", "random_normal"):
        mean = args[0] if args else DEFAULT_NORMAL_MEAN
        std = args[1] if len(args) > 1 else DEFAULT_NORMAL_STD

        def init(dst, seed):
            rng = np.random.default_rng(seed)
            dst[:] = rng.normal(mean, std, dim).astype(dtype)
        return init, None
    if name == "truncated_normal":
        mean = args[0] if args else DEFAULT_NORMAL_MEAN
        std = args[1] if len(args) > 1 else DEFAULT_NORMAL_STD

        def init(dst, seed):
            rng = np.random.default_rng(seed)
            dst[:] = _truncated_normal(rng, mean, std, dim).astype(dtype)
        return init, None
    raise ValueError(f"unknown initializer {name!r} (spec {spec!r})")
