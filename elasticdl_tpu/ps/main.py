"""`python -m elasticdl_tpu.ps.main` — parameter-server process entrypoint
(reference /root/reference/elasticdl/go/cmd/elasticdl_ps/main.go:27-74).
Exits when the master stops answering (master-liveness loop)."""

import sys

import grpc

from elasticdl_tpu import observability
from elasticdl_tpu.common.args import ps_parser, validate_args
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.ps.parameter_server import ParameterServer
from elasticdl_tpu.worker.master_client import MasterClient

logger = get_logger("ps.main")


def main(argv=None):
    args = ps_parser().parse_args(argv)
    validate_args(args)
    obs = observability.setup(
        role=f"ps-{args.ps_id}", job=args.job_name
    )
    if args.model_zoo:
        sys.path.insert(0, args.model_zoo)
    # The optimizer spec comes from the model zoo module, like the reference
    # extracting -opt_type/-opt_args from the live optimizer
    # (master/master.py:443-476); here the spec IS the serialized form.
    spec = get_model_spec(args.model_def)
    mc = (
        MasterClient(args.master_addr, worker_id=-1)
        if args.master_addr
        else None
    )
    ps = ParameterServer(
        args.ps_id,
        args.num_ps,
        port=args.port,
        optimizer_spec=spec.build_optimizer_spec(),
        use_async=args.use_async,
        grads_to_wait=args.grads_to_wait,
        sync_version_tolerance=args.sync_version_tolerance,
        sync_window_timeout=args.sync_window_timeout,
        lr_staleness_modulation=args.lr_staleness_modulation,
        checkpoint_dir=args.checkpoint_dir or None,
        checkpoint_steps=args.checkpoint_steps,
        keep_checkpoint_max=args.keep_checkpoint_max,
        checkpoint_dir_for_init=args.checkpoint_dir_for_init or None,
        master_client=mc,
    )

    def master_alive():
        if mc is None:
            return True
        try:
            mc.report_version(ps.parameters.version)
            return True
        except grpc.RpcError:
            return False

    # Push-based telemetry (opt-in via ELASTICDL_TELEMETRY_PUSH_INTERVAL):
    # fresh pushes take this shard off the master's pull-scrape list.
    reporter = None
    if mc is not None:
        from elasticdl_tpu.observability.metrics import default_registry
        from elasticdl_tpu.observability.push import TelemetryReporter

        reporter = TelemetryReporter(
            mc.report_telemetry,
            default_registry(),
            role=f"ps-{args.ps_id}",
            seed=args.ps_id,
        ).start()

    ps.wait(master_liveness_check=master_alive, poll_seconds=10)
    ps.stop()
    if reporter is not None:
        reporter.close()
    obs.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
