"""Parameter-server strategy: host-resident sharded model store.

The reference implements this twice — a production Go gRPC server with C++
Eigen kernels (/root/reference/elasticdl/go/) and a Python twin
(elasticdl/python/ps/). Here there is ONE implementation: a Python gRPC
control surface over slab-backed numpy state whose hot math (optimizer
updates, embedding gather/scatter, lazy init) runs in the native C++ library
(elasticdl_tpu/native/kernels.cc) via ctypes — the same split as the
reference's Go-control/C++-math, without the duplicate servicer.
"""
