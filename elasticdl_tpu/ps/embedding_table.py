"""Slab-backed embedding table with lazy per-id initialization.

Reference counterpart: map[int64]*Tensor with an RWMutex and lazy uniform
[-0.05, 0.05] row init (/root/reference/elasticdl/go/pkg/common/
embedding_table.go:22-88) and the Python dict twin
(elasticdl/python/ps/embedding_table.py:23-136). Redesign: rows live in ONE
contiguous [capacity, dim] float32 slab that doubles on growth, with an
id -> row-index map on the side. That layout is what lets the native
optimizer kernels update k sparse rows in a single C call, and what makes
lookups a single gather instead of k dict hits.

The id -> row map itself is native too (native/idmap.cc): the reference's
production PS resolves ids in compiled Go/C++ (go/pkg/ps/server.go:176-206),
and the measured cost of doing it in Python was ~2.5 s per 320k-id pull —
one dict hit plus one ctypes init call per id. One C call now resolves the
whole id batch and bulk-initializes the fresh rows. Rows are assigned in
first-seen order, so row i <-> the i-th distinct id and a checkpoint page is
a contiguous slab slice.

Slot tables (Adam m/v, momentum velocity, ...) are companion slabs allocated
by the optimizer with the SAME row mapping, so one row-index array drives the
parameter and all its slots.
"""

import ctypes
import threading

import numpy as np

from elasticdl_tpu import native
from elasticdl_tpu.ps.initializers import (
    make_row_initializer,
    resolve_native_init,
)

DEFAULT_CAPACITY = 1024


class _NativeIdMap:
    """ctypes wrapper over the C open-addressing id->row map."""

    def __init__(self, lib, capacity):
        self._lib = lib
        self._handle = lib.edl_idmap_new(capacity)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and self._handle:
            lib.edl_idmap_free(self._handle)
            self._handle = None

    def __len__(self):
        return self._lib.edl_idmap_size(self._handle)

    def rows_for_ids(self, ids, create_missing):
        """-> (rows [n] int64, size_after). New rows are exactly
        [size_before, size_after) in first-seen order."""
        rows = np.empty(len(ids), dtype=np.int64)
        size_after = self._lib.edl_idmap_rows_for_ids(
            self._handle, native._i64p(ids), len(ids),
            1 if create_missing else 0, native._i64p(rows),
        )
        return rows, size_after

    def export_ids(self, start, count):
        out = np.empty(count, dtype=np.int64)
        self._lib.edl_idmap_export_ids(
            self._handle, start, count, native._i64p(out)
        )
        return out


class EmbeddingTable:
    def __init__(self, name, dim, initializer="uniform", dtype=np.float32,
                 capacity=DEFAULT_CAPACITY, seed=0):
        self.name = name
        self.dim = int(dim)
        self.initializer = initializer
        self.dtype = np.dtype(dtype)
        # Full initializer library (zeros/constant/uniform/normal/
        # truncated_normal, optionally parameterized — ps/initializers.py,
        # matching the reference's initializer.go). Specs the native bulk
        # kernels understand resolve to a flat descriptor; everything else
        # goes through the per-row numpy closure. Random init values are
        # deterministic per (seed, row) WITHIN a backend, but the native and
        # numpy generators are different streams — a restore that re-inits
        # unseen ids reproduces exactly only on a host with the same
        # backend available (true for uniform since round 1; normal joined
        # the native path in round 4).
        self._init_fn, _ = make_row_initializer(
            initializer, self.dim, self.dtype
        )
        self._native_init = resolve_native_init(initializer)
        self._lock = threading.RLock()
        self._slab = np.zeros((capacity, self.dim), dtype=self.dtype)
        lib = native.lib()
        if lib is not None:
            self._map = _NativeIdMap(lib, capacity)
            self._id_to_row = None
        else:
            self._map = None
            self._id_to_row = {}
        self._seed = seed
        # Companion slabs (optimizer slots) registered via create_slot;
        # grown in lockstep with the parameter slab.
        self._slots = {}
        self._slot_init_val = {}

    # ---------- row management ----------

    def __len__(self):
        with self._lock:
            if self._map is not None:
                return len(self._map)
            return len(self._id_to_row)

    @property
    def ids(self):
        with self._lock:
            if self._map is not None:
                return self._map.export_ids(0, len(self._map))
            return np.fromiter(
                self._id_to_row.keys(), dtype=np.int64,
                count=len(self._id_to_row),
            )

    def _grow_locked(self, min_capacity):
        capacity = self._slab.shape[0]
        while capacity < min_capacity:
            capacity *= 2
        grown = np.zeros((capacity, self.dim), dtype=self.dtype)
        grown[: self._slab.shape[0]] = self._slab
        self._slab = grown
        for slot_name, slab in self._slots.items():
            g = np.full((capacity, self.dim), self._slot_init_val[slot_name],
                        dtype=self.dtype)
            g[: slab.shape[0]] = slab
            self._slots[slot_name] = g

    def _row_seed(self, row):
        # Deterministic per-row seed so a resharded restore that re-inits
        # unseen ids stays reproducible.
        return (self._seed * 0x9E3779B1 + row + 1) & 0xFFFFFFFFFFFFFFFF

    def _init_rows_locked(self, start, n):
        """Initialize the fresh contiguous rows [start, start+n). Called
        under the lock, after any grow."""
        if n <= 0:
            return
        lib = native.lib()
        spec = self._native_init
        if lib is not None and self.dtype == np.float32 and spec is not None:
            if spec[0] == "zeros":
                return  # grown slab area is already zeroed
            if spec[0] == "constant":
                self._slab[start:start + n] = spec[1]
                return
            slab_p = native._f32p(self._slab)
            if spec[0] == "uniform":
                lib.edl_uniform_init_rows(
                    slab_p, self.dim, start, n, spec[1], spec[2],
                    ctypes.c_uint64(self._seed),
                )
                return
            if spec[0] == "normal":
                lib.edl_normal_init_rows(
                    slab_p, self.dim, start, n, spec[1], spec[2],
                    ctypes.c_uint64(self._seed), 1 if spec[3] else 0,
                )
                return
        for row in range(start, start + n):
            self._init_row(row)

    def _init_row(self, row):
        # Pure-python per-row fallback: runs only when the native lib is
        # absent (no map, no bulk kernels) or for specs/dtypes the bulk
        # kernels don't cover.
        self._init_fn(self._slab[row], self._row_seed(row))

    def rows_for_ids(self, ids, create_missing=True):
        """id array -> row-index array, lazily materializing unseen ids (the
        'lazy init on first lookup' semantics)."""
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        with self._lock:
            if self._map is not None:
                size_before = len(self._map)
                rows, size_after = self._map.rows_for_ids(
                    ids, create_missing
                )
                n_new = size_after - size_before
                if n_new:
                    if size_after > self._slab.shape[0]:
                        self._grow_locked(size_after)
                    self._init_rows_locked(size_before, n_new)
                return rows
            rows = np.empty(len(ids), dtype=np.int64)
            for i, id_ in enumerate(ids):
                row = self._id_to_row.get(int(id_))
                if row is None:
                    if not create_missing:
                        rows[i] = -1
                        continue
                    row = len(self._id_to_row)
                    if row >= self._slab.shape[0]:
                        self._grow_locked(row + 1)
                    self._id_to_row[int(id_)] = row
                    self._init_row(row)
                rows[i] = row
            return rows

    # ---------- lookup / assign ----------

    def lookup(self, ids):
        """[k] ids -> [k, dim] values; unseen ids are lazily initialized."""
        rows = self.rows_for_ids(ids)
        with self._lock:
            lib = native.lib()
            if lib is not None and self.dtype == np.float32:
                out = np.empty((len(rows), self.dim), dtype=np.float32)
                lib.edl_gather_rows(
                    native._f32p(self._slab), native._i64p(rows),
                    len(rows), self.dim, native._f32p(out),
                )
                return out
            return self._slab[rows].copy()

    def assign(self, ids, values):
        values = np.ascontiguousarray(values, dtype=self.dtype)
        rows = self.rows_for_ids(ids)
        with self._lock:
            lib = native.lib()
            if lib is not None and self.dtype == np.float32:
                lib.edl_scatter_rows(
                    native._f32p(self._slab), native._i64p(rows),
                    len(rows), self.dim, native._f32p(values),
                )
            else:
                self._slab[rows] = values

    # ---------- optimizer slots ----------

    def create_slot(self, slot_name, init_value=0.0):
        with self._lock:
            if slot_name not in self._slots:
                self._slot_init_val[slot_name] = init_value
                self._slots[slot_name] = np.full(
                    self._slab.shape, init_value, dtype=self.dtype
                )
            return self._slots[slot_name]

    def slot_slab(self, slot_name):
        return self._slots[slot_name]

    @property
    def slab(self):
        return self._slab

    @property
    def lock(self):
        """RLock guarding the slab: callers that hold row indices across a
        kernel call take this so a concurrent grow can't swap the buffer
        out from under the raw pointers."""
        return self._lock

    # ---------- checkpoint export/import ----------

    def export_rows(self, start=0, count=None):
        """(ids, values) for materialized ids in stable insertion order,
        row-aligned. `start`/`count` page through the table (new ids only
        ever append, so earlier pages stay stable while paging). Row i was
        created by the i-th distinct id, so a page's values are the
        contiguous slab slice [start, end)."""
        with self._lock:
            n = len(self)
            end = n if count is None else min(n, start + count)
            if start >= end:
                return np.empty(0, np.int64), np.empty(
                    (0, self.dim), self.dtype
                )
            if self._map is not None:
                ids = self._map.export_ids(start, end - start)
            else:
                ids = np.fromiter(
                    self._id_to_row.keys(), dtype=np.int64, count=n
                )[start:end]
            return ids, self._slab[start:end].copy()

    def import_rows(self, ids, values):
        self.assign(ids, values)
