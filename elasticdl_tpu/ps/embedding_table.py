"""Slab-backed embedding table with lazy per-id initialization.

Reference counterpart: map[int64]*Tensor with an RWMutex and lazy uniform
[-0.05, 0.05] row init (/root/reference/elasticdl/go/pkg/common/
embedding_table.go:22-88) and the Python dict twin
(elasticdl/python/ps/embedding_table.py:23-136). Redesign: rows live in ONE
contiguous [capacity, dim] float32 slab that doubles on growth, with an
id -> row-index dict on the side. That layout is what lets the native
optimizer kernels update k sparse rows in a single C call, and what makes
lookups a single gather instead of k dict hits.

Slot tables (Adam m/v, momentum velocity, ...) are companion slabs allocated
by the optimizer with the SAME row mapping, so one row-index array drives the
parameter and all its slots.
"""

import threading

import numpy as np

from elasticdl_tpu import native
from elasticdl_tpu.ps.initializers import make_row_initializer

DEFAULT_CAPACITY = 1024


class EmbeddingTable:
    def __init__(self, name, dim, initializer="uniform", dtype=np.float32,
                 capacity=DEFAULT_CAPACITY, seed=0):
        self.name = name
        self.dim = int(dim)
        self.initializer = initializer
        self.dtype = np.dtype(dtype)
        # Full initializer library (zeros/constant/uniform/normal/
        # truncated_normal, optionally parameterized — ps/initializers.py,
        # matching the reference's initializer.go). Uniform specs resolve
        # to a (low, high) range that _init_row feeds the fast native
        # kernel; everything else goes through the numpy closure.
        self._init_fn, self._uniform_range = make_row_initializer(
            initializer, self.dim, self.dtype
        )
        self._lock = threading.RLock()
        self._slab = np.zeros((capacity, self.dim), dtype=self.dtype)
        self._id_to_row = {}
        self._seed = seed
        # Companion slabs (optimizer slots) registered via create_slot;
        # grown in lockstep with the parameter slab.
        self._slots = {}
        self._slot_init_val = {}

    # ---------- row management ----------

    def __len__(self):
        return len(self._id_to_row)

    @property
    def ids(self):
        with self._lock:
            return np.fromiter(
                self._id_to_row.keys(), dtype=np.int64, count=len(self._id_to_row)
            )

    def _grow(self, min_capacity):
        capacity = self._slab.shape[0]
        while capacity < min_capacity:
            capacity *= 2
        grown = np.zeros((capacity, self.dim), dtype=self.dtype)
        grown[: self._slab.shape[0]] = self._slab
        self._slab = grown
        for slot_name, slab in self._slots.items():
            g = np.full((capacity, self.dim), self._slot_init_val[slot_name],
                        dtype=self.dtype)
            g[: slab.shape[0]] = slab
            self._slots[slot_name] = g

    def _init_row(self, row):
        dst = self._slab[row]
        # Deterministic per-row seed so a resharded restore that re-inits
        # unseen ids stays reproducible.
        seed = (self._seed * 0x9E3779B1 + row + 1) & 0xFFFFFFFFFFFFFFFF
        lib = native.lib()
        if (
            self._uniform_range is not None
            and lib is not None
            and self.dtype == np.float32
        ):
            low, high = self._uniform_range
            lib.edl_uniform_init(
                dst.ctypes.data_as(native.ctypes.POINTER(
                    native.ctypes.c_float)),
                self.dim, low, high, seed,
            )
        else:
            self._init_fn(dst, seed)

    def rows_for_ids(self, ids, create_missing=True):
        """id array -> row-index array, lazily materializing unseen ids (the
        'lazy init on first lookup' semantics)."""
        ids = np.asarray(ids, dtype=np.int64)
        rows = np.empty(len(ids), dtype=np.int64)
        with self._lock:
            for i, id_ in enumerate(ids):
                row = self._id_to_row.get(int(id_))
                if row is None:
                    if not create_missing:
                        rows[i] = -1
                        continue
                    row = len(self._id_to_row)
                    if row >= self._slab.shape[0]:
                        self._grow(row + 1)
                    self._id_to_row[int(id_)] = row
                    self._init_row(row)
                rows[i] = row
        return rows

    # ---------- lookup / assign ----------

    def lookup(self, ids):
        """[k] ids -> [k, dim] values; unseen ids are lazily initialized."""
        rows = self.rows_for_ids(ids)
        with self._lock:
            lib = native.lib()
            if lib is not None and self.dtype == np.float32:
                out = np.empty((len(rows), self.dim), dtype=np.float32)
                lib.edl_gather_rows(
                    native._f32p(self._slab), native._i64p(rows),
                    len(rows), self.dim, native._f32p(out),
                )
                return out
            return self._slab[rows].copy()

    def assign(self, ids, values):
        values = np.ascontiguousarray(values, dtype=self.dtype)
        rows = self.rows_for_ids(ids)
        with self._lock:
            lib = native.lib()
            if lib is not None and self.dtype == np.float32:
                lib.edl_scatter_rows(
                    native._f32p(self._slab), native._i64p(rows),
                    len(rows), self.dim, native._f32p(values),
                )
            else:
                self._slab[rows] = values

    # ---------- optimizer slots ----------

    def create_slot(self, slot_name, init_value=0.0):
        with self._lock:
            if slot_name not in self._slots:
                self._slot_init_val[slot_name] = init_value
                self._slots[slot_name] = np.full(
                    self._slab.shape, init_value, dtype=self.dtype
                )
            return self._slots[slot_name]

    def slot_slab(self, slot_name):
        return self._slots[slot_name]

    @property
    def slab(self):
        return self._slab

    @property
    def lock(self):
        """RLock guarding the slab: callers that hold row indices across a
        kernel call take this so a concurrent grow can't swap the buffer
        out from under the raw pointers."""
        return self._lock

    # ---------- checkpoint export/import ----------

    def export_rows(self, start=0, count=None):
        """(ids, values) for materialized ids in stable insertion order,
        row-aligned. `start`/`count` page through the table (new ids only
        ever append, so earlier pages stay stable while paging)."""
        with self._lock:
            ids = self.ids
            rows = np.fromiter(
                self._id_to_row.values(), dtype=np.int64, count=len(ids)
            )
            if count is not None or start:
                end = len(ids) if count is None else start + count
                ids, rows = ids[start:end], rows[start:end]
            return ids, self._slab[rows].copy()

    def import_rows(self, ids, values):
        self.assign(ids, values)
