"""The PS shard's model store: dense numpy params + embedding tables.

Reference counterparts: Go Model (/root/reference/elasticdl/go/pkg/ps/
model.go:25-110) and Python Parameters (elasticdl/python/ps/
parameters.py:30-224). Dense parameters are plain float32 numpy arrays
(updated in place by the native kernels); embedding tables are slab-backed
(ps/embedding_table.py). Initialization happens once, from the first
worker's push_model — that lazy-init path is also the PS fault-tolerance
story: a restarted empty PS gets re-seeded by whichever worker notices
initialized=False on its next pull.
"""

import threading

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.ps.embedding_table import EmbeddingTable


class Parameters:
    def __init__(self):
        self.dense = {}  # name -> np.ndarray (float32, contiguous)
        self.embedding_tables = {}  # name -> EmbeddingTable
        self.version = 0
        # Training records behind accepted gradient pushes so far;
        # checkpointed for exact resume fast-forwarding.
        self.total_records = 0
        self.initialized = False
        self.init_lock = threading.Lock()

    def init_from_model_pb(self, model_pb):
        """First-push initialization; later pushes are no-ops (the reference
        ignores re-pushes once initialized, python/ps/parameters.py:112-120).
        Returns True iff this call performed the init."""
        with self.init_lock:
            if self.initialized:
                return False
            self.init_embedding_infos(model_pb.embedding_table_infos)
            for t in model_pb.dense_parameters:
                self.dense[t.name] = np.ascontiguousarray(
                    tensor_utils.tensor_pb_to_ndarray(t), dtype=np.float32
                )
            for name, slices in model_pb.embedding_tables.items():
                values, ids = tensor_utils.indexed_slices_pb_to_ndarrays(
                    slices
                )
                self.embedding_tables[name].assign(ids, values)
            self.version = model_pb.version
            self.initialized = True
            return True

    def init_embedding_infos(self, infos):
        for info in infos:
            if info.name not in self.embedding_tables:
                self.embedding_tables[info.name] = EmbeddingTable(
                    info.name,
                    info.dim,
                    initializer=info.initializer or "uniform",
                )

    def to_model_pb(self, include_embeddings=True):
        model = pb.Model(
            version=self.version,
            total_records=self.total_records,
        )
        for name in sorted(self.dense):
            model.dense_parameters.append(
                tensor_utils.ndarray_to_tensor_pb(self.dense[name], name)
            )
        for name in sorted(self.embedding_tables):
            table = self.embedding_tables[name]
            model.embedding_table_infos.append(
                pb.EmbeddingTableInfo(
                    name=name,
                    dim=table.dim,
                    initializer=table.initializer,
                    dtype=pb.DT_FLOAT32,
                )
            )
            if include_embeddings and len(table):
                ids, values = table.export_rows()
                model.embedding_tables[name].CopyFrom(
                    tensor_utils.ndarray_to_indexed_slices_pb(
                        values, ids, name
                    )
                )
        return model
