"""Parameter-server process bootstrap.

Reference counterparts: the Go PS main (/root/reference/elasticdl/go/cmd/
elasticdl_ps/main.go:27-74) and the Python twin bootstrap
(elasticdl/python/ps/parameter_server.py:34-163): build store + optimizer +
servicer, optionally restore from a checkpoint (resharding to this shard's
id/count), serve, and exit when the master goes away.
"""

import threading

from elasticdl_tpu.common import rpc
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.ops.optimizers import OptimizerSpec
from elasticdl_tpu.ps import checkpoint as ckpt
from elasticdl_tpu.ps.optimizer import PSOptimizer
from elasticdl_tpu.ps.parameters import Parameters
from elasticdl_tpu.ps.servicer import PserverServicer

logger = get_logger("ps.parameter_server")


class ParameterServer:
    def __init__(
        self,
        ps_id,
        num_ps,
        port=0,
        optimizer_spec=None,
        use_async=True,
        grads_to_wait=1,
        sync_version_tolerance=0,
        sync_window_timeout=30.0,
        lr_staleness_modulation=False,
        checkpoint_dir=None,
        checkpoint_steps=0,
        keep_checkpoint_max=3,
        checkpoint_dir_for_init=None,
        master_client=None,
    ):
        # The PS compiles (ps_step/ps_local_apply): wire the persistent
        # compilation cache before the first jit so a relaunched shard
        # rehydrates from disk. No-op when the knob is unset.
        from elasticdl_tpu.common.compile_cache import (
            ensure_compile_cache,
        )

        ensure_compile_cache()
        self.ps_id = ps_id
        self.num_ps = num_ps
        self.parameters = Parameters()
        self.optimizer = PSOptimizer(
            optimizer_spec or OptimizerSpec("sgd")
        )
        saver = None
        if checkpoint_dir and checkpoint_steps:
            saver = ckpt.CheckpointSaver(
                checkpoint_dir, ps_id, num_ps, keep_checkpoint_max
            )
        if checkpoint_dir_for_init:
            version = ckpt.latest_complete_version(checkpoint_dir_for_init)
            if version is None:
                raise ValueError(
                    f"no complete checkpoint under {checkpoint_dir_for_init}"
                )
            ckpt.restore_shard(
                checkpoint_dir_for_init,
                version,
                self.parameters,
                ps_id,
                num_ps,
            )
        self.servicer = PserverServicer(
            self.parameters,
            self.optimizer,
            use_async=use_async,
            grads_to_wait=grads_to_wait,
            sync_version_tolerance=sync_version_tolerance,
            sync_window_timeout=sync_window_timeout,
            lr_staleness_modulation=lr_staleness_modulation,
            checkpoint_saver=saver,
            checkpoint_steps=checkpoint_steps,
            master_client=master_client,
            shard_id=ps_id,
        )
        self._server, self.port = rpc.serve(
            self.servicer, rpc.PSERVER_SERVICE, port=port
        )
        logger.info("PS %d/%d serving on port %d", ps_id, num_ps, self.port)
        self._stop_event = threading.Event()
        # Memory accounting: this shard's embedding-table / dense-param
        # byte counts become edl_mem_component_bytes{component=...} so a
        # hot shard's RSS is attributable to the table that causes it.
        from elasticdl_tpu.observability import memory as _memory

        self._mem_provider = _memory.embedding_bytes_provider(
            self.parameters
        )
        _memory.accountant().add_provider(self._mem_provider)

    @property
    def addr(self):
        return f"localhost:{self.port}"

    def wait(self, master_liveness_check=None, poll_seconds=30):
        """Block until stopped; with a liveness callable, exit when the
        master is gone (reference PS watches the master pod,
        go/cmd/elasticdl_ps/main.go:48-74)."""
        while not self._stop_event.is_set():
            if master_liveness_check is not None:
                try:
                    alive = master_liveness_check()
                except Exception:
                    alive = False
                if not alive:
                    logger.info("Master gone; PS %d exiting", self.ps_id)
                    break
            self._stop_event.wait(poll_seconds)

    def stop(self):
        self._stop_event.set()
        self._server.stop(0)
        from elasticdl_tpu.observability import memory as _memory

        _memory.accountant().remove_provider(self._mem_provider)
