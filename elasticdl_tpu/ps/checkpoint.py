"""Sharded, reshardable PS checkpoints.

Reference counterparts: Go checkpoint (/root/reference/elasticdl/go/pkg/ps/
checkpoint.go:61-141) and Python save_utils (elasticdl/python/common/
save_utils.py:151-282). Layout kept: `<dir>/version-<V>/
variables-<i>-of-<N>.ckpt`, one serialized Model pb per PS shard; a
checkpoint is valid iff the complete shard set is present; restore reshards
(dense params by name-hash, embedding ids by modulo) so a job can come back
with a different PS count; keep_checkpoint_max GC prunes old versions.
"""

import json
import os
import re
import shutil

import numpy as np

from elasticdl_tpu.common import hash_utils, tensor_utils
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("ps.checkpoint")

_SHARD_RE = re.compile(r"variables-(\d+)-of-(\d+)\.ckpt$")


def _version_dir(checkpoint_dir, version):
    return os.path.join(checkpoint_dir, f"version-{version}")


def _shard_path(checkpoint_dir, version, ps_id, num_ps):
    return os.path.join(
        _version_dir(checkpoint_dir, version),
        f"variables-{ps_id}-of-{num_ps}.ckpt",
    )


class CheckpointSaver:
    def __init__(self, checkpoint_dir, ps_id, num_ps, keep_checkpoint_max=3):
        self._dir = checkpoint_dir
        self._ps_id = ps_id
        self._num_ps = num_ps
        self._keep_max = keep_checkpoint_max
        os.makedirs(checkpoint_dir, exist_ok=True)

    def snapshot(self, version, parameters):
        """Serialize a consistent Model pb of the store. Callers that share
        the store with concurrent writers must hold the version lock here
        (and may release it before save_snapshot, which only does I/O)."""
        model = parameters.to_model_pb(include_embeddings=True)
        model.version = version
        return model

    def save(self, version, parameters):
        """Snapshot + write in one call (single-writer callers only)."""
        self.save_snapshot(version, self.snapshot(version, parameters))

    def save_snapshot(self, version, model):
        """Write this shard's file for `version` (atomic rename), then GC."""
        os.makedirs(_version_dir(self._dir, version), exist_ok=True)
        path = _shard_path(self._dir, version, self._ps_id, self._num_ps)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(model.SerializeToString())
        os.replace(tmp, path)
        # Tiny sidecar so a resuming master can read the consumed-record
        # count without deserializing the (possibly multi-GB) shard pb.
        meta = _meta_path(self._dir, version, self._ps_id, self._num_ps)
        with open(f"{meta}.tmp", "w") as f:
            json.dump(
                {"version": version, "total_records": model.total_records},
                f,
            )
        os.replace(f"{meta}.tmp", meta)
        logger.info("Saved checkpoint shard %s", path)
        self._gc()

    def _gc(self):
        versions = list_checkpoint_versions(self._dir)
        for stale in versions[: -self._keep_max] if self._keep_max else []:
            shutil.rmtree(_version_dir(self._dir, stale), ignore_errors=True)
            logger.info("Pruned checkpoint version-%d", stale)


def list_checkpoint_versions(checkpoint_dir):
    versions = []
    if not os.path.isdir(checkpoint_dir):
        return versions
    for entry in os.listdir(checkpoint_dir):
        m = re.fullmatch(r"version-(\d+)", entry)
        if m:
            versions.append(int(m.group(1)))
    return sorted(versions)


def is_complete(checkpoint_dir, version):
    """Valid iff all N shard files of one write are present (the reference's
    completeness rule, save_utils.py:211-227)."""
    vdir = _version_dir(checkpoint_dir, version)
    if not os.path.isdir(vdir):
        return False
    shards = {}
    for entry in os.listdir(vdir):
        m = _SHARD_RE.fullmatch(entry)
        if m:
            shards[int(m.group(1))] = int(m.group(2))
    if not shards:
        return False
    n = next(iter(shards.values()))
    return set(shards) == set(range(n)) and all(
        v == n for v in shards.values()
    )


def latest_complete_version(checkpoint_dir):
    for version in reversed(list_checkpoint_versions(checkpoint_dir)):
        if is_complete(checkpoint_dir, version):
            return version
    return None


def _meta_path(checkpoint_dir, version, ps_id, num_ps):
    return os.path.join(
        _version_dir(checkpoint_dir, version),
        f"meta-{ps_id}-of-{num_ps}.json",
    )


def read_total_records(checkpoint_dir, version):
    """Max total_records across a checkpoint's shards — the exact count of
    training records consumed when it was written (each push fans out to
    every shard holding one of its params, so the busiest shard's counter
    is the job-wide number). Prefers the tiny meta sidecars; falls back to
    parsing shard protobufs (pre-sidecar checkpoints). 0 when absent."""
    vdir = _version_dir(checkpoint_dir, version)
    total = 0
    found_meta = False
    for entry in sorted(os.listdir(vdir)):
        if entry.startswith("meta-") and entry.endswith(".json"):
            with open(os.path.join(vdir, entry)) as f:
                total = max(total, json.load(f).get("total_records", 0))
            found_meta = True
    if found_meta:
        return total
    for entry in sorted(os.listdir(vdir)):
        if not _SHARD_RE.fullmatch(entry):
            continue
        model = pb.Model()
        with open(os.path.join(vdir, entry), "rb") as f:
            model.ParseFromString(f.read())
        total = max(total, model.total_records)
    return total


def restore_shard(checkpoint_dir, version, parameters, ps_id, num_ps):
    """Load `parameters` for PS shard `ps_id` of `num_ps` from a checkpoint
    written by ANY shard count: reads every saved shard file and keeps what
    hashes to this shard (dense by name-hash, ids by modulo) — the
    reference's reshard-on-load (go/pkg/ps/checkpoint.go:61-95)."""
    vdir = _version_dir(checkpoint_dir, version)
    if not is_complete(checkpoint_dir, version):
        raise ValueError(f"incomplete or missing checkpoint at {vdir}")
    with parameters.init_lock:
        for entry in sorted(os.listdir(vdir)):
            if not _SHARD_RE.fullmatch(entry):
                continue
            model = pb.Model()
            with open(os.path.join(vdir, entry), "rb") as f:
                model.ParseFromString(f.read())
            parameters.init_embedding_infos(model.embedding_table_infos)
            parameters.total_records = max(
                parameters.total_records, model.total_records
            )
            for t in model.dense_parameters:
                if hash_utils.string_to_id(t.name, num_ps) != ps_id:
                    continue
                parameters.dense[t.name] = np.ascontiguousarray(
                    tensor_utils.tensor_pb_to_ndarray(t), dtype=np.float32
                )
            for name, slices in model.embedding_tables.items():
                values, ids = tensor_utils.indexed_slices_pb_to_ndarrays(
                    slices
                )
                mask = (ids % num_ps) == ps_id
                if mask.any():
                    parameters.embedding_tables[name].assign(
                        ids[mask], values[mask]
                    )
        parameters.version = version
        parameters.initialized = True
    logger.info(
        "Restored shard %d/%d from %s: %d dense, %d tables",
        ps_id,
        num_ps,
        vdir,
        len(parameters.dense),
        len(parameters.embedding_tables),
    )
