"""User hook for handling prediction outputs (reference
/root/reference/elasticdl/python/worker/prediction_outputs_processor.py:17-35).
"""

from abc import ABC, abstractmethod


class BasePredictionOutputsProcessor(ABC):
    @abstractmethod
    def process(self, predictions, worker_id):
        ...
