"""The worker loop: pull tasks, train/evaluate/predict minibatches, report.

Reference counterpart (/root/reference/elasticdl/python/worker/
worker.py:42-444): job-type dispatch, per-minibatch retry (<=64), evaluation
tasks interleaved into training, prediction output processing, train-end
callback task handling.
"""

import traceback

import grpc

from elasticdl_tpu.common.constants import (
    DEFAULT_MAX_MINIBATCH_RETRY_NUM,
    JobType,
)
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.common.timing import Timing
from elasticdl_tpu.observability import datapath, tracing
from elasticdl_tpu.observability.metrics import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.worker.task_data_service import TaskDataService

logger = get_logger("worker.worker")

_REG = default_registry()
_STEPS = _REG.counter(
    "edl_worker_steps_total", "Minibatch steps this worker completed"
)
_TASKS = _REG.counter(
    "edl_worker_tasks_total",
    "Tasks this worker processed, by result",
    labelnames=("result",),
)
_PHASE_SECONDS = _REG.histogram(
    "edl_phase_seconds",
    "Worker phase latency (task_process/batch_process + trainer phases)",
    labelnames=("phase",),
)


class Worker:
    def __init__(
        self,
        worker_id,
        master_client,
        data_reader,
        model_spec,
        trainer,
        minibatch_size=64,
        job_type=JobType.TRAINING_ONLY,
        log_loss_steps=100,
        max_minibatch_retries=DEFAULT_MAX_MINIBATCH_RETRY_NUM,
        extra_callbacks=(),
        profile_dir="",
        profile_start_step=10,
        profile_steps=5,
        lease_mode=False,
    ):
        self._worker_id = worker_id
        self._mc = master_client
        self._tds = TaskDataService(master_client, data_reader)
        self._spec = model_spec
        self._trainer = trainer
        self._minibatch_size = minibatch_size
        self._job_type = job_type
        self._log_loss_steps = log_loss_steps
        self._max_minibatch_retries = max_minibatch_retries
        self._metadata = data_reader.metadata
        # Step-synchronized lease mode (multi-host AllReduce): training is
        # driven by whole-world leases instead of independent task pulls.
        self._lease_mode = lease_mode
        self._steps = 0
        self._timing = Timing().bind_histogram(_PHASE_SECONDS)
        # Data-plane stages recorded off the worker loop (task acquire,
        # read/starve, decode) mirror into this Timing as input_<stage>
        # phases; the trainer's h2d stage binds its own Timing at the
        # call site so bench attribution sees it in the trainer summary.
        datapath.get().bind_timing(self._timing)
        trainer_timing = getattr(trainer, "timing", None)
        if trainer_timing is not None:
            # Trainer phases (pull/step/push) reach /metrics through the
            # same labeled histogram.
            trainer_timing.bind_histogram(_PHASE_SECONDS)
        # One-shot device trace of steady-state steps (past the compile):
        # [profile_start_step, profile_start_step + profile_steps), written
        # as a TensorBoard trace-viewer profile. The reference's deepest
        # tracing is wall-clock Timing (timing_utils.py:17-48); on TPU the
        # XLA-level trace is the tool that actually explains a step.
        self._profile_dir = profile_dir
        self._profile_start_step = profile_start_step
        self._profile_steps = profile_steps
        self._profiling = False
        self._callbacks = (
            model_spec.callbacks() if model_spec.callbacks else []
        ) + list(extra_callbacks)

    # ---------- public ----------

    def run(self):
        try:
            if self._profile_dir and self._job_type in (
                JobType.EVALUATION_ONLY,
                JobType.PREDICTION_ONLY,
            ):
                # The trace window opens on the training minibatch path
                # only; say so instead of silently writing nothing.
                logger.warning(
                    "--profile_dir is only honored for training jobs; "
                    "no trace will be captured for job type %s",
                    self._job_type,
                )
            if self._job_type in (
                JobType.TRAINING_ONLY,
                JobType.TRAINING_WITH_EVALUATION,
            ):
                if self._lease_mode:
                    # Leases cover TRAINING work only; the regular loop
                    # afterwards drains evaluation and train-end tasks.
                    self._train_leases()
                self._train_and_evaluate()
            elif self._job_type == JobType.EVALUATION_ONLY:
                self._evaluate_only()
            elif self._job_type == JobType.PREDICTION_ONLY:
                self._predict_only()
            else:
                raise ValueError(f"unknown job type {self._job_type}")
        finally:
            # A short job can end inside the profiled window; an unclosed
            # trace would be empty on disk.
            self._stop_profile_if_running()

    # ---------- job loops ----------

    def _train_and_evaluate(self):
        while True:
            task = self._tds.get_task()
            if task is None:
                # Batched leases: results buffered past the last fetch
                # must land before the loop exits.
                self._tds.flush_reports()
                logger.info("Worker %d: no more tasks", self._worker_id)
                break
            if task.type == pb.TRAINING:
                self._run_task(task, self._process_train_batch)
                # In local/AllReduce modes the worker is the version source
                # (the PS plays that role in PS mode): reporting after each
                # training task drives version-triggered evaluation. A lost
                # report only delays the next eval trigger — never worth a
                # worker's life during a master blip.
                try:
                    self._mc.report_version(
                        self._trainer.get_model_version()
                    )
                except grpc.RpcError:
                    logger.warning(
                        "report_version failed (master unreachable?); "
                        "continuing",
                    )
                # Interleave pending evaluation tasks between training tasks
                # (reference worker.py:343-349).
                if self._job_type == JobType.TRAINING_WITH_EVALUATION:
                    self._drain_eval_tasks()
            elif task.type == pb.EVALUATION:
                self._run_task(task, self._process_eval_batch)
            elif task.type == pb.TRAIN_END_CALLBACK:
                self._run_train_end_callbacks(task)
            else:
                logger.warning("Skipping unexpected task %s", task)
                self._tds.report_task(task.task_id)

    def _train_leases(self):
        """Step-synchronized lease loop (multi-host AllReduce): every rank
        of the current membership epoch runs exactly lease.n_steps SPMD
        minibatches, then the lease's tasks complete; a comm failure or a
        membership change abandons the lease (the master requeues it). The
        loop returns when training work is exhausted — evaluation and
        train-end tasks drain through the regular task loop after."""
        import time as _time

        import jax

        while True:
            lease = self._mc.lease_steps(self._minibatch_size)
            if lease.status == pb.LeaseStepsResponse.FINISHED:
                logger.info(
                    "Worker %d: training leases exhausted", self._worker_id
                )
                return
            if lease.status == pb.LeaseStepsResponse.WAIT:
                # Not in the group yet, peers still finishing the active
                # lease, or no mintable work: announce ourselves, drain any
                # pending evaluation work, and poll again.
                self._mc.report_liveness()
                if self._job_type == JobType.TRAINING_WITH_EVALUATION:
                    self._drain_eval_tasks()
                _time.sleep(0.5)
                continue
            try:
                records = self._read_lease_records(lease.ranges)
            except Exception as e:
                logger.error("Lease %d data read failed: %s", lease.lease_id, e)
                self._mc.report_lease(
                    lease.lease_id, lease.rank, False, str(e)
                )
                continue
            if not records:
                self._mc.report_lease(
                    lease.lease_id, lease.rank, False, "empty lease ranges"
                )
                continue
            B = self._minibatch_size
            first = self._spec.feed(
                records[:B], Modes.TRAINING, self._metadata
            )
            self._trainer.init_variables_if_needed(first[0])
            self._trainer.init_world_if_needed()
            if (
                self._trainer.group_id != lease.epoch
                or self._trainer.rank != lease.rank
                or self._trainer.world_size != lease.world_size
            ):
                # The world moved between minting and joining; the master
                # aborts this lease on its next epoch observation.
                logger.info(
                    "Worker %d: lease %d is for epoch %d but this worker "
                    "is at epoch %d (rank %d/%d); refetching",
                    self._worker_id,
                    lease.lease_id,
                    lease.epoch,
                    self._trainer.group_id,
                    self._trainer.rank,
                    self._trainer.world_size,
                )
                continue
            tracing.set_context(lease_epoch=lease.epoch)
            try:
                loss = None
                dp = datapath.get()
                for i in range(lease.n_steps):
                    # Cycle this rank's records to fill every batch: all
                    # ranks must dispatch identically-shaped steps.
                    with dp.stage("collate"):
                        rows = [
                            records[(i * B + j) % len(records)]
                            for j in range(B)
                        ]
                    with dp.stage("decode"):
                        features, labels = self._spec.feed(
                            rows, Modes.TRAINING, self._metadata
                        )
                    loss = self._trainer.train_lease_minibatch(
                        features, labels
                    )
                    self._steps += 1
                    _STEPS.inc()
                    if self._steps % self._log_loss_steps == 0:
                        logger.info(
                            "Step %d (lease %d) loss %.6f",
                            self._steps,
                            lease.lease_id,
                            float(loss),
                        )
                # Async dispatch: a peer failure surfaces at
                # materialization. Block before reporting so "success"
                # means the steps actually ran.
                if loss is not None:
                    jax.block_until_ready(loss)
            except Exception as e:
                logger.warning(
                    "Lease %d failed mid-steps; re-checking world",
                    lease.lease_id,
                    exc_info=True,
                )
                old_epoch = self._trainer.group_id
                try:
                    self._trainer.init_world_if_needed(force=True)
                except Exception:
                    logger.warning(
                        "World re-init failed; will retry on next lease",
                        exc_info=True,
                    )
                if self._trainer.group_id == old_epoch:
                    # Same membership epoch: this was a deterministic
                    # failure (bad feed, NaN'd compile, ...), not an
                    # elastic event — report it so the master's retry
                    # ladder can bound it instead of silently re-minting
                    # the same doomed lease forever.
                    self._mc.report_lease(
                        lease.lease_id, lease.rank, False, str(e)
                    )
                    _time.sleep(0.5)
                continue
            self._mc.report_lease(lease.lease_id, lease.rank, True)
            self._mc.report_version(self._trainer.get_model_version())

    def _read_lease_records(self, ranges):
        records = []
        for r in ranges:
            records.extend(self._tds.read_range(r))
        return records

    def _evaluate_only(self):
        while True:
            task = self._tds.get_task(pb.EVALUATION)
            if task is None:
                break
            self._run_task(task, self._process_eval_batch)

    def _predict_only(self):
        processor = self._spec.prediction_outputs_processor
        while True:
            task = self._tds.get_task(pb.PREDICTION)
            if task is None:
                break
            self._run_task(
                task,
                lambda records, task=task: self._process_predict_batch(
                    records, processor
                ),
            )
        # Optional end-of-stream hook: buffering processors (e.g. the
        # ODPS writer's) flush their tail here.
        close = getattr(processor, "close", None)
        if close is not None:
            close()

    def _drain_eval_tasks(self):
        while True:
            task = self._tds.try_get_eval_task()
            if task is None:
                return
            self._run_task(task, self._process_eval_batch)

    # ---------- task/batch processing ----------

    def _run_task(self, task, process_batch):
        # Re-key this thread's trace context to the task: every span and
        # RPC from here to report_task_result (PS pulls/pushes included)
        # carries the task id and one fresh trace id, which is what lets
        # trace_report.py stitch the task's cross-process chain together.
        tracing.set_context(task_id=task.task_id)
        try:
            with self._timing.record("task_process"), tracing.span(
                "task_process",
                task_type=pb.TaskType.Name(task.type),
            ):
                for records in self._tds.read_batches(
                    task, self._minibatch_size
                ):
                    with self._timing.record("batch_process"), tracing.span(
                        "batch_process"
                    ):
                        self._process_with_retries(process_batch, records)
            self._tds.report_task(task.task_id)
            _TASKS.labels(result="success").inc()
        except Exception as e:
            logger.error(
                "Task %d failed: %s\n%s",
                task.task_id,
                e,
                traceback.format_exc(),
            )
            self._tds.report_task(task.task_id, err_message=str(e))
            _TASKS.labels(result="failure").inc()
        finally:
            # Per-task phase breakdown at DEBUG (reference worker.py:380-382
            # reports get_model/report_gradient/batch_process the same way);
            # in the finally so a failed task's time can't leak into the
            # next task's report.
            self._timing.report(logger, reset=True)
            trainer_timing = getattr(self._trainer, "timing", None)
            if trainer_timing is not None:
                trainer_timing.report(logger, reset=True)
            # One `datapath` event per task: the per-stage seconds this
            # task spent in the feed path, keyed by task id.
            datapath.get().flush_event(task_id=task.task_id)

    def _process_with_retries(self, process_batch, records):
        """Per-minibatch retry (reference worker.py:165-218): transient
        failures (PS restart, comm regroup) retry up to the cap; then the
        whole task is failed back to the master for re-dispatch."""
        for attempt in range(self._max_minibatch_retries):
            try:
                process_batch(records)
                return
            except Exception:
                if attempt == self._max_minibatch_retries - 1:
                    raise
                logger.warning(
                    "Minibatch failed (attempt %d):\n%s",
                    attempt + 1,
                    traceback.format_exc(),
                )

    def _process_train_batch(self, records):
        with datapath.get().stage("decode"):
            features, labels = self._spec.feed(
                records, Modes.TRAINING, self._metadata
            )
        if self._profile_dir:
            # Before the dispatch, so the trace window covers exactly the
            # steps the log names.
            self._maybe_profile(self._steps + 1)
        accepted, version, loss = self._trainer.train_minibatch(
            features, labels
        )
        if accepted:
            self._steps += 1
            _STEPS.inc()
            if self._steps % self._log_loss_steps == 0:
                # Only materialize the (lazy, on-device) loss when logging;
                # every other step stays dispatch-ahead.
                logger.info(
                    "Step %d (version %d) loss %.6f",
                    self._steps,
                    version,
                    float(loss),
                )

    def _maybe_profile(self, next_step):
        """Open/close the trace window around `next_step` (the step about
        to be dispatched). Window = [start, start + steps); >= comparisons
        so a start below the current counter (e.g. --profile_start_step 0)
        still captures a window instead of silently never matching."""
        end = self._profile_start_step + self._profile_steps
        if (
            not self._profiling
            and self._profile_start_step <= next_step < end
        ):
            import jax

            self._profiling = True
            jax.profiler.start_trace(self._profile_dir)
            logger.info(
                "Profiling steps %d-%d to %s",
                next_step,
                end - 1,
                self._profile_dir,
            )
        elif self._profiling and next_step >= end:
            self._stop_profile_if_running()

    def _stop_profile_if_running(self):
        if not self._profiling:
            return
        import jax

        self._profiling = False
        try:
            jax.profiler.stop_trace()
            logger.info(
                "Profile written to %s (view: tensorboard --logdir %s)",
                self._profile_dir,
                self._profile_dir,
            )
        except Exception:
            logger.warning("Failed to finalize profile", exc_info=True)

    def _process_eval_batch(self, records):
        with datapath.get().stage("decode"):
            features, labels = self._spec.feed(
                records, Modes.EVALUATION, self._metadata
            )
        outputs = self._trainer.evaluate_minibatch(features)
        self._mc.report_evaluation_metrics(outputs, labels)

    def _process_predict_batch(self, records, processor):
        with datapath.get().stage("decode"):
            features, _ = self._spec.feed(
                records, Modes.PREDICTION, self._metadata
            )
        outputs = self._trainer.predict_minibatch(features)
        if processor is not None:
            processor.process(outputs, self._worker_id)

    def _run_train_end_callbacks(self, task):
        try:
            for cb in self._callbacks:
                on_train_end = getattr(cb, "on_train_end", None)
                if on_train_end:
                    on_train_end(self._trainer)
            self._tds.report_task(task.task_id)
        except Exception as e:
            self._tds.report_task(task.task_id, err_message=str(e))

    @property
    def steps(self):
        return self._steps

    @property
    def trainer(self):
        return self._trainer
