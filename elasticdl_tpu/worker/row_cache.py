"""Versioned per-table embedding-row cache for the PS trainer's prefetch.

``prefetch_embeddings`` was the PS step's single biggest host cost after
the push itself (BENCH_r06: 280-775 ms/step) — and most of those pulls
re-fetch rows this worker saw a handful of steps ago. The cache keeps
recently pulled rows per table, stamped with the PS model version at
fill time, and serves a hit only while the row is younger than the
staleness budget (ELASTICDL_PREFETCH_CACHE_STALENESS versions). Async
SGD already tolerates exactly this class of bounded staleness — it is
the same bound the pipelined push imposes — while the version advancing
past the budget invalidates by construction: no hit can ever be served
more than ``staleness`` versions old.

Layout per table: a DENSE id -> slot index (int32, sized to the largest
id seen, capped by ELASTICDL_PREFETCH_CACHE_DENSE_IDS) over a growable
row slab plus per-slot fill versions. Embedding id spaces here are
hashed into bounded buckets (DeepFM's shared space is ~5.5M ids), so
the index is a few tens of MB and every operation is one vectorized
gather/scatter — lookups for 600k ids cost ~5 ms where a sorted-array
searchsorted design cost ~30 ms and its merge-inserts ~40 ms. A table
whose ids exceed the cap simply stops caching (misses pull from the PS
as before). Crossing ELASTICDL_PREFETCH_CACHE_ROWS flushes the table
(rows re-fill on the following misses) instead of tracking an eviction
order; stale slots are reclaimed by that same flush.

Hit rates export as edl_prefetch_row_cache_{hits,misses}_total counters
plus the edl_prefetch_row_cache_hit_ratio gauge (cumulative).
"""

import threading

import numpy as np

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("worker.row_cache")

_REG = default_registry()
_HITS = _REG.counter(
    "edl_prefetch_row_cache_hits_total",
    "Embedding prefetch ids served from the worker row cache",
    labelnames=("table",),
)
_MISSES = _REG.counter(
    "edl_prefetch_row_cache_misses_total",
    "Embedding prefetch ids that needed a PS pull",
    labelnames=("table",),
)
_HIT_RATIO = _REG.gauge(
    "edl_prefetch_row_cache_hit_ratio",
    "Cumulative hit ratio of the worker embedding row cache",
)


class _TableSlab:
    __slots__ = ("idx", "rows", "fill_versions", "used")

    def __init__(self, id_space, dim, dtype, capacity=65536):
        self.idx = np.full(id_space, -1, dtype=np.int32)
        self.rows = np.empty((capacity, dim), dtype=dtype)
        self.fill_versions = np.empty(capacity, dtype=np.int64)
        self.used = 0


class EmbeddingRowCache:
    def __init__(self, max_rows=None, staleness=None, dense_ids=None):
        self._max_rows = (
            knobs.get_int("ELASTICDL_PREFETCH_CACHE_ROWS")
            if max_rows is None
            else max_rows
        )
        self._staleness = (
            knobs.get_int("ELASTICDL_PREFETCH_CACHE_STALENESS")
            if staleness is None
            else staleness
        )
        self._dense_ids = (
            knobs.get_int("ELASTICDL_PREFETCH_CACHE_DENSE_IDS")
            if dense_ids is None
            else dense_ids
        )
        self._lock = threading.Lock()
        self._tables = {}
        self._disabled = set()  # tables whose ids exceed the index cap
        self._version = 0
        self._hits = 0
        self._lookups = 0

    @property
    def enabled(self):
        return self._max_rows > 0

    @property
    def version(self):
        with self._lock:
            return self._version

    def note_version(self, version):
        """Record the newest PS model version this worker observed (pull
        or push response). Monotonic; rows older than
        ``version - staleness`` stop hitting from here on."""
        version = int(version)
        with self._lock:
            if version > self._version:
                self._version = version

    def lookup(self, table, ids):
        """Unique ids [k] -> (hit mask [k], rows [nhit, dim] | None).

        A hit requires the id to be cached AND filled within the
        staleness budget of the current version. Returns rows as a
        gathered COPY in id order (callers scatter them into the batch
        layout)."""
        k = int(len(ids))
        with self._lock:
            entry = self._tables.get(table)
            if entry is None or not entry.used:
                hit = np.zeros(k, dtype=bool)
                rows = None
            else:
                # Negative ids never hit (a dense index can't represent
                # them — insert() disables such tables); the clip keeps
                # the gather in bounds for out-of-range ids either way.
                in_range = (ids >= 0) & (ids < len(entry.idx))
                slots = entry.idx[np.clip(ids, 0, len(entry.idx) - 1)]
                hit = in_range & (slots >= 0)
                if self._staleness >= 0:
                    fresh_floor = self._version - self._staleness
                    hit_slots = slots[hit]
                    fresh = (
                        entry.fill_versions[hit_slots] >= fresh_floor
                    )
                    hit[np.flatnonzero(hit)[~fresh]] = False
                rows = (
                    entry.rows[slots[hit]] if hit.any() else None
                )
            nhit = int(hit.sum())
            self._hits += nhit
            self._lookups += k
            if self._lookups:
                _HIT_RATIO.set(self._hits / self._lookups)
        if nhit:
            _HITS.labels(table=table).inc(nhit)
        if k - nhit:
            _MISSES.labels(table=table).inc(k - nhit)
        return hit, rows

    def insert(self, table, ids, rows):
        """Record freshly pulled rows (this lookup's misses), stamped
        with the current version. An id re-pulled after aging out
        overwrites its old slot in place. Overflowing max_rows flushes
        the table first (the following misses re-fill it)."""
        if not len(ids):
            return
        ids = np.asarray(ids, dtype=np.int64)
        rows = np.ascontiguousarray(rows)
        with self._lock:
            if table in self._disabled:
                return
            entry = self._tables.get(table)
            max_id = int(ids.max())
            min_id = int(ids.min())
            if max_id >= self._dense_ids or min_id < 0:
                self._disabled.add(table)
                self._tables.pop(table, None)
                logger.warning(
                    "row cache disabled for table %r: id range [%d, %d] "
                    "does not fit a dense index (cap "
                    "ELASTICDL_PREFETCH_CACHE_DENSE_IDS=%d, negatives "
                    "unsupported)",
                    table, min_id, max_id, self._dense_ids,
                )
                return
            if entry is not None and (
                entry.rows.shape[1:] != rows.shape[1:]
                or entry.rows.dtype != rows.dtype
            ):
                entry = None
            if entry is None:
                entry = self._tables[table] = _TableSlab(
                    max_id + 1, rows.shape[1], rows.dtype
                )
            elif max_id >= len(entry.idx):
                grown = np.full(max_id + 1, -1, dtype=np.int32)
                grown[: len(entry.idx)] = entry.idx
                entry.idx = grown
            # Refresh ids that still hold a (stale) slot in place; only
            # genuinely new ids consume fresh slots.
            slots = entry.idx[ids]
            fresh_mask = slots < 0
            n_new = int(fresh_mask.sum())
            if entry.used + n_new > self._max_rows:
                entry = self._tables[table] = _TableSlab(
                    len(entry.idx), rows.shape[1], rows.dtype
                )
                slots = entry.idx[ids]
                fresh_mask = slots < 0
                n_new = int(fresh_mask.sum())
                if n_new > self._max_rows:
                    return  # one batch exceeds the whole budget
            need = entry.used + n_new
            if need > len(entry.rows):
                capacity = len(entry.rows)
                while capacity < need:
                    capacity *= 2
                entry.rows = np.concatenate(
                    [
                        entry.rows,
                        np.empty(
                            (capacity - len(entry.rows),)
                            + entry.rows.shape[1:],
                            entry.rows.dtype,
                        ),
                    ]
                )
                entry.fill_versions = np.concatenate(
                    [
                        entry.fill_versions,
                        np.empty(
                            capacity - len(entry.fill_versions),
                            np.int64,
                        ),
                    ]
                )
            if n_new:
                new_slots = np.arange(
                    entry.used, entry.used + n_new, dtype=np.int32
                )
                slots = slots.copy()
                slots[fresh_mask] = new_slots
                entry.idx[ids[fresh_mask]] = new_slots
                entry.used += n_new
            entry.rows[slots] = rows
            entry.fill_versions[slots] = self._version

    def flush(self, table=None):
        with self._lock:
            if table is None:
                self._tables.clear()
            else:
                self._tables.pop(table, None)

    def stats(self):
        with self._lock:
            return {
                "version": self._version,
                "lookups": self._lookups,
                "hits": self._hits,
                "hit_ratio": (
                    self._hits / self._lookups if self._lookups else 0.0
                ),
                "cached_rows": {
                    t: e.used for t, e in self._tables.items()
                },
            }
