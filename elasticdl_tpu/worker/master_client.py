"""Worker-side wrapper over the Master service stub (reference
/root/reference/elasticdl/python/worker/master_client.py:20-117)."""

import threading

import numpy as np

from elasticdl_tpu.common import rpc, tensor_utils
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb


class MasterClient:
    def __init__(self, master_addr, worker_id, worker_host=""):
        self._addr = master_addr
        self._reconnect_lock = threading.Lock()
        self._channel = rpc.build_channel(master_addr)
        self._stub = rpc.Stub(self._channel, rpc.MASTER_SERVICE)
        self._worker_id = worker_id
        self._worker_host = worker_host

    def reconnect(self, probe_timeout=1.0):
        """Tear down and rebuild the channel once the master accepts TCP
        again. A channel that connect-attempted the unbound port of a
        restarting master can wedge in UNAVAILABLE even after the port
        returns (the failure mode rpc.build_channel's readiness probe
        exists for) — riding out a master restart therefore needs a FRESH
        channel, probed only after the peer is really back. Returns True
        when the swap happened; False (channel untouched) while the
        master is still unreachable. Safe from any thread: every stub
        call reads self._stub at call time, so in-flight users migrate on
        their next call and the old channel's failures stay on the old
        channel."""
        with self._reconnect_lock:
            if not rpc.wait_channel_ready(self._addr, probe_timeout):
                return False
            old = self._channel
            self._channel = rpc.build_channel(self._addr, ready_timeout=0)
            self._stub = rpc.Stub(self._channel, rpc.MASTER_SERVICE)
            old.close()
            return True

    @property
    def worker_host(self):
        """The "ip:port" address this worker registers with the master; the
        port is the worker's Collective (broadcast) service port, bound after
        construction, so trainers update this before first registration."""
        return self._worker_host

    @worker_host.setter
    def worker_host(self, host):
        self._worker_host = host

    def get_task(self, task_type=pb.TRAINING):
        return self._stub.get_task(
            pb.GetTaskRequest(
                worker_id=self._worker_id, task_type=task_type
            )
        )

    def get_task_batch(self, max_tasks, task_type=pb.TRAINING):
        """Lease up to max_tasks tasks in one RPC (TaskBatch response;
        empty tasks + finished=False means wait and poll again)."""
        return self._stub.get_task_batch(
            pb.GetTaskRequest(
                worker_id=self._worker_id,
                task_type=task_type,
                max_tasks=max_tasks,
            )
        )

    def report_task_results(self, results):
        """Batch-report task results. results: iterable of
        (task_id, err_message, exec_counters) or
        (task_id, err_message, exec_counters, lease_token) tuples; the
        token (when the dispatched Task carried one) makes the report
        exactly-once across a master restart."""
        req = pb.ReportTaskResultsRequest()
        for result in results:
            task_id, err_message, exec_counters = result[:3]
            lease_token = result[3] if len(result) > 3 else 0
            entry = req.results.add(
                task_id=task_id,
                err_message=err_message or "",
                lease_token=lease_token,
            )
            if exec_counters:
                for k, v in exec_counters.items():
                    entry.exec_counters[k] = int(v)
        return self._stub.report_task_results(req)

    def get_world_hint(self):
        """Poll the master's announced next world (policy scale events);
        hint_seq == 0 means no hint has ever been announced."""
        return self._stub.get_world_hint(
            pb.GetWorldHintRequest(worker_id=self._worker_id)
        )

    def report_task_result(self, task_id, err_message="", exec_counters=None,
                           lease_token=0):
        req = pb.ReportTaskResultRequest(
            task_id=task_id, err_message=err_message,
            lease_token=lease_token,
        )
        if exec_counters:
            for k, v in exec_counters.items():
                req.exec_counters[k] = int(v)
        return self._stub.report_task_result(req)

    def report_evaluation_metrics(self, model_outputs, labels):
        # Multi-output models pass a list/tuple; each output goes on the wire
        # as its own tensor so the master can hand metrics the same list.
        if not isinstance(model_outputs, (list, tuple)):
            model_outputs = [model_outputs]
        req = pb.ReportEvaluationMetricsRequest(
            model_outputs=[
                tensor_utils.ndarray_to_tensor_pb(np.asarray(o))
                for o in model_outputs
            ],
            labels=tensor_utils.ndarray_to_tensor_pb(np.asarray(labels)),
            worker_id=self._worker_id,
        )
        return self._stub.report_evaluation_metrics(req)

    def report_version(self, model_version):
        return self._stub.report_version(
            pb.ReportVersionRequest(model_version=model_version)
        )

    def get_comm_rank(self, ready_epoch=None):
        """ready_epoch: declare this worker at the join gate for that
        membership epoch (see proto GetCommRankRequest); the response's
        world_ready says whether the whole world has arrived."""
        return self._stub.get_comm_rank(
            pb.GetCommRankRequest(
                worker_host=self._worker_host,
                ready_epoch_plus_one=(
                    0 if ready_epoch is None else ready_epoch + 1
                ),
            )
        )

    def lease_steps(self, batch_size):
        return self._stub.lease_steps(
            pb.LeaseStepsRequest(
                worker_id=self._worker_id,
                worker_host=self._worker_host,
                batch_size=batch_size,
            )
        )

    def report_lease(self, lease_id, rank, success, err_message=""):
        return self._stub.report_lease(
            pb.ReportLeaseRequest(
                lease_id=lease_id,
                worker_id=self._worker_id,
                rank=rank,
                success=success,
                err_message=err_message,
            )
        )

    def report_telemetry(self, snapshots, origin=""):
        """Push a batch of (possibly delta-encoded) metric snapshots.
        snapshots: iterable of pb.TelemetrySnapshot (or kwargs dicts)."""
        req = pb.ReportTelemetryRequest(origin=origin)
        for snap in snapshots:
            if isinstance(snap, dict):
                req.snapshots.add(**snap)
            else:
                req.snapshots.append(snap)
        return self._stub.report_telemetry(req)

    def report_liveness(self):
        return self._stub.report_worker_liveness(
            pb.ReportWorkerLivenessRequest(
                worker_id=self._worker_id, host=self._worker_host
            )
        )

    def close(self):
        self._channel.close()
