"""Speculative ahead-of-time compilation of nearby elastic worlds.

The compile tracker proved that compile IS the elastic rejoin (a
~6.5 s step re-lowering on every `mesh_change`). The unified world spec
(parallel/mesh.py) makes the fix possible: the mesh of a world this
process is NOT in yet is a pure function of (config, topology), so a
background thread can lower + compile that world's step — through
`tracked_jit`'s AOT surface (`.lower(...).compile()`) — while training
continues, and `init_world_if_needed` consumes the prebuilt executable
instead of cold-compiling when the guess lands.

Semantics the trainer relies on:

- **Non-blocking**: submit/cancel/take are lock-brief; compilation runs
  in one daemon thread. A world change mid-compile never stalls the
  step loop — it bumps the generation, and the in-flight result is
  discarded on completion (`abandoned`), since XLA compiles cannot be
  interrupted.
- **Wrong guesses are abandoned cleanly**: `cancel(keep=...)` drops
  every prebuilt executable whose spec fingerprint is not the world
  that actually formed; consuming is an exact (fingerprint, shape-key)
  match, so a stale executable can never run a wrong world's program.
- **Donation is preserved**: the executable comes from the SAME jit
  object the live path would build (`donate_argnums` captured at
  lower time), so consuming it keeps the in-place update aliasing.
- **Everything lands in the persistent cache too**: when
  ELASTICDL_COMPILE_CACHE_DIR is set, a speculative compile writes its
  disk entry even if the executable object later dies with a backend
  re-init (multi-host regroups) — the re-lowering on the other side
  rehydrates it (`compile_cache_hit`), which is how speculation helps
  worlds whose devices it cannot hold.

Outcome accounting: `edl_speculative_compiles_total{outcome}` with
outcome in {built, consumed, abandoned, failed} plus a
`speculative_compile` event per attempt.
"""

import collections
import threading
import time

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("worker.world_speculator")

SPECULATE_ENV = "ELASTICDL_AOT_SPECULATE"
AOT_WORLDS_ENV = "ELASTICDL_AOT_WORLDS"

_C_SPECULATIVE = default_registry().counter(
    "edl_speculative_compiles_total",
    "Speculative world-step compiles by outcome "
    "(built / consumed / abandoned / failed)",
    labelnames=("outcome",),
)


def speculation_enabled():
    return knobs.get_str(SPECULATE_ENV).lower() not in (
        "0", "false", "off",
    )


def world_deltas():
    """How many neighboring world sizes to guess in each direction."""
    return max(0, knobs.get_int(AOT_WORLDS_ENV))


class _Job:
    __slots__ = ("generation", "spec", "real_n")

    def __init__(self, generation, spec, real_n):
        self.generation = generation
        self.spec = spec
        self.real_n = real_n


class SpeculativeWorldCompiler:
    """Owns the background compile thread and the prebuilt-executable
    store. `plan_fn(spec, real_n)` — supplied by the trainer — returns
    `(shape_key, jitted_step, abstract_args)` for a candidate world, or
    None when that world's step cannot be planned (hook-bound paths)."""

    def __init__(self, plan_fn, max_prebuilt=8):
        self._plan_fn = plan_fn
        self._max_prebuilt = max_prebuilt
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._queue = collections.deque()
        self._prebuilt = collections.OrderedDict()
        # (fingerprint, shape_key) sets so one (world, shape) is only
        # ever attempted once per generation.
        self._attempted = set()
        self._generation = 0
        # Fingerprint the last cancel() kept: an in-flight compile for
        # exactly that world is stored on completion instead of being
        # discarded by the generation bump (it is the executable the
        # next step wants).
        self._keep_fp = None
        self._in_flight = False
        self._stopped = False
        self._thread = None
        self.stats = collections.Counter()

    # ---------- trainer-facing API (all lock-brief) ----------

    def submit(self, specs, real_n):
        """Queue candidate worlds for background compilation. Dedups by
        (fingerprint, real_n) within the current generation."""
        if not specs:
            return
        with self._lock:
            if self._stopped:
                return
            queued = False
            for spec in specs:
                tag = (spec.fingerprint(), real_n)
                if tag in self._attempted:
                    continue
                self._attempted.add(tag)
                self._queue.append(
                    _Job(self._generation, spec, real_n)
                )
                queued = True
            if queued:
                self._ensure_thread_locked()
                self._idle.notify_all()

    def cancel(self, keep_fingerprint=None):
        """The world changed: drop queued guesses and prebuilt
        executables that are not `keep_fingerprint`, and invalidate any
        in-flight compile (its result is discarded on completion —
        unless it is for `keep_fingerprint`, the world that actually
        formed, in which case it is stored as usual). Returns
        immediately — never waits on the compile thread."""
        with self._lock:
            self._generation += 1
            self._keep_fp = keep_fingerprint
            kept_jobs = [
                j for j in self._queue
                if keep_fingerprint is not None
                and j.spec.fingerprint() == keep_fingerprint
            ]
            abandoned = len(self._queue) - len(kept_jobs)
            self._queue.clear()
            self._attempted = set()
            for job in kept_jobs:
                job.generation = self._generation
                self._queue.append(job)
                self._attempted.add(
                    (job.spec.fingerprint(), job.real_n)
                )
            for key in list(self._prebuilt):
                if key[0] != keep_fingerprint:
                    del self._prebuilt[key]
                    abandoned += 1
            self.stats["abandoned"] += abandoned
        if abandoned:
            _C_SPECULATIVE.labels(outcome="abandoned").inc(abandoned)

    def take(self, fingerprint, shape_key):
        """Pop the prebuilt executable for (world fingerprint, shape
        key), or None. Exact match only — a wrong-world guess can never
        be consumed."""
        with self._lock:
            exe = self._prebuilt.pop((fingerprint, shape_key), None)
            if exe is not None:
                self.stats["consumed"] += 1
        if exe is not None:
            _C_SPECULATIVE.labels(outcome="consumed").inc()
        return exe

    def prebuilt_keys(self):
        with self._lock:
            return list(self._prebuilt)

    def drain(self, timeout=30.0):
        """Block until no work is queued or in flight (tests/bench —
        the trainer never calls this). True when idle was reached."""
        deadline = time.time() + timeout
        with self._lock:
            while self._queue or self._in_flight:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    def stop(self):
        with self._lock:
            self._stopped = True
            self._queue.clear()
            self._prebuilt.clear()
            self._idle.notify_all()

    # ---------- the compile thread ----------

    def _ensure_thread_locked(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="world-speculator", daemon=True
            )
            self._thread.start()

    def _run(self):
        while True:
            with self._lock:
                while not self._queue and not self._stopped:
                    self._idle.notify_all()
                    self._idle.wait()
                if self._stopped:
                    self._idle.notify_all()
                    return
                job = self._queue.popleft()
                self._in_flight = True
            try:
                self._compile_one(job)
            finally:
                with self._lock:
                    self._in_flight = False
                    self._idle.notify_all()

    def _compile_one(self, job):
        fingerprint = job.spec.fingerprint()
        start = time.perf_counter()
        outcome = "failed"
        shape_key = None
        try:
            plan = self._plan_fn(job.spec, job.real_n)
            if plan is None:
                outcome = "skipped"
                return
            shape_key, step, abstract_args = plan
            executable = step.lower(*abstract_args).compile()
            with self._lock:
                stale = job.generation != self._generation
                if self._stopped or (
                    stale and fingerprint != self._keep_fp
                ):
                    outcome = "abandoned"
                    return
                self._prebuilt[(fingerprint, shape_key)] = executable
                while len(self._prebuilt) > self._max_prebuilt:
                    self._prebuilt.popitem(last=False)
            outcome = "built"
        except Exception as e:
            logger.warning(
                "Speculative compile for world %s failed: %s",
                fingerprint, e,
            )
        finally:
            seconds = time.perf_counter() - start
            with self._lock:
                self.stats[outcome] += 1
            if outcome != "skipped":
                _C_SPECULATIVE.labels(outcome=outcome).inc()
                emit_event(
                    "speculative_compile",
                    spec=fingerprint,
                    outcome=outcome,
                    seconds=round(seconds, 4),
                    shape_key=list(shape_key) if shape_key else None,
                )
                if outcome == "built":
                    logger.info(
                        "Speculatively compiled world %s %s in %.2fs",
                        fingerprint, shape_key, seconds,
                    )
