"""Elastic data-parallel trainer over a jax.sharding Mesh.

Reference counterpart: the Horovod AllReduce trainer
(/root/reference/elasticdl/python/worker/allreduce_trainer.py:39-184) and its
rendezvous manager. TPU-first redesign:

- The allreduce itself is NOT hand-written: the train step is jitted with the
  batch sharded along the mesh "data" axis and parameters replicated, so XLA
  inserts the gradient all-reduce as an ICI collective. There is no Horovod
  tape wrapper — gradient averaging falls out of the sharding.
- Elastic membership: the worker polls the master's get_comm_rank every
  `steps_per_world_check` steps (reference checks every 20,
  allreduce_trainer.py:141-148). A changed rendezvous_id means the world
  changed: re-init jax.distributed over the new (coordinator, world, rank),
  rebuild the mesh, recompile, and refresh state from rank 0.
- Rank-0 broadcast: instead of Horovod broadcast_variables, every worker
  runs a tiny gRPC Collective service; after a regroup, non-zero ranks pull
  (variables, opt_state, version) from the rank-0 worker's service
  (parallel/broadcast.py) and overwrite local state.
- Comm failures retry with re-init, up to `max_comm_retries` (reference
  retries <=5 on Horovod UnknownError, allreduce_trainer.py:125-139).
- Hybrid DP x TP (extension; the reference is DP-only): with
  `model_parallel_size > 1` and a model-spec `param_specs(variables)` hook
  (e.g. parallel/tensor_parallel.transformer_param_specs), the mesh gains a
  "model" axis and parameters are laid out by those PartitionSpecs instead
  of replicated — XLA inserts the Megatron-style collectives. Optimizer
  state is left to GSPMD sharding propagation (it mirrors the param layout
  after the first step). If an elastic world change leaves the device count
  indivisible by the model-parallel size, the trainer falls back to pure DP
  for that epoch rather than failing the job.

- Multi-host composition invariant: sharding axes other than "data" NEVER
  cross process boundaries. In a multi-process world the model axis (TP)
  and the zero axis (ZeRO-1) are laid out over each process's LOCAL
  devices (the mesh is built over process-grouped device order), while
  the data axis spans processes. Consequences, both deliberate:
  (1) every process always holds a fully-addressable copy of (variables,
  opt_state), so the elastic regroup machinery — host snapshot +
  broadcast_one_to_all — is untouched by TP/ZeRO-1, and any SURVIVOR can
  re-seed a joiner (cross-process shards would die with the process that
  owned them, which no broadcast can undo); (2) TP collectives ride the
  dense intra-host ICI rather than DCN, the standard placement for tensor
  parallelism at multi-host scale. The tradeoff is that ZeRO-1's memory
  saving is the local chip count, not the global DP degree.
"""

import itertools
import threading
import time

import grpc
import jax
import numpy as np

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.jax_compat import shard_map
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event
from elasticdl_tpu.observability.metrics import default_registry
from elasticdl_tpu.parallel import broadcast, distributed
from elasticdl_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    STAGE_AXIS,
    ZERO_AXIS,
    ParallelConfig,
    WorldTopology,
    batch_axes,
    data_parallel_size,
    data_sharding,
    pad_batch_to_multiple,
    replicated_sharding,
    resolve_world_spec,
    shard_batch,
)
from elasticdl_tpu.worker.trainer import JaxTrainer
from elasticdl_tpu.worker.world_speculator import (
    SpeculativeWorldCompiler,
    speculation_enabled,
    world_deltas,
)

logger = get_logger("worker.allreduce_trainer")

# Elastic regroups by how much work they had to do: "fast" = the new
# world resolved to the SAME world spec on a stable backend, so the
# compiled steps (and state placement) were kept verbatim — the
# recompile-free path; "rebuild" = mesh + steps rebuilt.
_C_REGROUPS = default_registry().counter(
    "edl_regroups_total",
    "Elastic world changes absorbed, by path (fast = no re-mesh / no "
    "re-lowering; rebuild = mesh and steps rebuilt)",
    labelnames=("mode",),
)

DEFAULT_STEPS_PER_WORLD_CHECK = 20
DEFAULT_MAX_COMM_RETRIES = 5

# What counts as a communication/runtime failure worth a re-mesh + retry.
# XLA/distributed-runtime errors surface as RuntimeError subclasses
# (XlaRuntimeError); master RPCs fail as grpc.RpcError. User-code bugs
# (TypeError/ValueError from tracing a bad model or loss) must NOT retry —
# the reference similarly retried only Horovod comm errors
# (allreduce_trainer.py:125-139).
RETRYABLE_ERRORS = (grpc.RpcError, RuntimeError)

# Per-instance salt for the compile tracker's mesh fingerprint. The
# tracker's per-fn history is process-global (it must survive wrapper
# rebuilds), so two trainer INSTANCES in one process — bench matrix
# cells, back-to-back tests — would otherwise reproduce identical
# `epochN:{axes}` tokens and have a fresh trainer's mesh change
# misclassified as `rebuild` against the previous instance's history.
# A monotonic counter (not id(): CPython reuses ids after GC) keeps
# tokens unique across instances while staying constant within one, so
# same-instance rebuild detection is unaffected.
_trainer_seq = itertools.count(1)


def join_gate_budget():
    """The join-gate wait budget for an elastic regroup.

    Explicit ELASTICDL_JOIN_GATE_SECONDS wins; unset/0 derives from a
    measured-compile-time floor: a peer that must re-lower its step
    (~6.5 s per compile on a loaded 1-core box, per the compile
    tracker) can burn many multiples of that before reaching the gate,
    which is exactly how the old fixed 90 s gate lost to load and
    churned membership (epoch 14+ in the 1f1b flake)."""
    budget = knobs.get_float("ELASTICDL_JOIN_GATE_SECONDS")
    if budget > 0:
        return budget
    from elasticdl_tpu.observability import profiling

    # Capped: the gate's timeout fall-through exists for masters that
    # never answer world_ready (predating the gate) — one long flagship
    # compile must widen the wait to minutes, not hours.
    return min(
        max(90.0, 20.0 * profiling.peak_compile_seconds()), 600.0
    )


class AllReduceTrainer(JaxTrainer):
    def __init__(
        self,
        model,
        loss_fn,
        optimizer_spec,
        master_client,
        steps_per_world_check=DEFAULT_STEPS_PER_WORLD_CHECK,
        max_comm_retries=DEFAULT_MAX_COMM_RETRIES,
        multi_host=False,
        broadcast_port=0,
        seed=0,
        model_parallel_size=1,
        param_specs_fn=None,
        zero1=False,
        quantized_grads=False,
        pipeline_stages=1,
        pipeline_schedule="1f1b",
        pipeline_microbatches=0,
        pipeline_virtual_stages=2,
        pipeline_spec_fn=None,
        context_parallel_size=1,
        context_parallel_impl="zigzag",
        context_parallel_model_fn=None,
    ):
        super().__init__(model, loss_fn, optimizer_spec, seed=seed)
        self._mesh_salt = next(_trainer_seq)
        self._model_parallel_size = max(1, int(model_parallel_size or 1))
        self._param_specs_fn = param_specs_fn
        # Pipeline parallelism (parallel/pipeline.py): the model spec's
        # pipeline_spec hook builds the staged step; the mesh gains a
        # "stage" axis laid out like the model axis (intra-process in
        # multi-host worlds — the composition invariant above). The staged
        # param tree replaces the monolithic one, so ALL of the elastic
        # machinery (snapshot, broadcast, checkpoint) carries it untouched;
        # worlds that can't host the stage axis degrade to running the
        # same staged tree sequentially under pure DP (the schedule-free
        # apply in the PipelineBuild), keeping state intact.
        self._pipeline_stages = max(1, int(pipeline_stages or 1))
        self._pipeline_schedule = pipeline_schedule
        self._pipeline_microbatches = int(pipeline_microbatches or 0) or (
            2 * self._pipeline_stages
        )
        self._pipeline_vstages = max(1, int(pipeline_virtual_stages or 1))
        self._pipeline_spec_fn = pipeline_spec_fn
        self._pipeline_build = None
        if self._pipeline_stages > 1 and pipeline_spec_fn is None:
            logger.warning(
                "pipeline_stages %d requested but the model spec has no "
                "pipeline_spec hook; running unpipelined",
                self._pipeline_stages,
            )
            self._pipeline_stages = 1
        if self._pipeline_stages > 1:
            if self._model_parallel_size > 1:
                raise ValueError(
                    "pipeline_stages and model_parallel_size cannot be "
                    "combined (both lay out the intra-process device "
                    "slice); pick one"
                )
            if zero1:
                logger.warning(
                    "zero1 is ignored under pipeline parallelism (stage "
                    "params already shard over the stage axis; the "
                    "optimizer layout follows them)"
                )
                zero1 = False
            if quantized_grads:
                logger.warning(
                    "quantized_grads is ignored under pipeline "
                    "parallelism (the data-axis reduction happens inside "
                    "the pipeline's shard_map, which has no quantized "
                    "variant yet)"
                )
                quantized_grads = False
        # Sequence/context parallelism (parallel/ring_attention.py,
        # parallel/ulysses.py): the mesh gains a "seq" axis (intra-process
        # in multi-host worlds, like model/stage) and the TRAIN step runs
        # a mesh-bound variant of the model whose attention is the ring /
        # Ulysses callable from the model spec's context_parallel_model
        # hook. The param tree is identical to the plain model's (the
        # attention carries no params), so init, evaluation, checkpoints
        # and elastic transitions all keep using self._model untouched.
        self._context_parallel_size = max(
            1, int(context_parallel_size or 1)
        )
        self._context_parallel_impl = context_parallel_impl
        self._context_parallel_model_fn = context_parallel_model_fn
        self._sp_model = None  # mesh-bound train model, rebuilt per world
        if (
            self._context_parallel_size > 1
            and context_parallel_model_fn is None
        ):
            logger.warning(
                "context_parallel_size %d requested but the model spec "
                "has no context_parallel_model hook; running without "
                "sequence parallelism", self._context_parallel_size,
            )
            self._context_parallel_size = 1
        # Per-world downgrade bit: a hook rejection that depends on the
        # CURRENT mesh (e.g. ulysses under an active TP head axis) drops
        # the seq axis for that world only — the next world change
        # retries (unlike the pipeline hook, whose rejections are
        # config-determined and permanent).
        self._sp_suspend_once = False
        if self._context_parallel_size > 1:
            if self._pipeline_stages > 1:
                raise ValueError(
                    "context_parallel_size and pipeline_stages cannot "
                    "be combined (no model spec stages a "
                    "sequence-parallel attention); pick one"
                )
            # zero1/quantized_grads are SUSPENDED while the seq axis is
            # active (the SP attention runs its own shard_map, which
            # neither the quantized data-axis step nor the zero-axis
            # factoring nests with yet) — not zeroed: a world where SP
            # drops (indivisible devices) gets them back.
            if zero1:
                logger.warning(
                    "zero1 is suspended while the seq axis is active; "
                    "it applies again in worlds that cannot host "
                    "sequence parallelism"
                )
            if quantized_grads:
                logger.warning(
                    "quantized_grads is suspended while the seq axis "
                    "is active; it applies again in worlds that cannot "
                    "host sequence parallelism"
                )
        # Cross-replica weight-update sharding (ZeRO-1, parallel/zero1.py):
        # optimizer state shards over the data axis (single process) or the
        # intra-process "zero" axis (multi-host — see the module docstring's
        # composition invariant); GSPMD compiles the update as
        # reduce-scatter -> shard-local math -> all-gather. Pure-DP meshes
        # only (under TP the opt layout follows the params).
        self._zero1 = bool(zero1)
        if zero1 and self._model_parallel_size > 1:
            logger.warning(
                "zero1 is ignored when tensor parallelism is active "
                "(the optimizer layout follows the param layout); "
                "per-chip optimizer memory will NOT drop"
            )
        # EQuARX-style int8 gradient allreduce (parallel/quantized.py):
        # the DP step is formulated with shard_map so the data-axis
        # gradient reduction goes through quantized_pmean (int8 wire both
        # legs) instead of XLA's f32 collective. On a {data, zero} mesh
        # only the cross-process data leg quantizes — the intra-host zero
        # reduction stays exact f32 on ICI, which is precisely the
        # EQuARX deployment shape (quantize DCN, not ICI). Composes with
        # TP: shard_map goes manual over the data axis ONLY, the model
        # axis stays automatic so GSPMD keeps the exact Megatron
        # collectives while the data-axis mean of the model-sharded grads
        # quantizes (_quantized_step_fn, TP variant) — the flagship's multi-host
        # DP x intra-host TP shape quantizes exactly its DCN leg.
        self._quantized_grads = bool(quantized_grads)
        self._step_rng_base = jax.random.fold_in(
            jax.random.PRNGKey(seed), 0x5EED
        )
        self._mc = master_client
        self._steps_per_world_check = steps_per_world_check
        self._max_comm_retries = max_comm_retries
        self._multi_host = multi_host
        self._group_id = -1
        self._rank = -1
        self._world_size = 0
        self._mesh = None
        # The resolved WorldSpec of the current mesh: the deterministic
        # identity regroups, compile tokens and speculation key on.
        self._world_spec = None
        # Test/bench seams: pin the topology the resolver sees, and the
        # candidate topologies the speculator guesses (production derives
        # both from the live backend / world size).
        self._topo_override = None
        self._topo_candidates = None
        # Master-announced next world (policy scale events): polled from
        # get_world_hint, consumed as the FIRST speculation candidate so
        # the regroup that follows a policy scale finds its executable
        # prebuilt. 0 = no hint ever seen; poll interval 0 disables.
        self._hint_poll_s = knobs.get_float(
            "ELASTICDL_POLICY_HINT_POLL_SECONDS"
        )
        self._last_hint_poll = 0.0
        self._hint_seq_seen = 0
        self._hinted_world = 0
        self._speculated = set()  # (fingerprint, real_n) already queued
        self._last_batch_abstract = None  # (feat_abs, label_abs, real_n)
        self._speculator = SpeculativeWorldCompiler(self.plan_step_for_spec)
        self._sharded_steps = {}  # real_n -> jitted step
        self._local_forward = None  # multi-host eval path, built lazily
        # Multi-host eval host copy, keyed on (group_id, version): an eval
        # task runs many minibatches against ONE model version, and a
        # fresh jax.device_get per minibatch re-downloads the whole model
        # each time (~0.9 GB for the flagship). One transfer per version.
        self._eval_host_cache = None  # ((group_id, version), host_vars)
        self._steps_since_check = 0
        # Guards the (variables, opt_state, version) triple: the broadcast
        # server reads it from gRPC threads while the training thread swaps
        # it, and a torn read would hand a joiner step-N+1 weights with
        # step-N optimizer moments.
        self._state_lock = threading.Lock()
        # Every worker serves its state; only the rank-0 instance gets pulled
        # from. Port 0 binds an ephemeral port that the worker advertises as
        # part of its host string: the master hands that "ip:port" string out
        # verbatim as coordinator_addr, which is where regrouping workers
        # dial their broadcast pulls.
        self._broadcast_server = broadcast.BroadcastServer(
            self._state_provider, port=broadcast_port
        )
        ip = (master_client.worker_host or "127.0.0.1").split(":")[0]
        master_client.worker_host = f"{ip}:{self._broadcast_server.port}"

    @property
    def broadcast_port(self):
        return self._broadcast_server.port

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def group_id(self):
        """Membership epoch this trainer last joined."""
        return self._group_id

    def restore_variables(self, exported):
        # The broadcast server reads (variables, opt_state, version) from
        # gRPC threads; a checkpoint restore swaps all three, so it must
        # hold the same lock or a regrouping peer could pull checkpoint
        # weights paired with init-time optimizer moments.
        with self._state_lock:
            super().restore_variables(exported)
            # The restored version can collide with the cached one (e.g.
            # resuming the same step the cache was made at, with different
            # weights on disk): drop the eval host copy unconditionally.
            self._eval_host_cache = None
            if self._mesh is not None:
                # Re-shard the restored state per the unified world
                # spec: the base restore places leaves uncommitted
                # (single-device default), which would silently demote a
                # ZeRO-1/TP layout — and cost a first-step reshard —
                # after every checkpoint resume. With the placement done
                # here, a rejoin that restores from checkpoint dispatches
                # its first step against warm executables immediately.
                self._variables = jax.device_put(
                    self._variables,
                    self._variables_sharding(self._variables),
                )
                self._opt_state = jax.device_put(
                    self._opt_state,
                    self._opt_placement(self._opt_state),
                )

    def _state_provider(self):
        # Bounded retry: with buffer donation on the step path there is a
        # microsecond-scale window each step — execution enqueue (which
        # consumes the donated inputs) to the under-lock swap — where the
        # attributes still name deleted arrays. A read landing there
        # succeeds on the next attempt, once the swap publishes the new
        # arrays. Only genuinely poisoned state (async collective
        # failure) exhausts the retries.
        for attempt in range(3):
            with self._state_lock:
                if self._variables is None:
                    return None
                try:
                    return (
                        jax.device_get(self._variables),
                        jax.device_get(self._opt_state),
                        self._version,
                    )
                except Exception:
                    if attempt == 2:
                        # Device arrays poisoned by an async collective
                        # failure: treat local state as lost. Regroup
                        # then falls back to a rank-0 pull (or data
                        # re-seed), instead of crashing the recovery
                        # path itself.
                        logger.warning(
                            "Local state unreadable (poisoned by a "
                            "failed step); discarding for recovery",
                            exc_info=True,
                        )
                        return None
            # Lock RELEASED between attempts: the training thread needs
            # it to complete the swap this read is waiting out.
            time.sleep(0.05 * (attempt + 1))
        return None

    # ---------- world management ----------

    def init_world_if_needed(self, force=False):
        """Poll the master for the current comm world; on membership-epoch
        change, rejoin + rebuild mesh + refresh state from rank 0."""
        resp = self._mc.get_comm_rank()
        if resp.rank_id < 0:
            # Not registered in the group yet: announce and re-poll.
            self._mc.report_liveness()
            resp = self._mc.get_comm_rank()
        if resp.rank_id < 0:
            raise RuntimeError("master did not admit this worker to the group")
        if resp.rendezvous_id == self._group_id and not force:
            return
        logger.info(
            "World change: epoch %d -> %d (rank %d of %d)",
            self._group_id,
            resp.rendezvous_id,
            resp.rank_id,
            resp.world_size,
        )
        if self._multi_host and resp.world_size > 1:
            # Two-phase join: wait at the master's gate until EVERY rank
            # of this epoch is about to initialize, so nobody blocks at a
            # stale epoch's coordination port while a peer is still busy
            # (the missed-rendezvous churn that killed workers with fatal
            # RegisterTask deadlines). If membership moves while waiting,
            # follow it to the new epoch.
            resp = self._await_join_gate(resp)
        self._rank = resp.rank_id
        self._world_size = resp.world_size
        if not force and self._try_fast_regroup(resp):
            return
        # Snapshot to host BEFORE any distributed teardown: device arrays of
        # the old world are unusable once jax.distributed re-initializes.
        host_state = self._state_provider()
        if self._multi_host:
            # Quiesce the speculator BEFORE the backend teardown: an XLA
            # compile still executing on the old PJRT client when
            # ensure_world clears backends is a use-after-teardown race.
            # cancel() first so the drained result is discarded, then a
            # bounded wait for the in-flight compile to finish (compiles
            # cannot be interrupted; the bound mirrors the scale the
            # join gate already tolerates for peers' compiles).
            self._speculator.cancel()
            if not self._speculator.drain(timeout=120.0):
                logger.warning(
                    "A speculative compile is still in flight at "
                    "distributed re-init; proceeding — the stale "
                    "result will be discarded"
                )
            coordinator_ip = resp.coordinator_addr.rsplit(":", 1)[0]
            distributed.ensure_world(
                f"{coordinator_ip}:{resp.rendezvous_port}",
                resp.world_size,
                resp.rank_id,
                epoch=resp.rendezvous_id,
            )
        self._mesh = self._make_world_mesh()
        logger.info("Mesh axes: %s", dict(self._mesh.shape))
        self._sharded_steps = {}
        self._local_forward = None  # compiled against the torn-down backend
        self._rebuild_pipeline_build()
        self._rebind_sp_model()
        # Stamp the new world's fingerprint BEFORE any step (re)lowering:
        # the compile tracker attributes what follows to this regroup
        # (cause=mesh_change) instead of to shape drift. The token is the
        # SPEC fingerprint, not the membership epoch — a later epoch that
        # resolves to a mesh this process already compiled re-lowers as
        # `rebuild` (accurate: the mesh shape did not change), and
        # usually rehydrates from the persistent cache anyway.
        from elasticdl_tpu.observability import profiling

        profiling.note_mesh(
            f"t{self._mesh_salt}:{self._spec_token()}",
            world_size=resp.world_size,
        )
        if self._multi_host and jax.process_count() > 1:
            # SPMD world: sync state through an on-mesh collective that
            # EVERY member executes right after the rendezvous, instead of
            # a host gRPC pull. The pull deadlocks here: rank 0's device
            # stream can already be blocked inside the new world's first
            # collective, so its broadcast server can't serve device reads
            # (single-process-world regroups keep the gRPC path below —
            # they have no shared world to collective over).
            host_state = self._sync_state_over_world(host_state)
        elif self._rank != 0 and resp.coordinator_addr:
            pulled = self._pull_from_rank0(resp.coordinator_addr)
            if pulled is not None:
                host_state = pulled
        if host_state is not None:
            variables, opt_state, version = host_state
            with self._state_lock:
                self._variables = jax.device_put(
                    variables, self._variables_sharding(variables)
                )
                self._opt_state = jax.device_put(
                    opt_state, self._opt_placement(opt_state)
                )
                self._version = version
        elif self._variables is not None:
            # Local device state was unreadable (poisoned by a failed
            # collective) and nothing could be pulled from rank 0: drop it
            # so init_variables_if_needed re-seeds from data instead of
            # replaying poisoned buffers into every retry.
            logger.warning(
                "No recoverable state after world change; re-seeding "
                "variables from data (version %d kept)", self._version,
            )
            with self._state_lock:
                self._variables = None
                self._opt_state = None
        self._group_id = resp.rendezvous_id
        _C_REGROUPS.labels(mode="rebuild").inc()
        emit_event(
            "elastic_regroup",
            mode="rebuild",
            epoch=resp.rendezvous_id,
            spec=self._spec_token(),
            world_size=resp.world_size,
        )
        # Re-aim the speculator at this world's neighbors: guesses for
        # worlds that did NOT form are dropped (a mid-compile guess is
        # discarded when it finishes — never waited on). Prebuilt
        # executables matching the world that DID form survive for
        # _sharded_step_for to consume — but ONLY when the backend was
        # not torn down: a multi-host regroup re-initializes
        # jax.distributed (ensure_world clears all backends), which
        # invalidates every live executable, so there the prebuilts are
        # dropped wholesale and speculation's value is the warm DISK
        # cache entries those compiles wrote.
        self._speculated.clear()
        keep = None if self._multi_host else self._spec_token()
        self._speculator.cancel(keep_fingerprint=keep)
        self._maybe_speculate()

    def _spec_token(self):
        """The current world's spec fingerprint — with a fallback to the
        raw mesh axes for tests that monkeypatch `_make_world_mesh` past
        the spec resolution."""
        if self._world_spec is not None:
            return self._world_spec.fingerprint()
        return str(dict(self._mesh.shape)) if self._mesh else ""

    def _try_fast_regroup(self, resp):
        """The recompile-free regroup: membership moved but the world
        resolves to the SAME spec on a stable backend (no jax.distributed
        re-init), so mesh, compiled steps, and state placement are all
        still valid — adopt the epoch, sync state if this rank is a
        (re)joiner, and keep training. This is the common case for every
        single-host elastic event (peer died / peer joined): the epoch
        bump used to cost a full ~compile-time re-lowering for nothing.
        """
        if self._mesh is None or self._world_spec is None:
            return False
        backend_stable = not self._multi_host or (
            resp.world_size <= 1 and not distributed.is_live()
        )
        if not backend_stable:
            return False
        new_spec = self._resolve_spec()
        if new_spec.fingerprint() != self._world_spec.fingerprint():
            return False
        # A non-zero rank still aligns state with rank 0 — membership
        # changed even though the mesh did not (this worker may BE the
        # rejoiner, or rank 0 may have moved).
        if self._rank != 0 and resp.coordinator_addr:
            pulled = self._pull_from_rank0(resp.coordinator_addr)
            if pulled is not None:
                variables, opt_state, version = pulled
                with self._state_lock:
                    self._variables = jax.device_put(
                        variables, self._variables_sharding(variables)
                    )
                    self._opt_state = jax.device_put(
                        opt_state, self._opt_placement(opt_state)
                    )
                    self._version = version
        self._group_id = resp.rendezvous_id
        # Refresh the tracker's world_size with the SAME token: later
        # compile/compile_cache_hit events carry the new membership
        # without perturbing mesh_change attribution (the token is what
        # classification keys on, and it did not change).
        from elasticdl_tpu.observability import profiling

        profiling.note_mesh(
            f"t{self._mesh_salt}:{self._spec_token()}",
            world_size=resp.world_size,
        )
        _C_REGROUPS.labels(mode="fast").inc()
        emit_event(
            "elastic_regroup",
            mode="fast",
            epoch=resp.rendezvous_id,
            spec=new_spec.fingerprint(),
            world_size=resp.world_size,
        )
        logger.info(
            "World change to epoch %d absorbed without re-mesh "
            "(spec %s unchanged): compiled steps kept",
            resp.rendezvous_id,
            new_spec.fingerprint(),
        )
        self._maybe_speculate()
        return True

    def _await_join_gate(self, resp, timeout=None, poll_seconds=0.25):
        """Poll the master's join gate until the whole world of
        resp.rendezvous_id has arrived (world_ready), following any epoch
        bump to the newest world. Falls through with a warning after
        the budget (e.g. a master predating the gate always answers
        world_ready=False) — the jax.distributed initialization timeout
        then remains the backstop, as before the gate existed.

        timeout=None reads join_gate_budget(): the registered knob, or
        a floor scaled to the longest compile this process has measured
        (the fixed 90 s default lost to ~6.5 s step compiles on loaded
        1-core boxes)."""
        if timeout is None:
            timeout = join_gate_budget()
        deadline = time.time() + timeout
        last_liveness = 0.0
        while time.time() < deadline:
            # The gate can outlast the master's silent-worker watchdog
            # window; an actively-polling worker must not look dead
            # (re-register with the same host is a membership no-op).
            if time.time() - last_liveness > 5.0:
                self._mc.report_liveness()
                last_liveness = time.time()
            gated = self._mc.get_comm_rank(
                ready_epoch=resp.rendezvous_id
            )
            if gated.rendezvous_id != resp.rendezvous_id:
                if gated.rank_id < 0:
                    # Dropped from the group mid-gate (e.g. liveness
                    # timeout); announce and rejoin — paced, not a hot
                    # loop against the master while it churns.
                    self._mc.report_liveness()
                    time.sleep(poll_seconds)
                    continue
                logger.info(
                    "Membership moved at the join gate: epoch %d -> %d "
                    "(rank %d of %d)",
                    resp.rendezvous_id,
                    gated.rendezvous_id,
                    gated.rank_id,
                    gated.world_size,
                )
                resp = gated
                if resp.world_size <= 1:
                    return resp
                continue
            if gated.world_ready:
                return resp
            time.sleep(poll_seconds)
        logger.warning(
            "Join gate for epoch %d did not fill within %.0fs; "
            "proceeding to the rendezvous anyway",
            resp.rendezvous_id,
            timeout,
        )
        return resp

    def _sync_state_over_world(self, host_state):
        """Collective state broadcast from (new-world) rank 0: the TPU-first
        analog of the reference's `broadcast_variables(rank 0)` after a
        Horovod re-rendezvous (allreduce_trainer.py:150-152), expressed as
        XLA collectives over the fresh mesh rather than host RPC. Every
        process contributes its snapshot (zeros when it has none — a fresh
        joiner initialized params from data just for the shapes) and
        receives rank 0's (variables, opt_state, version) triple."""
        from jax.experimental import multihost_utils

        if host_state is None:
            # Poisoned local state (unreadable device buffers). The
            # broadcast is a collective, so this process must still
            # participate — with a zero template of the right shapes it
            # receives rank 0's state like any joiner. Without variables
            # at all there are no shapes to offer; every member hits the
            # same branch only at cold start, where data re-seed follows.
            if self._variables is None:
                return None
            variables = jax.tree_util.tree_map(
                lambda a: np.zeros(a.shape, a.dtype), self._variables
            )
            opt_state = jax.tree_util.tree_map(
                lambda a: np.zeros(
                    getattr(a, "shape", ()), getattr(a, "dtype", np.float32)
                ),
                self._opt_state,
            )
            host_state = (variables, opt_state, 0)
        variables, opt_state, version = host_state
        is_source = jax.process_index() == 0
        synced_vars, synced_opt, synced_version = (
            multihost_utils.broadcast_one_to_all(
                (variables, opt_state, np.int64(version)),
                is_source=is_source,
            )
        )
        version = int(synced_version)
        logger.info(
            "Collective state sync complete (version %d, source rank 0, "
            "this rank %d)",
            version,
            self._rank,
        )
        return (
            jax.tree_util.tree_map(np.asarray, synced_vars),
            jax.tree_util.tree_map(np.asarray, synced_opt),
            version,
        )

    def _pull_from_rank0(self, coordinator_addr):
        if self._variables is None:
            return None  # nothing local to align; init will seed from data
        # treedefs describe containers only — no device transfer needed.
        v_treedef = jax.tree_util.tree_structure(self._variables)
        o_treedef = jax.tree_util.tree_structure(self._opt_state)
        try:
            state = broadcast.pull_state(
                coordinator_addr, v_treedef, o_treedef
            )
        except Exception as e:
            logger.warning(
                "Broadcast pull from %s failed (%s); keeping local state",
                coordinator_addr,
                e,
            )
            return None
        if state is not None:
            logger.info(
                "Pulled rank-0 state (version %d) from %s",
                state[2],
                coordinator_addr,
            )
        return state

    # ---------- mesh / sharding layout (via the unified world spec) ----------

    def _world_topology(self):
        """The topology world resolution sees: the live backend, unless
        a test/bench pinned `_topo_override` to stand in for a world
        this process is not in."""
        if self._topo_override is not None:
            return self._topo_override
        return WorldTopology.current()

    def _parallel_config(self):
        """This trainer's parallel dimensions as the pure config slice
        `resolve_world_spec` consumes — hook presence as booleans, the
        per-world SP downgrade bit included."""
        return ParallelConfig(
            model_parallel=self._model_parallel_size,
            has_param_specs=self._param_specs_fn is not None,
            zero1=self._zero1,
            pipeline_stages=self._pipeline_stages,
            has_pipeline_spec=self._pipeline_spec_fn is not None,
            context_parallel=self._context_parallel_size,
            has_context_parallel_model=(
                self._context_parallel_model_fn is not None
            ),
            sp_suspended=self._sp_suspend_once,
        )

    def _param_check(self, mp):
        if self._variables is None:
            return []
        return self._spec_violations(self._variables, mp)

    def _resolve_spec(self, topo=None):
        """Deterministically resolve the WorldSpec for `topo` (default:
        the current topology) under this trainer's config. Same config +
        same topology always yields the same fingerprint — the property
        the fast regroup path and the speculator are built on."""
        return resolve_world_spec(
            self._parallel_config(),
            topo if topo is not None else self._world_topology(),
            param_check=self._param_check,
        )

    def _make_world_mesh(self):
        spec = self._resolve_spec()
        for note in spec.notes:
            # Degrades stay as loud as the old ad-hoc ladder's warnings:
            # a silently dropped axis is duplicated compute.
            logger.warning("%s", note)
        self._world_spec = spec
        return spec.build_mesh()

    def _spec_violations(self, variables, mp):
        """Sharded dims that don't divide the model-axis size, as human
        messages ([] = layout is valid). Checked before mesh construction
        so misconfiguration degrades to DP instead of dying in jax
        internals with an opaque device_put ValueError."""
        from jax.sharding import PartitionSpec

        specs = self._param_specs_fn(variables)
        sizes = {"model": mp}
        bad = []

        def _check(path, v, s):
            ndim = len(getattr(v, "shape", ()))
            if len(s) > ndim:
                bad.append(
                    f"{'/'.join(str(p) for p in path)}: spec rank "
                    f"{len(s)} exceeds param rank {ndim}"
                )
                return
            for i, axes in enumerate(s):
                if axes is None:
                    continue
                names = axes if isinstance(axes, tuple) else (axes,)
                size = int(
                    np.prod([sizes.get(a, 1) for a in names])
                )
                if size > 1 and v.shape[i] % size:
                    bad.append(
                        f"{'/'.join(str(p) for p in path)}: dim {i} "
                        f"({v.shape[i]}) % {size} != 0"
                    )

        jax.tree_util.tree_map_with_path(
            lambda p, v, s: _check(p, v, s), variables, specs,
            is_leaf=lambda v: isinstance(v, PartitionSpec),
        )
        return bad

    @staticmethod
    def _donation_for(opt_sh, n_processes):
        """The ONE donation rule, shared by the live build and the
        speculative planner so a consumed executable aliases exactly
        like a locally-compiled one. Donate (variables, opt_state) in
        single-process worlds only (multi-process donation would turn a
        failed collective into silent zero-broadcast corruption — see
        the live build's comment). opt_state donation additionally
        requires a PINNED in/out layout: when GSPMD owns it (opt_sh
        None, the TP/pipeline paths) the propagated output layout can't
        alias the replicated input buffer (XLA rejects the size
        mismatch), so only the variables donate there."""
        if n_processes != 1:
            return ()
        return (0,) if opt_sh is None else (0, 1)

    def _opt_placement(self, opt_tree, mesh=None, spec=None):
        """Optimizer-state layout: ZeRO-1 dim-0 sharding when enabled
        (pure DP) — over the whole data axis in a single-process world,
        over the intra-process "zero" axis in a multi-host one —
        replicated otherwise (under TP the initial replication is
        resharded by GSPMD to mirror the param layout after the first
        step). Default: the LIVE world; pass (mesh, spec) to decide for
        a candidate world instead (speculative planning) — one decision
        ladder for both, so the planner cannot drift from the build."""
        live = mesh is None
        if live:
            mesh = self._mesh
            tp_or_sp = self._tp_active() or self._sp_active()
            n_processes = jax.process_count()
        else:
            tp_or_sp = spec.tp > 1 or spec.sp > 1
            n_processes = spec.topology.n_processes
        if self._zero1 and not tp_or_sp:
            from elasticdl_tpu.parallel.zero1 import (
                weight_update_shardings,
            )

            if ZERO_AXIS in mesh.shape:
                axis = ZERO_AXIS
            elif n_processes == 1:
                axis = "data"
            else:
                # Multi-process world whose mesh got no zero axis (one
                # local device per process): dim-0 sharding over the
                # cross-process data axis would make the optimizer state
                # non-fully-addressable and break the regroup snapshot —
                # the exact failure the composition invariant exists to
                # prevent. Replicate instead; there is no intra-process
                # slice to save memory over anyway.
                if live:  # a planner would spam this per candidate
                    logger.warning(
                        "zero1 has no effect in this world: each "
                        "process holds one device, so there is no "
                        "intra-process axis to shard optimizer state "
                        "over"
                    )
                return replicated_sharding(mesh)
            return weight_update_shardings(opt_tree, mesh, axis=axis)
        return replicated_sharding(mesh)

    def _tp_active(self):
        return (
            self._param_specs_fn is not None
            and "model" in self._mesh.shape
            and self._mesh.shape["model"] > 1
        )

    def _pp_active(self):
        """True when the current mesh really hosts the stage axis (the
        scheduled pipeline runs); a staged build on a pure-DP fallback
        mesh trains sequentially instead."""
        return (
            self._pipeline_build is not None
            and STAGE_AXIS in self._mesh.shape
            and self._mesh.shape[STAGE_AXIS] > 1
        )

    def _sp_active(self):
        return (
            self._sp_model is not None
            and SEQ_AXIS in self._mesh.shape
            and self._mesh.shape[SEQ_AXIS] > 1
        )

    def _rebind_sp_model(self):
        """(Re)bind the model spec's context_parallel_model hook to the
        current mesh's seq axis. Only the TRAIN step uses the bound
        model; init/eval/export keep self._model — same param tree, no
        sharding constraints on arbitrary eval batch shapes."""
        self._sp_model = None
        if (
            self._context_parallel_size <= 1
            or self._context_parallel_model_fn is None
            or SEQ_AXIS not in self._mesh.shape
            or self._mesh.shape[SEQ_AXIS] <= 1
        ):
            return
        head_axis = MODEL_AXIS if self._tp_active() else None
        try:
            self._sp_model = self._context_parallel_model_fn(
                mesh=self._mesh,
                axis_name=SEQ_AXIS,
                batch_axis=DATA_AXIS,
                head_axis=head_axis,
                impl=self._context_parallel_impl,
            )
        except ValueError as e:
            # World-scoped, not permanent: the rejection can depend on
            # this mesh (head_axis only exists when TP is active here);
            # the next world change retries the hook fresh.
            logger.warning(
                "context_parallel_model hook rejected this world's "
                "configuration (%s); running without sequence "
                "parallelism for this world — rebuilding a mesh "
                "without the seq axis", e,
            )
            self._sp_suspend_once = True
            try:
                self._mesh = self._make_world_mesh()
            finally:
                self._sp_suspend_once = False
            self._sharded_steps = {}
            logger.info("Mesh axes: %s", dict(self._mesh.shape))

    def _rebuild_pipeline_build(self):
        """(Re)bind the model spec's pipeline_spec hook to the current
        mesh. Runs on every world change — the factories close over the
        mesh. A hook that rejects the configuration (e.g. layer count not
        divisible by the stage count) downgrades to the monolithic model
        permanently: the rejection is config-determined, so every world
        would reject it the same way and the param tree stays consistent
        across regroups."""
        self._pipeline_build = None
        if self._pipeline_stages <= 1 or self._pipeline_spec_fn is None:
            return
        try:
            self._pipeline_build = self._pipeline_spec_fn(
                mesh=self._mesh,
                n_stages=self._pipeline_stages,
                num_microbatches=self._pipeline_microbatches,
                schedule=self._pipeline_schedule,
                batch_axis=DATA_AXIS,
                virtual_stages=self._pipeline_vstages,
            )
        except ValueError as e:
            logger.warning(
                "pipeline_spec hook rejected the configuration (%s); "
                "running the monolithic model data-parallel", e,
            )
            self._pipeline_stages = 1
            # The mesh just built may carry a stage axis the monolithic
            # step would duplicate compute over; rebuild without it (and
            # re-log, so the earlier "Mesh axes" line can't read as
            # pipelining being active).
            self._mesh = self._make_world_mesh()
            self._sharded_steps = {}
            logger.info("Mesh axes: %s", dict(self._mesh.shape))

    def _variables_sharding(self, variables):
        """NamedSharding layout for the variables pytree: the model-spec's
        param_specs when running TP, else replicated."""
        from jax.sharding import NamedSharding, PartitionSpec

        if self._pp_active():
            specs = self._pipeline_build.param_specs_fn(
                variables["params"]
            )
            return {
                "params": jax.tree_util.tree_map(
                    lambda s: NamedSharding(self._mesh, s),
                    specs,
                    is_leaf=lambda v: isinstance(v, PartitionSpec),
                )
            }
        if not self._tp_active():
            return replicated_sharding(self._mesh)
        # Safety net for the rare path where the mesh was built before
        # variables existed: replicate rather than die in device_put.
        # (_make_world_mesh normally rebuilds a pure-DP mesh instead.)
        bad = self._spec_violations(
            variables, self._mesh.shape["model"]
        )
        if bad:
            logger.warning(
                "param_specs incompatible with the current mesh (%s); "
                "replicating params on it — the model axis duplicates "
                "compute until the next world change rebuilds a DP mesh",
                "; ".join(bad[:3]),
            )
            return replicated_sharding(self._mesh)
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self._mesh, s),
            self._param_specs_fn(variables),
            is_leaf=lambda v: isinstance(v, PartitionSpec),
        )

    # ---------- sharded step ----------

    def _sharded_step_for(self, real_n, padded_n):
        # One compiled program per distinct (real_n, padded_n): full batches
        # share one entry; only the final partial minibatch of a task adds
        # variants, so the cache stays small in practice.
        key = (real_n, padded_n)
        step = self._sharded_steps.get(key)
        if step is None and self._world_spec is not None:
            # A speculative guess for exactly this world may already be
            # compiled: consume the executable instead of cold-compiling.
            # Donation semantics ride along — the executable was lowered
            # from the same jit parameters the build below would use.
            fingerprint = self._world_spec.fingerprint()
            prebuilt = self._speculator.take(fingerprint, key)
            if prebuilt is not None:
                logger.info(
                    "Consuming speculatively compiled step for world %s "
                    "%s", fingerprint, key,
                )
                emit_event(
                    "aot_consumed", spec=fingerprint, shape_key=list(key)
                )
                self._sharded_steps[key] = prebuilt
                return prebuilt
        if step is None:
            repl = replicated_sharding(self._mesh)
            data = data_sharding(self._mesh)

            # Slicing padding rows off before the loss keeps partial
            # minibatches bit-identical to single-device training. The
            # slice index is a LOCAL row count, only meaningful when one
            # process owns the whole global batch; in multi-host runs the
            # loss is taken over the full padded global batch instead —
            # padding is cyclic repetition of real rows, so only a task's
            # final partial minibatch is (slightly) reweighted, matching
            # the reference's ragged-last-batch Horovod averaging.
            slice_to = real_n if jax.process_count() == 1 else None

            if self._pipeline_build is not None:
                step_fn = self._pipeline_step_fn()
            elif self._sp_active():
                # Sequence parallelism trains through the mesh-bound
                # attention variant; identical param tree, so everything
                # else (shardings, state, eval) is unchanged. Quantized
                # grads stay suspended on SP worlds (see __init__).
                model = self._sp_model

                def step_fn(variables, opt_state, rng, features, labels):
                    return self._step_body(
                        variables, opt_state, rng, features, labels,
                        slice_to, model=model,
                    )

            elif self._quantized_grads:
                step_fn = self._quantized_step_fn()
            else:

                def step_fn(variables, opt_state, rng, features, labels):
                    return self._step_body(
                        variables, opt_state, rng, features, labels,
                        slice_to,
                    )

            # Donate (variables, opt_state) in single-process worlds:
            # the outputs alias the inputs, so XLA updates the
            # params+moments in place instead of re-allocating both
            # trees every step. After a failed step the donated inputs
            # are gone — which the recovery path already treats as the
            # poisoned-state case (_state_provider answers None; regroup
            # falls back to a rank-0 pull or a data re-seed), and the
            # per-step enqueue->swap window where the attrs briefly name
            # deleted arrays is covered by _state_provider's bounded
            # retry (the swap publishes the new arrays microseconds
            # later).
            # Multi-PROCESS worlds must NOT donate: a failed collective
            # kills every rank's state at once, and the zero-template
            # fallback in _sync_state_over_world would then broadcast
            # rank 0's zeros as the recovered model — donation would
            # turn a recoverable fault into silent corruption there.
            # Under TP, optimizer-state shardings are deliberately
            # unconstrained (None): GSPMD propagation reshards mu/nu to
            # mirror the param layout after the first step (one extra
            # compile when the inferred layout differs from the initial
            # replicated placement). Under ZeRO-1 the state pins to its
            # data-axis dim-0 sharding so the update compiles as
            # reduce-scatter -> shard-local math -> all-gather.
            var_sh = self._variables_sharding(self._variables)
            # Under TP and pipeline, optimizer-state shardings propagate
            # from the param layout (GSPMD); ZeRO-1/replicated otherwise.
            opt_sh = (
                None
                if self._tp_active() or self._pp_active()
                else self._opt_placement(self._opt_state)
            )
            donate = self._donation_for(opt_sh, jax.process_count())
            from elasticdl_tpu.observability.profiling import tracked_jit

            step = tracked_jit(
                step_fn,
                name="allreduce_step",
                key_argnums=(3, 4),
                in_shardings=(var_sh, opt_sh, repl, data, data),
                out_shardings=(var_sh, opt_sh, repl),
                donate_argnums=donate,
            )
            self._sharded_steps[key] = step
        return step

    # ---------- speculative AOT planning ----------

    def plan_step_for_spec(self, spec, real_n):
        """AOT plan for a world this trainer is NOT currently in — the
        speculator's callback. Returns (shape_key, jitted step, abstract
        args) or None when the candidate world's step cannot be planned
        off-world: the pipeline/SP paths are bound to per-world hook
        state (their builds close over the live mesh), and nothing can
        be planned before the first batch reveals its shapes."""
        if self._pipeline_build is not None or self._sp_model is not None:
            return None
        if spec.pp > 1 or spec.sp > 1:
            return None
        if self._variables is None or self._last_batch_abstract is None:
            return None
        mesh = spec.build_mesh()
        repl = replicated_sharding(mesh)
        data = data_sharding(mesh)
        multiple = data_parallel_size(mesh)
        padded_n = -(-real_n // multiple) * multiple
        # Semantics follow the CANDIDATE world's process count, not the
        # live backend's: the plan must compile byte-what the live build
        # would compile once that world forms (slice_to, donation, and
        # the ZeRO axis below all branch on it).
        slice_to = real_n if spec.topology.n_processes == 1 else None
        if self._quantized_grads:
            step_fn = self._quantized_step_fn(
                mesh=mesh, tp=spec.tp > 1
            )
        else:

            def step_fn(variables, opt_state, rng, features, labels):
                return self._step_body(
                    variables, opt_state, rng, features, labels,
                    slice_to,
                )

        var_sh, opt_sh, donate = self._plan_shardings(mesh, spec)
        from elasticdl_tpu.observability.profiling import tracked_jit

        step = tracked_jit(
            step_fn,
            name="allreduce_step",
            key_argnums=(3, 4),
            in_shardings=(var_sh, opt_sh, repl, data, data),
            out_shardings=(var_sh, opt_sh, repl),
            donate_argnums=donate,
        )
        abstract = self._abstract_step_args(padded_n)
        if abstract is None:
            return None
        return (real_n, padded_n), step, abstract

    def _plan_shardings(self, mesh, spec):
        """(variables sharding, opt sharding, donate_argnums) for a
        candidate (mesh, spec): the same decision ladder as the live
        build — opt placement and donation come from the SHARED helpers
        (`_opt_placement` in candidate mode, `_donation_for`), so a
        consumed executable is indistinguishable from a locally-compiled
        one, donation included."""
        from jax.sharding import NamedSharding, PartitionSpec

        tp = spec.tp > 1
        if tp:
            var_sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s),
                self._param_specs_fn(self._variables),
                is_leaf=lambda v: isinstance(v, PartitionSpec),
            )
            opt_sh = None  # GSPMD propagates the param layout
        else:
            var_sh = replicated_sharding(mesh)
            opt_sh = self._opt_placement(
                self._opt_state, mesh=mesh, spec=spec
            )
        donate = self._donation_for(opt_sh, spec.topology.n_processes)
        return var_sh, opt_sh, donate

    def _abstract_step_args(self, padded_n):
        """ShapeDtypeStruct tree for (variables, opt_state, rng,
        features, labels) with the batch re-padded to the candidate
        world's multiple — what `.lower()` needs to compile a step
        without concrete arrays."""

        def abs_of(a):
            shape = tuple(getattr(a, "shape", ()))
            dtype = getattr(a, "dtype", np.float32)
            return jax.ShapeDtypeStruct(shape, dtype)

        def repad(s):
            return jax.ShapeDtypeStruct(
                (padded_n,) + tuple(s.shape[1:]), s.dtype
            )

        feat_abs, label_abs, _ = self._last_batch_abstract
        try:
            return (
                jax.tree_util.tree_map(abs_of, self._variables),
                jax.tree_util.tree_map(abs_of, self._opt_state),
                abs_of(
                    jax.random.fold_in(self._step_rng_base, 0)
                ),
                jax.tree_util.tree_map(repad, feat_abs),
                jax.tree_util.tree_map(repad, label_abs),
            )
        except Exception:  # deleted/odd leaves mid-transition
            return None

    def _note_batch_abstract(self, features, labels, real_n):
        self._last_batch_abstract = (
            jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    tuple(a.shape), a.dtype
                ),
                features,
            ),
            jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    tuple(a.shape), a.dtype
                ),
                labels,
            ),
            real_n,
        )

    def _maybe_speculate(self):
        """Queue AOT compiles for the worlds a regroup is most likely to
        land on next. Cheap when there is nothing to do: candidates are
        deduped per (spec, batch shape) and single-host worlds have no
        candidates at all (their spec is membership-invariant — the fast
        regroup path absorbs epoch bumps for free)."""
        if not speculation_enabled():
            return
        if self._world_spec is None or self._last_batch_abstract is None:
            return
        self._poll_world_hint()
        real_n = self._last_batch_abstract[2]
        current = self._world_spec.fingerprint()
        specs = []
        for topo in self._candidate_topologies():
            # Dedup on (topology, shape) BEFORE resolving: this runs
            # every step, and resolution under TP walks the whole
            # parameter tree (param_check) — pay that once per new
            # candidate, not per minibatch.
            tag = (topo, real_n)
            if tag in self._speculated:
                continue
            self._speculated.add(tag)
            if topo.n_devices < 1 or topo.n_devices > len(jax.devices()):
                # Worlds bigger than the live backend can't be built
                # here; their regroup is covered by the persistent
                # compilation cache instead.
                continue
            try:
                spec = self._resolve_spec(topo)
            except Exception:
                continue
            if spec.fingerprint() == current:
                continue
            specs.append(spec)
        if specs:
            self._speculator.submit(specs, real_n)

    def _poll_world_hint(self):
        """Throttled get_world_hint poll. A new announcement (hint_seq
        advanced) records the target world so _candidate_topologies
        front-loads it — the announced world beats the N±delta guesses."""
        if self._hint_poll_s <= 0:
            return
        now = time.time()
        if now - self._last_hint_poll < self._hint_poll_s:
            return
        self._last_hint_poll = now
        try:
            hint = self._mc.get_world_hint()
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if code == grpc.StatusCode.UNIMPLEMENTED:
                # Pre-policy master: stop asking.
                self._hint_poll_s = 0.0
            return
        except Exception:
            return
        if hint.hint_seq > self._hint_seq_seen:
            self._hint_seq_seen = hint.hint_seq
            self._hinted_world = hint.target_world_size
            logger.info(
                "World hint #%d: target world %d (%s)",
                hint.hint_seq, hint.target_world_size, hint.reason,
            )

    def _candidate_topologies(self):
        if self._topo_candidates is not None:
            return list(self._topo_candidates)
        if not self._multi_host or self._world_size <= 1:
            # Single-host worlds: the mesh is device-determined; every
            # membership epoch resolves to the same spec, so there is
            # nothing to guess.
            return []
        local = jax.local_device_count()
        out = []
        hinted = self._hinted_world
        if hinted >= 1 and hinted != self._world_size:
            # The master TOLD us the next world; compile it first.
            out.append(WorldTopology(hinted * local, local, hinted))
        for delta in range(1, world_deltas() + 1):
            for w in (
                self._world_size - delta, self._world_size + delta
            ):
                if w >= 1 and w != self._world_size and w != hinted:
                    out.append(WorldTopology(w * local, local, w))
        return out

    def _quantized_step_fn(self, mesh=None, tp=None):
        """Step with the data-axis gradient reduction quantized to int8
        (EQuARX-style — see the constructor comment). `mesh`/`tp`
        default to the live world; the speculative planner passes a
        candidate world's instead. Two deployments, one body:

        - Pure DP (possibly factored {data, zero}): shard_map manual over
          every batch axis; any intra-host zero leg reduces exact f32 on
          ICI first, then quantized_pmean over "data" — so on multi-host
          meshes only the cross-process leg quantizes.
        - DP x TP: shard_map goes manual over the DATA axis ONLY
          (jax.shard_map axis_names, EQuARX's own deployment doctrine:
          quantize the slow leg, keep the fast one exact). The model axis
          stays AUTOMATIC, so GSPMD keeps inserting the exact Megatron
          collectives inside each data shard's forward/backward — TP
          activations ride intra-host ICI in f32 — while the cross-shard
          gradient mean (the DCN leg in the flagship's multi-host DP x
          intra-host TP north star) goes through quantized_pmean's int8
          wire.

        Either way the optimizer update runs outside on the reduced
        grads, composing with ZeRO-1's sharded opt state (GSPMD shards
        the update math and all-gathers the params) or resharding to
        mirror the TP param layout. No slice_to: the loss is over the
        whole padded batch, same semantics as the multi-host path
        documented in _sharded_step_for."""
        import optax
        from jax.sharding import PartitionSpec as P

        from elasticdl_tpu.parallel.quantized import quantized_pmean

        mesh = self._mesh if mesh is None else mesh
        tp = self._tp_active() if tp is None else tp
        axes = (DATA_AXIS,) if tp else batch_axes(mesh)
        sm_kwargs = {"axis_names": {DATA_AXIS}} if tp else {}

        def shard_fn(params, state, rng, features, labels):
            # Decorrelate dropout across batch shards only (each holds
            # different rows); under TP the model shards hold the SAME
            # rows and must draw identical masks, which the auto model
            # axis keeps consistent by construction.
            idx = jax.lax.axis_index(axes)
            rng = jax.random.fold_in(rng, idx)
            loss, grads, new_state = self._apply_train(
                params, state, rng, features, labels, None
            )
            if ZERO_AXIS in axes:
                # Intra-host leg stays exact f32 on ICI.
                grads = jax.lax.pmean(grads, ZERO_AXIS)
            # Under TP the shard_map is PARTIAL-auto (model axis stays
            # automatic) and the partitioner can only handle psum-family
            # collectives in the manual subgroup — the all_to_all wire
            # dies in a fatal IsManualSubgroup check (the bug behind the
            # dp_tp_quantized drill's old xfail). psum_lanes keeps the
            # DCN leg quantized (int8 grid in int16 lanes) there.
            grads = quantized_pmean(
                grads, DATA_AXIS,
                collectives="psum_lanes" if tp else "all_to_all",
            )
            loss = jax.lax.pmean(loss, axes)
            if new_state:
                new_state = jax.lax.pmean(new_state, axes)
            return loss, grads, new_state

        def step_fn(variables, opt_state, rng, features, labels):
            params = variables["params"]
            state = {k: v for k, v in variables.items() if k != "params"}
            loss, grads, new_state = shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(), P(), P(), P(axes), P(axes)),
                out_specs=(P(), P(), P()),
                check_vma=False,
                **sm_kwargs,
            )(params, state, rng, features, labels)
            updates, new_opt_state = self._optax.update(
                grads, opt_state, params
            )
            new_params = optax.apply_updates(params, updates)
            return {"params": new_params, **new_state}, new_opt_state, loss

        return step_fn

    def _pipeline_step_fn(self):
        """Training step over the staged param tree: the scheduled
        loss_and_grads when the mesh hosts the stage axis, the
        schedule-free sequential apply (plain DP value_and_grad) when an
        elastic world degraded the mesh to pure data parallelism. Either
        way the optimizer update runs on the same tree, so transitions
        between the two keep (params, opt_state) bit-compatible. The loss
        is over the whole padded batch (cyclic repetition), the same
        ragged-last-batch semantics documented in _sharded_step_for for
        multi-host runs."""
        import optax

        build = self._pipeline_build
        if self._pp_active():
            lg = build.loss_and_grads_fn
        else:
            apply_fn = build.apply_fn

            def lg(params, features, labels, rng=None):
                def loss_of(p):
                    rngs = {"dropout": rng} if rng is not None else None
                    return self._loss_fn(
                        labels,
                        apply_fn(p, features, training=True, rngs=rngs),
                    )

                return jax.value_and_grad(loss_of)(params)

        def step_fn(variables, opt_state, rng, features, labels):
            params = variables["params"]
            loss, grads = lg(params, features, labels, rng)
            updates, new_opt_state = self._optax.update(
                grads, opt_state, params
            )
            new_params = optax.apply_updates(params, updates)
            return {"params": new_params}, new_opt_state, loss

        return step_fn

    def _init_pipeline_variables(self, features):
        """Lazy init for pipeline mode: params come from the build's
        init_fn (staged tree), not self._model.init."""
        import jax.numpy as jnp

        self._rng, init_rng = jax.random.split(self._rng)
        params = self._pipeline_build.init_fn(
            init_rng, jnp.asarray(np.asarray(features))
        )
        variables = {"params": params}
        with self._state_lock:
            self._variables = jax.device_put(
                variables, self._variables_sharding(variables)
            )
            self._opt_state = jax.device_put(
                self._optax.init(self._variables["params"]),
                self._opt_placement(None),
            )
        n_params = sum(
            int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(params)
        )
        logger.info(
            "Initialized pipelined model with %d parameters "
            "(%d stage rows, schedule %s)",
            n_params,
            jax.tree_util.tree_leaves(params["stages"])[0].shape[0],
            self._pipeline_schedule if self._pp_active() else "sequential",
        )
        self._forward = self._build_forward()
        if self.restore_on_init:
            from elasticdl_tpu.common.save_utils import (
                restore_trainer_checkpoint,
            )

            path, self.restore_on_init = self.restore_on_init, None
            restore_trainer_checkpoint(self, path)

    def _build_forward(self):
        if self._pipeline_build is not None:
            from elasticdl_tpu.observability.profiling import tracked_jit

            apply_fn = self._pipeline_build.apply_fn

            def forward(variables, features):
                return apply_fn(
                    variables["params"], features, training=False
                )

            return tracked_jit(
                forward, name="pipeline_forward", key_argnums=(1,)
            )
        return super()._build_forward()

    # ---------- Trainer interface ----------

    def init_variables_if_needed(self, features):
        if self._pipeline_stages > 1:
            if self._mesh is None:
                self.init_world_if_needed(force=True)
                if self._variables is not None:
                    # Restored-before-world state (checkpoint resume):
                    # any forward built before the pipeline build existed
                    # compiled against the monolithic tree — rebuild.
                    self._forward = self._build_forward()
            if self._pipeline_build is not None:
                if self._variables is None:
                    self._init_pipeline_variables(features)
                return
            # The hook rejected the config during world init: fall through
            # to the monolithic path below (stages was reset to 1).
        first_init = self._variables is None
        super().init_variables_if_needed(features)
        if self._mesh is None:
            self.init_world_if_needed(force=True)
        elif first_init:
            # The broadcast server's _state_provider reads (variables,
            # opt_state) as a pair from gRPC threads; replacing them one
            # by one outside the lock can serve a regrouping peer fresh
            # variables paired with stale optimizer moments.
            with self._state_lock:
                self._variables = jax.device_put(
                    self._variables,
                    self._variables_sharding(self._variables),
                )
                self._opt_state = jax.device_put(
                    self._opt_state, self._opt_placement(self._opt_state)
                )

    def train_minibatch(self, features, labels):
        self.init_variables_if_needed(features)
        self._steps_since_check += 1
        sync_step = self._steps_since_check >= self._steps_per_world_check
        if sync_step:
            self._steps_since_check = 0
            with self.timing.record("world_check"):
                self.init_world_if_needed()
        features = jax.tree_util.tree_map(np.asarray, features)
        labels = jax.tree_util.tree_map(np.asarray, labels)
        for attempt in range(self._max_comm_retries):
            try:
                with self.timing.record("sharded_step_dispatch"):
                    loss = self._run_sharded_step(features, labels)
                if sync_step:
                    # Async dispatch means a collective failure surfaces on
                    # materialization, not dispatch. Block here — on the
                    # same cadence as the world check, which already costs
                    # a host round trip — so comm errors land inside this
                    # try block and the re-mesh/retry path below runs,
                    # instead of exploding later at a logging float().
                    # edl-lint: disable=hot-path-sync
                    jax.block_until_ready(loss)
                return True, self._version, loss
            except RETRYABLE_ERRORS:
                if attempt == self._max_comm_retries - 1:
                    raise
                logger.warning(
                    "Sharded step failed (attempt %d); re-checking world",
                    attempt + 1,
                    exc_info=True,
                )
                time.sleep(min(3, 0.1 * 2**attempt))
                self.init_world_if_needed(force=True)

    def train_lease_minibatch(self, features, labels):
        """One SPMD step with NO world check and NO internal retry: in
        step-lease mode every member of the world must dispatch exactly the
        same step sequence, so recovery decisions belong to the lease loop
        (which abandons the lease and re-rendezvouses), not to a per-step
        retry that would desynchronize this rank from its peers."""
        self.init_variables_if_needed(features)
        features = jax.tree_util.tree_map(np.asarray, features)
        labels = jax.tree_util.tree_map(np.asarray, labels)
        return self._run_sharded_step(features, labels)

    def _run_sharded_step(self, features, labels):
        n_data = data_parallel_size(self._mesh)
        multiple = n_data
        if self._pp_active():
            # The pipeline splits the batch into M microbatches, each
            # sharded over the data axis: B must divide by M * dp.
            multiple = n_data * self._pipeline_microbatches
        padded_f, real_n = pad_batch_to_multiple(features, multiple)
        padded_l, _ = pad_batch_to_multiple(labels, multiple)
        padded_n = jax.tree_util.tree_leaves(padded_f)[0].shape[0]
        # Remember this batch's shape signature and (maybe) queue AOT
        # compiles for neighboring worlds — both are cheap bookkeeping;
        # actual speculative compilation runs in the background thread.
        self._note_batch_abstract(features, labels, real_n)
        self._maybe_speculate()
        step = self._sharded_step_for(real_n, padded_n)
        # Derive the dropout key from the SHARED model version, not a local
        # split chain: a joining worker's split count differs from the
        # incumbents', and in multi-host runs the step rng is a replicated
        # jit input that must be bit-identical across processes. version is
        # part of the rank-0 broadcast state, so fold_in(base, version) is
        # history-independent and agrees everywhere.
        step_rng = jax.random.fold_in(self._step_rng_base, self._version)
        # The step call stays OUTSIDE the state lock: a fresh
        # (real_n, padded_n) key compiles here (seconds), and holding
        # the lock across it would stall the broadcast provider past a
        # regrouping peer's pull budget. Donation is still safe: the
        # donated inputs are consumed at execution ENQUEUE — after
        # compile, microseconds before the under-lock swap below — and
        # _state_provider retries across exactly that window.
        with self._mesh:
            new_variables, new_opt_state, loss = step(
                self._variables,
                self._opt_state,
                step_rng,
                shard_batch(padded_f, self._mesh),
                shard_batch(padded_l, self._mesh),
            )
        with self._state_lock:
            self._variables = new_variables
            self._opt_state = new_opt_state
            self._version += 1
            # The eval host copy is stale from this step on; free it now
            # rather than pinning ~model-size host RAM until the next
            # eval task happens to overwrite it.
            self._eval_host_cache = None
        return loss

    def evaluate_minibatch(self, features, model_version=-1):
        if jax.process_count() <= 1:
            return super().evaluate_minibatch(features, model_version)
        # Same lazy-init guard as the base path: a relaunched worker can
        # draw an evaluation task before its first training lease.
        self.init_variables_if_needed(features)
        # Multi-host: the training variables live sharded across the global
        # mesh, but evaluation tasks are dispatched to ONE worker — a
        # global-mesh forward would need every process to participate.
        # Pull a host copy and run the forward on this process's local
        # devices only. The copy is cached keyed on (group_id, version):
        # an eval task's many minibatches all see one model version, and
        # re-downloading the model per minibatch is ~0.9 GB of host
        # transfer each for the flagship. A world change bumps group_id
        # (old-world device arrays are torn down), a train step bumps
        # version — either invalidates.
        with self._state_lock:
            key = (self._group_id, self._version)
            if (
                self._eval_host_cache is not None
                and self._eval_host_cache[0] == key
            ):
                host_vars = self._eval_host_cache[1]
            else:
                host_vars = jax.device_get(self._variables)
                self._eval_host_cache = (key, host_vars)
        if self._local_forward is None:
            from elasticdl_tpu.observability.profiling import tracked_jit

            if self._pipeline_build is not None:
                apply_fn = self._pipeline_build.apply_fn
                self._local_forward = tracked_jit(
                    lambda v, f: apply_fn(
                        v["params"], f, training=False
                    ),
                    name="allreduce_local_forward",
                    key_argnums=(1,),
                )
            else:
                self._local_forward = tracked_jit(
                    lambda v, f: self._model.apply(v, f, training=False),
                    name="allreduce_local_forward",
                    key_argnums=(1,),
                )
        outputs = self._local_forward(
            host_vars, jax.tree_util.tree_map(np.asarray, features)
        )
        return jax.tree_util.tree_map(np.asarray, outputs)

    def close(self):
        self._speculator.stop()
        self._broadcast_server.stop()
        if self._multi_host:
            distributed.leave_world()
