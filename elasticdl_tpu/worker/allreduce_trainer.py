"""Elastic data-parallel trainer over a jax.sharding Mesh.

Reference counterpart: the Horovod AllReduce trainer
(/root/reference/elasticdl/python/worker/allreduce_trainer.py:39-184) and its
rendezvous manager. TPU-first redesign:

- The allreduce itself is NOT hand-written: the train step is jitted with the
  batch sharded along the mesh "data" axis and parameters replicated, so XLA
  inserts the gradient all-reduce as an ICI collective. There is no Horovod
  tape wrapper — gradient averaging falls out of the sharding.
- Elastic membership: the worker polls the master's get_comm_rank every
  `steps_per_world_check` steps (reference checks every 20,
  allreduce_trainer.py:141-148). A changed rendezvous_id means the world
  changed: re-init jax.distributed over the new (coordinator, world, rank),
  rebuild the mesh, recompile, and refresh state from rank 0.
- Rank-0 broadcast: instead of Horovod broadcast_variables, every worker
  runs a tiny gRPC Collective service; after a regroup, non-zero ranks pull
  (variables, opt_state, version) from the rank-0 worker's service
  (parallel/broadcast.py) and overwrite local state.
- Comm failures retry with re-init, up to `max_comm_retries` (reference
  retries <=5 on Horovod UnknownError, allreduce_trainer.py:125-139).
- Hybrid DP x TP (extension; the reference is DP-only): with
  `model_parallel_size > 1` and a model-spec `param_specs(variables)` hook
  (e.g. parallel/tensor_parallel.transformer_param_specs), the mesh gains a
  "model" axis and parameters are laid out by those PartitionSpecs instead
  of replicated — XLA inserts the Megatron-style collectives. Optimizer
  state is left to GSPMD sharding propagation (it mirrors the param layout
  after the first step). If an elastic world change leaves the device count
  indivisible by the model-parallel size, the trainer falls back to pure DP
  for that epoch rather than failing the job. TP is single-host only
  (multi-host TP is rejected at construction: cross-process param shards
  would break the rank-0 state broadcast).
"""

import threading
import time

import grpc
import jax
import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.parallel import broadcast, distributed
from elasticdl_tpu.parallel.mesh import (
    data_sharding,
    make_mesh,
    pad_batch_to_multiple,
    replicated_sharding,
    shard_batch,
)
from elasticdl_tpu.worker.trainer import JaxTrainer

logger = get_logger("worker.allreduce_trainer")

DEFAULT_STEPS_PER_WORLD_CHECK = 20
DEFAULT_MAX_COMM_RETRIES = 5

# What counts as a communication/runtime failure worth a re-mesh + retry.
# XLA/distributed-runtime errors surface as RuntimeError subclasses
# (XlaRuntimeError); master RPCs fail as grpc.RpcError. User-code bugs
# (TypeError/ValueError from tracing a bad model or loss) must NOT retry —
# the reference similarly retried only Horovod comm errors
# (allreduce_trainer.py:125-139).
RETRYABLE_ERRORS = (grpc.RpcError, RuntimeError)


class AllReduceTrainer(JaxTrainer):
    def __init__(
        self,
        model,
        loss_fn,
        optimizer_spec,
        master_client,
        steps_per_world_check=DEFAULT_STEPS_PER_WORLD_CHECK,
        max_comm_retries=DEFAULT_MAX_COMM_RETRIES,
        multi_host=False,
        broadcast_port=0,
        seed=0,
        model_parallel_size=1,
        param_specs_fn=None,
        zero1=False,
    ):
        super().__init__(model, loss_fn, optimizer_spec, seed=seed)
        self._model_parallel_size = max(1, int(model_parallel_size or 1))
        self._param_specs_fn = param_specs_fn
        # Cross-replica weight-update sharding (ZeRO-1, parallel/zero1.py):
        # optimizer state shards over the data axis, GSPMD compiles the
        # update as reduce-scatter -> shard-local math -> all-gather.
        # Pure-DP meshes only (under TP the opt layout follows the params).
        self._zero1 = bool(zero1)
        if zero1 and multi_host:
            # Same failure mode the multi-host TP guard below rejects:
            # dim-0 sharding over a cross-process data axis makes the
            # optimizer state non-fully-addressable, so the host snapshot
            # backing elastic regroups (_state_provider) cannot
            # device_get it — every world change would silently broadcast
            # zeros over all training state.
            raise ValueError(
                "zero1=True is not supported with multi_host=True: "
                "optimizer state sharded across processes breaks the "
                "regroup state snapshot. Use ZeRO-1 within one host "
                "(single process, multiple chips) or pure DP across "
                "hosts."
            )
        if zero1 and self._model_parallel_size > 1:
            logger.warning(
                "zero1 is ignored when tensor parallelism is active "
                "(the optimizer layout follows the param layout); "
                "per-chip optimizer memory will NOT drop"
            )
        if multi_host and self._model_parallel_size > 1:
            # Multi-host TP would shard params across processes, making
            # them non-fully-addressable — the host-side state snapshot
            # that backs rank-0 broadcast (_state_provider) cannot
            # device_get such arrays, so every elastic regroup would
            # silently discard progress. Gathering inside the snapshot is
            # a collective and _state_provider runs on rank 0's gRPC
            # thread alone, so it cannot be done there. Refuse loudly
            # until the broadcast path grows a sharded-pull protocol.
            raise ValueError(
                "model_parallel_size > 1 is not supported with "
                "multi_host=True: params sharded across processes break "
                "the rank-0 state broadcast. Run TP within one host "
                "(single process, multiple chips) or use pure DP "
                "across hosts."
            )
        self._step_rng_base = jax.random.fold_in(
            jax.random.PRNGKey(seed), 0x5EED
        )
        self._mc = master_client
        self._steps_per_world_check = steps_per_world_check
        self._max_comm_retries = max_comm_retries
        self._multi_host = multi_host
        self._group_id = -1
        self._rank = -1
        self._world_size = 0
        self._mesh = None
        self._sharded_steps = {}  # real_n -> jitted step
        self._local_forward = None  # multi-host eval path, built lazily
        self._steps_since_check = 0
        # Guards the (variables, opt_state, version) triple: the broadcast
        # server reads it from gRPC threads while the training thread swaps
        # it, and a torn read would hand a joiner step-N+1 weights with
        # step-N optimizer moments.
        self._state_lock = threading.Lock()
        # Every worker serves its state; only the rank-0 instance gets pulled
        # from. Port 0 binds an ephemeral port that the worker advertises as
        # part of its host string: the master hands that "ip:port" string out
        # verbatim as coordinator_addr, which is where regrouping workers
        # dial their broadcast pulls.
        self._broadcast_server = broadcast.BroadcastServer(
            self._state_provider, port=broadcast_port
        )
        ip = (master_client.worker_host or "127.0.0.1").split(":")[0]
        master_client.worker_host = f"{ip}:{self._broadcast_server.port}"

    @property
    def broadcast_port(self):
        return self._broadcast_server.port

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def group_id(self):
        """Membership epoch this trainer last joined."""
        return self._group_id

    def restore_variables(self, exported):
        # The broadcast server reads (variables, opt_state, version) from
        # gRPC threads; a checkpoint restore swaps all three, so it must
        # hold the same lock or a regrouping peer could pull checkpoint
        # weights paired with init-time optimizer moments.
        with self._state_lock:
            super().restore_variables(exported)

    def _state_provider(self):
        with self._state_lock:
            if self._variables is None:
                return None
            try:
                return (
                    jax.device_get(self._variables),
                    jax.device_get(self._opt_state),
                    self._version,
                )
            except Exception:
                # Device arrays poisoned by an async collective failure:
                # treat local state as lost. Regroup then falls back to a
                # rank-0 pull (or data re-seed), instead of crashing the
                # recovery path itself.
                logger.warning(
                    "Local state unreadable (poisoned by a failed step); "
                    "discarding for recovery", exc_info=True,
                )
                return None

    # ---------- world management ----------

    def init_world_if_needed(self, force=False):
        """Poll the master for the current comm world; on membership-epoch
        change, rejoin + rebuild mesh + refresh state from rank 0."""
        resp = self._mc.get_comm_rank()
        if resp.rank_id < 0:
            # Not registered in the group yet: announce and re-poll.
            self._mc.report_liveness()
            resp = self._mc.get_comm_rank()
        if resp.rank_id < 0:
            raise RuntimeError("master did not admit this worker to the group")
        if resp.rendezvous_id == self._group_id and not force:
            return
        logger.info(
            "World change: epoch %d -> %d (rank %d of %d)",
            self._group_id,
            resp.rendezvous_id,
            resp.rank_id,
            resp.world_size,
        )
        self._rank = resp.rank_id
        self._world_size = resp.world_size
        # Snapshot to host BEFORE any distributed teardown: device arrays of
        # the old world are unusable once jax.distributed re-initializes.
        host_state = self._state_provider()
        if self._multi_host:
            coordinator_ip = resp.coordinator_addr.rsplit(":", 1)[0]
            distributed.ensure_world(
                f"{coordinator_ip}:{resp.rendezvous_port}",
                resp.world_size,
                resp.rank_id,
                epoch=resp.rendezvous_id,
            )
        self._mesh = self._make_world_mesh()
        self._sharded_steps = {}
        self._local_forward = None  # compiled against the torn-down backend
        if self._multi_host and jax.process_count() > 1:
            # SPMD world: sync state through an on-mesh collective that
            # EVERY member executes right after the rendezvous, instead of
            # a host gRPC pull. The pull deadlocks here: rank 0's device
            # stream can already be blocked inside the new world's first
            # collective, so its broadcast server can't serve device reads
            # (single-process-world regroups keep the gRPC path below —
            # they have no shared world to collective over).
            host_state = self._sync_state_over_world(host_state)
        elif self._rank != 0 and resp.coordinator_addr:
            pulled = self._pull_from_rank0(resp.coordinator_addr)
            if pulled is not None:
                host_state = pulled
        if host_state is not None:
            variables, opt_state, version = host_state
            with self._state_lock:
                self._variables = jax.device_put(
                    variables, self._variables_sharding(variables)
                )
                self._opt_state = jax.device_put(
                    opt_state, self._opt_placement(opt_state)
                )
                self._version = version
        elif self._variables is not None:
            # Local device state was unreadable (poisoned by a failed
            # collective) and nothing could be pulled from rank 0: drop it
            # so init_variables_if_needed re-seeds from data instead of
            # replaying poisoned buffers into every retry.
            logger.warning(
                "No recoverable state after world change; re-seeding "
                "variables from data (version %d kept)", self._version,
            )
            with self._state_lock:
                self._variables = None
                self._opt_state = None
        self._group_id = resp.rendezvous_id

    def _sync_state_over_world(self, host_state):
        """Collective state broadcast from (new-world) rank 0: the TPU-first
        analog of the reference's `broadcast_variables(rank 0)` after a
        Horovod re-rendezvous (allreduce_trainer.py:150-152), expressed as
        XLA collectives over the fresh mesh rather than host RPC. Every
        process contributes its snapshot (zeros when it has none — a fresh
        joiner initialized params from data just for the shapes) and
        receives rank 0's (variables, opt_state, version) triple."""
        from jax.experimental import multihost_utils

        if host_state is None:
            # Poisoned local state (unreadable device buffers). The
            # broadcast is a collective, so this process must still
            # participate — with a zero template of the right shapes it
            # receives rank 0's state like any joiner. Without variables
            # at all there are no shapes to offer; every member hits the
            # same branch only at cold start, where data re-seed follows.
            if self._variables is None:
                return None
            variables = jax.tree_util.tree_map(
                lambda a: np.zeros(a.shape, a.dtype), self._variables
            )
            opt_state = jax.tree_util.tree_map(
                lambda a: np.zeros(
                    getattr(a, "shape", ()), getattr(a, "dtype", np.float32)
                ),
                self._opt_state,
            )
            host_state = (variables, opt_state, 0)
        variables, opt_state, version = host_state
        is_source = jax.process_index() == 0
        synced_vars, synced_opt, synced_version = (
            multihost_utils.broadcast_one_to_all(
                (variables, opt_state, np.int64(version)),
                is_source=is_source,
            )
        )
        version = int(synced_version)
        logger.info(
            "Collective state sync complete (version %d, source rank 0, "
            "this rank %d)",
            version,
            self._rank,
        )
        return (
            jax.tree_util.tree_map(np.asarray, synced_vars),
            jax.tree_util.tree_map(np.asarray, synced_opt),
            version,
        )

    def _pull_from_rank0(self, coordinator_addr):
        if self._variables is None:
            return None  # nothing local to align; init will seed from data
        # treedefs describe containers only — no device transfer needed.
        v_treedef = jax.tree_util.tree_structure(self._variables)
        o_treedef = jax.tree_util.tree_structure(self._opt_state)
        try:
            state = broadcast.pull_state(
                coordinator_addr, v_treedef, o_treedef
            )
        except Exception as e:
            logger.warning(
                "Broadcast pull from %s failed (%s); keeping local state",
                coordinator_addr,
                e,
            )
            return None
        if state is not None:
            logger.info(
                "Pulled rank-0 state (version %d) from %s",
                state[2],
                coordinator_addr,
            )
        return state

    # ---------- mesh / sharding layout ----------

    def _make_world_mesh(self):
        mp = self._model_parallel_size
        n = len(jax.devices())
        if mp > 1 and self._param_specs_fn is None:
            # A model axis without param layouts would just duplicate the
            # same DP computation mp times — half (or worse) of the
            # cluster doing redundant work. Take the DP fallback instead.
            logger.warning(
                "model_parallel_size %d requested but the model spec has "
                "no param_specs hook; falling back to pure data "
                "parallelism", mp,
            )
        elif mp > 1 and n % mp != 0:
            logger.warning(
                "model_parallel_size %d does not divide %d devices; "
                "falling back to pure data parallelism for this world",
                mp, n,
            )
        elif mp > 1:
            bad = (
                self._spec_violations(self._variables, mp)
                if self._variables is not None
                else []
            )
            if bad:
                # Keeping a (data=n/mp, model=mp) mesh with replicated
                # params would silently run mp-way duplicated compute;
                # rebuild a genuine pure-DP mesh instead.
                logger.warning(
                    "param_specs incompatible with model_parallel_size "
                    "%d (%s); falling back to pure data parallelism",
                    mp, "; ".join(bad[:3]),
                )
            else:
                from elasticdl_tpu.parallel.mesh import (
                    DATA_AXIS,
                    MODEL_AXIS,
                )

                return make_mesh({DATA_AXIS: -1, MODEL_AXIS: mp})
        return make_mesh()

    def _spec_violations(self, variables, mp):
        """Sharded dims that don't divide the model-axis size, as human
        messages ([] = layout is valid). Checked before mesh construction
        so misconfiguration degrades to DP instead of dying in jax
        internals with an opaque device_put ValueError."""
        from jax.sharding import PartitionSpec

        specs = self._param_specs_fn(variables)
        sizes = {"model": mp}
        bad = []

        def _check(path, v, s):
            ndim = len(getattr(v, "shape", ()))
            if len(s) > ndim:
                bad.append(
                    f"{'/'.join(str(p) for p in path)}: spec rank "
                    f"{len(s)} exceeds param rank {ndim}"
                )
                return
            for i, axes in enumerate(s):
                if axes is None:
                    continue
                names = axes if isinstance(axes, tuple) else (axes,)
                size = int(
                    np.prod([sizes.get(a, 1) for a in names])
                )
                if size > 1 and v.shape[i] % size:
                    bad.append(
                        f"{'/'.join(str(p) for p in path)}: dim {i} "
                        f"({v.shape[i]}) % {size} != 0"
                    )

        jax.tree_util.tree_map_with_path(
            lambda p, v, s: _check(p, v, s), variables, specs,
            is_leaf=lambda v: isinstance(v, PartitionSpec),
        )
        return bad

    def _opt_placement(self, opt_tree):
        """Optimizer-state layout on the current mesh: ZeRO-1 dim-0
        sharding over the data axis when enabled (pure DP), replicated
        otherwise (under TP the initial replication is resharded by GSPMD
        to mirror the param layout after the first step)."""
        if self._zero1 and not self._tp_active():
            from elasticdl_tpu.parallel.zero1 import (
                weight_update_shardings,
            )

            return weight_update_shardings(opt_tree, self._mesh)
        return replicated_sharding(self._mesh)

    def _tp_active(self):
        return (
            self._param_specs_fn is not None
            and "model" in self._mesh.shape
            and self._mesh.shape["model"] > 1
        )

    def _variables_sharding(self, variables):
        """NamedSharding layout for the variables pytree: the model-spec's
        param_specs when running TP, else replicated."""
        from jax.sharding import NamedSharding, PartitionSpec

        if not self._tp_active():
            return replicated_sharding(self._mesh)
        # Safety net for the rare path where the mesh was built before
        # variables existed: replicate rather than die in device_put.
        # (_make_world_mesh normally rebuilds a pure-DP mesh instead.)
        bad = self._spec_violations(
            variables, self._mesh.shape["model"]
        )
        if bad:
            logger.warning(
                "param_specs incompatible with the current mesh (%s); "
                "replicating params on it — the model axis duplicates "
                "compute until the next world change rebuilds a DP mesh",
                "; ".join(bad[:3]),
            )
            return replicated_sharding(self._mesh)
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self._mesh, s),
            self._param_specs_fn(variables),
            is_leaf=lambda v: isinstance(v, PartitionSpec),
        )

    # ---------- sharded step ----------

    def _sharded_step_for(self, real_n, padded_n):
        # One compiled program per distinct (real_n, padded_n): full batches
        # share one entry; only the final partial minibatch of a task adds
        # variants, so the cache stays small in practice.
        key = (real_n, padded_n)
        step = self._sharded_steps.get(key)
        if step is None:
            repl = replicated_sharding(self._mesh)
            data = data_sharding(self._mesh)

            # Slicing padding rows off before the loss keeps partial
            # minibatches bit-identical to single-device training. The
            # slice index is a LOCAL row count, only meaningful when one
            # process owns the whole global batch; in multi-host runs the
            # loss is taken over the full padded global batch instead —
            # padding is cyclic repetition of real rows, so only a task's
            # final partial minibatch is (slightly) reweighted, matching
            # the reference's ragged-last-batch Horovod averaging.
            slice_to = real_n if jax.process_count() == 1 else None

            def step_fn(variables, opt_state, rng, features, labels):
                return self._step_body(
                    variables, opt_state, rng, features, labels, slice_to
                )

            # No buffer donation here (unlike the local trainer): a comm
            # failure mid-step must leave (variables, opt_state) intact for
            # the retry/re-mesh path — donated buffers would already be
            # invalidated when the except branch snapshots state.
            # Under TP, optimizer-state shardings are deliberately
            # unconstrained (None): GSPMD propagation reshards mu/nu to
            # mirror the param layout after the first step (one extra
            # compile when the inferred layout differs from the initial
            # replicated placement). Under ZeRO-1 the state pins to its
            # data-axis dim-0 sharding so the update compiles as
            # reduce-scatter -> shard-local math -> all-gather.
            var_sh = self._variables_sharding(self._variables)
            opt_sh = (
                None
                if self._tp_active()
                else self._opt_placement(self._opt_state)
            )
            step = jax.jit(
                step_fn,
                in_shardings=(var_sh, opt_sh, repl, data, data),
                out_shardings=(var_sh, opt_sh, repl),
            )
            self._sharded_steps[key] = step
        return step

    # ---------- Trainer interface ----------

    def init_variables_if_needed(self, features):
        first_init = self._variables is None
        super().init_variables_if_needed(features)
        if self._mesh is None:
            self.init_world_if_needed(force=True)
        elif first_init:
            self._variables = jax.device_put(
                self._variables, self._variables_sharding(self._variables)
            )
            self._opt_state = jax.device_put(
                self._opt_state, self._opt_placement(self._opt_state)
            )

    def train_minibatch(self, features, labels):
        self.init_variables_if_needed(features)
        self._steps_since_check += 1
        sync_step = self._steps_since_check >= self._steps_per_world_check
        if sync_step:
            self._steps_since_check = 0
            with self.timing.record("world_check"):
                self.init_world_if_needed()
        features = jax.tree_util.tree_map(np.asarray, features)
        labels = jax.tree_util.tree_map(np.asarray, labels)
        for attempt in range(self._max_comm_retries):
            try:
                with self.timing.record("sharded_step_dispatch"):
                    loss = self._run_sharded_step(features, labels)
                if sync_step:
                    # Async dispatch means a collective failure surfaces on
                    # materialization, not dispatch. Block here — on the
                    # same cadence as the world check, which already costs
                    # a host round trip — so comm errors land inside this
                    # try block and the re-mesh/retry path below runs,
                    # instead of exploding later at a logging float().
                    jax.block_until_ready(loss)
                return True, self._version, loss
            except RETRYABLE_ERRORS:
                if attempt == self._max_comm_retries - 1:
                    raise
                logger.warning(
                    "Sharded step failed (attempt %d); re-checking world",
                    attempt + 1,
                    exc_info=True,
                )
                time.sleep(min(3, 0.1 * 2**attempt))
                self.init_world_if_needed(force=True)

    def train_lease_minibatch(self, features, labels):
        """One SPMD step with NO world check and NO internal retry: in
        step-lease mode every member of the world must dispatch exactly the
        same step sequence, so recovery decisions belong to the lease loop
        (which abandons the lease and re-rendezvouses), not to a per-step
        retry that would desynchronize this rank from its peers."""
        self.init_variables_if_needed(features)
        features = jax.tree_util.tree_map(np.asarray, features)
        labels = jax.tree_util.tree_map(np.asarray, labels)
        return self._run_sharded_step(features, labels)

    def _run_sharded_step(self, features, labels):
        n_data = self._mesh.shape["data"]
        padded_f, real_n = pad_batch_to_multiple(features, n_data)
        padded_l, _ = pad_batch_to_multiple(labels, n_data)
        padded_n = jax.tree_util.tree_leaves(padded_f)[0].shape[0]
        step = self._sharded_step_for(real_n, padded_n)
        # Derive the dropout key from the SHARED model version, not a local
        # split chain: a joining worker's split count differs from the
        # incumbents', and in multi-host runs the step rng is a replicated
        # jit input that must be bit-identical across processes. version is
        # part of the rank-0 broadcast state, so fold_in(base, version) is
        # history-independent and agrees everywhere.
        step_rng = jax.random.fold_in(self._step_rng_base, self._version)
        with self._mesh:
            new_variables, new_opt_state, loss = step(
                self._variables,
                self._opt_state,
                step_rng,
                shard_batch(padded_f, self._mesh),
                shard_batch(padded_l, self._mesh),
            )
        with self._state_lock:
            self._variables = new_variables
            self._opt_state = new_opt_state
            self._version += 1
        return loss

    def evaluate_minibatch(self, features, model_version=-1):
        if jax.process_count() <= 1:
            return super().evaluate_minibatch(features, model_version)
        # Same lazy-init guard as the base path: a relaunched worker can
        # draw an evaluation task before its first training lease.
        self.init_variables_if_needed(features)
        # Multi-host: the training variables live sharded across the global
        # mesh, but evaluation tasks are dispatched to ONE worker — a
        # global-mesh forward would need every process to participate.
        # Pull a host copy and run the forward on this process's local
        # devices only (eval is forward-only and rare; the copy is cheap
        # next to a lease of training steps).
        with self._state_lock:
            host_vars = jax.device_get(self._variables)
        if self._local_forward is None:
            self._local_forward = jax.jit(
                lambda v, f: self._model.apply(v, f, training=False)
            )
        outputs = self._local_forward(
            host_vars, jax.tree_util.tree_map(np.asarray, features)
        )
        return jax.tree_util.tree_map(np.asarray, outputs)

    def close(self):
        self._broadcast_server.stop()
        if self._multi_host:
            distributed.leave_world()
