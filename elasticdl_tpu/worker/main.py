"""`python -m elasticdl_tpu.worker.main` — worker process entrypoint
(reference /root/reference/elasticdl/python/worker/main.py:28-82)."""

import sys

from elasticdl_tpu import observability
from elasticdl_tpu.common.args import validate_args, worker_parser
from elasticdl_tpu.common.constants import DistributionStrategy, JobType
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker

logger = get_logger("worker.main")

_JOB_TYPES = {
    "training_only": JobType.TRAINING_ONLY,
    "training_with_evaluation": JobType.TRAINING_WITH_EVALUATION,
    "evaluation_only": JobType.EVALUATION_ONLY,
    "prediction_only": JobType.PREDICTION_ONLY,
}


def build_trainer(args, spec, master_client):
    model = spec.build_model()
    optimizer_spec = spec.build_optimizer_spec()
    strategy = args.distribution_strategy
    if strategy == DistributionStrategy.PARAMETER_SERVER:
        from elasticdl_tpu.worker.ps_client import PSClient
        from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer

        if not args.ps_addrs:
            raise ValueError("ParameterServerStrategy requires --ps_addrs")
        return ParameterServerTrainer(
            model,
            spec.loss,
            optimizer_spec,
            PSClient(
                args.ps_addrs.split(","),
                worker_id=args.worker_id,
                wire_dtype=args.ps_wire_dtype,
            ),
            embedding_inputs=getattr(spec.module, "embedding_inputs", None),
            embedding_threshold_bytes=getattr(
                spec.module, "embedding_threshold_bytes", None
            ),
            embedding_device_capacity_bytes=getattr(
                spec.module, "embedding_device_capacity_bytes", 0
            ),
            seed=args.seed,
            model_steps=args.get_model_steps,
        )
    if strategy == DistributionStrategy.ALLREDUCE:
        from elasticdl_tpu.worker.allreduce_trainer import AllReduceTrainer

        return AllReduceTrainer(
            model,
            spec.loss,
            optimizer_spec,
            master_client,
            multi_host=args.multi_host,
            seed=args.seed,
            model_parallel_size=args.model_parallel_size,
            param_specs_fn=getattr(spec.module, "param_specs", None),
            zero1=args.zero1,
            quantized_grads=args.quantized_grads,
            pipeline_stages=args.pipeline_stages,
            pipeline_schedule=args.pipeline_schedule,
            pipeline_microbatches=args.pipeline_microbatches,
            pipeline_virtual_stages=args.pipeline_virtual_stages,
            pipeline_spec_fn=getattr(spec.module, "pipeline_spec", None),
            context_parallel_size=args.context_parallel_size,
            context_parallel_impl=args.context_parallel_impl,
            context_parallel_model_fn=getattr(
                spec.module, "context_parallel_model", None
            ),
        )
    from elasticdl_tpu.worker.trainer import LocalTrainer

    return LocalTrainer(model, spec.loss, optimizer_spec, seed=args.seed)


def main(argv=None):
    args = worker_parser().parse_args(argv)
    validate_args(args)
    obs = observability.setup(
        role=f"worker-{args.worker_id}", job=args.job_name
    )
    if args.model_zoo:
        sys.path.insert(0, args.model_zoo)
    spec = get_model_spec(args.model_def)
    job_type = _JOB_TYPES[args.job_type]
    reader_factory = spec.create_data_reader or create_data_reader
    if job_type == JobType.PREDICTION_ONLY:
        origins = [args.prediction_data]
    else:
        origins = [
            o for o in (args.training_data, args.validation_data) if o
        ]
    if len(origins) == 1:
        reader = reader_factory(origins[0])
    else:
        # Training + validation are distinct origins: route each task to
        # the reader owning its shard (see CompositeReader).
        from elasticdl_tpu.data.reader import CompositeReader

        reader = CompositeReader([reader_factory(o) for o in origins])
    if args.prefetch_records > 0:
        from elasticdl_tpu.data.prefetch import PrefetchReader

        reader = PrefetchReader(reader, buffer_records=args.prefetch_records)
    mc = MasterClient(
        args.master_addr, args.worker_id, worker_host=args.worker_host
    )
    trainer = build_trainer(args, spec, mc)
    extra_callbacks = []
    if args.output:
        from elasticdl_tpu.common.save_utils import ExportModelCallback

        extra_callbacks.append(ExportModelCallback(args.output))
    if args.checkpoint_dir_for_init and args.distribution_strategy != (
        DistributionStrategy.PARAMETER_SERVER
    ):
        # Worker-side restore for local/AllReduce: the PS strategy restores
        # server-side instead (ps/checkpoint.py). Applied right after the
        # trainer's lazy init on the first batch.
        trainer.restore_on_init = args.checkpoint_dir_for_init
    profile_dir = ""
    if args.profile_dir:
        # Per-worker subdir: concurrent workers on one host must not
        # interleave trace events in a single profile directory.
        import os

        profile_dir = os.path.join(
            args.profile_dir, f"worker{args.worker_id}"
        )
    worker = Worker(
        args.worker_id,
        mc,
        reader,
        spec,
        trainer,
        minibatch_size=args.minibatch_size,
        job_type=job_type,
        log_loss_steps=args.log_loss_steps,
        extra_callbacks=extra_callbacks,
        profile_dir=profile_dir,
        profile_start_step=args.profile_start_step,
        profile_steps=args.profile_steps,
        # Multi-host AllReduce trains through step-synchronized leases:
        # every process of the SPMD world must run the same step count.
        lease_mode=(
            args.distribution_strategy == DistributionStrategy.ALLREDUCE
            and args.multi_host
        ),
    )
    # Push-based telemetry (opt-in via ELASTICDL_TELEMETRY_PUSH_INTERVAL):
    # while the reporter's pushes stay fresh the master's aggregator stops
    # pull-scraping this worker's /metrics endpoint.
    from elasticdl_tpu.observability.metrics import default_registry
    from elasticdl_tpu.observability.push import TelemetryReporter

    reporter = TelemetryReporter(
        mc.report_telemetry,
        default_registry(),
        role=f"worker-{args.worker_id}",
        seed=args.worker_id,
    ).start()
    try:
        worker.run()
    finally:
        # Leave any distributed world deterministically: interpreter-exit
        # shutdown from N processes at scattered times fails the shutdown
        # barrier and crashes the slowest peer.
        close = getattr(trainer, "close", None)
        if close is not None:
            close()
        reporter.close()
        obs.close()
    logger.info("Worker %d exiting", args.worker_id)
    return 0


if __name__ == "__main__":
    sys.exit(main())
