"""Trainer abstraction + the local (single-process) JAX trainer.

Reference counterpart: the Trainer ABC and eager/`tf.function` training paths
(/root/reference/elasticdl/python/worker/trainer.py:17-56,
worker/ps_trainer.py:388-401). TPU-first redesign: the step is a pure jitted
function over an explicit (variables, opt_state) pytree — XLA fuses the
forward, backward and optimizer update into one program, and the same step
function is reused by the AllReduce trainer under shard_map.
"""

from abc import ABC, abstractmethod

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import datapath

logger = get_logger("worker.trainer")


class Trainer(ABC):
    """What the worker loop needs from any training strategy."""

    @abstractmethod
    def init_variables_if_needed(self, features):
        ...

    @abstractmethod
    def train_minibatch(self, features, labels):
        """Returns (accepted: bool, model_version: int, loss).

        `loss` is a float-convertible scalar. On-device strategies return a
        lazy jax array so the host never blocks on the step; callers must
        only materialize it (float()) when they actually log it, keeping
        steps dispatch-ahead on TPU."""

    @abstractmethod
    def evaluate_minibatch(self, features, model_version=-1):
        """Forward pass; returns model outputs (numpy)."""

    def predict_minibatch(self, features):
        return self.evaluate_minibatch(features)

    @abstractmethod
    def get_model_version(self) -> int:
        ...

    def export_variables(self):
        """Checkpointable state; override where meaningful."""
        return None


def _to_device_batch(features):
    """numpy batch (array or dict pytree) -> jnp arrays."""
    return jax.tree_util.tree_map(jnp.asarray, features)


class JaxTrainer(Trainer):
    """Shared JAX machinery: lazy variable init, jitted train/forward steps.

    Subclasses override `_build_train_step` / `_build_forward` to insert
    collectives (AllReduce) or parameter-exchange hooks (PS).
    """

    def __init__(self, model, loss_fn, optimizer_spec, seed=0):
        # Persistent compilation cache (recompile-free elasticity):
        # wired before the first jit so even bare trainers (tests,
        # benches) rehydrate executables when the knob names a dir.
        from elasticdl_tpu.common.compile_cache import (
            ensure_compile_cache,
        )

        ensure_compile_cache()
        self._model = model
        self._loss_fn = loss_fn
        self._optimizer_spec = optimizer_spec
        self._optax = optimizer_spec.to_optax()
        self._rng = jax.random.PRNGKey(seed)
        self._variables = None
        self._opt_state = None
        self._version = 0
        self._train_step = None
        self._forward = None
        # Checkpoint path to restore from right after lazy init (worker-side
        # resume for strategies whose state lives in the worker).
        self.restore_on_init = None
        # Step-phase breakdown, reported per task at DEBUG by the worker
        # loop (reference timing_utils.py usage in ps_trainer/worker).
        from elasticdl_tpu.common.timing import Timing

        self.timing = Timing()
        # Per-step MFU estimate (observability/mfu.py): FLOPs from the
        # jitted step's cost analysis, period from successive steps.
        from elasticdl_tpu.observability.mfu import StepCostModel

        self.step_cost = StepCostModel()

    # ---------- init ----------

    def init_variables_if_needed(self, features):
        if self._variables is not None:
            return
        self._rng, init_rng = jax.random.split(self._rng)
        device_features = _to_device_batch(features)
        variables = self._model.init(
            {"params": init_rng, "dropout": init_rng},
            device_features,
            training=False,
        )
        self._variables = jax.tree_util.tree_map(jnp.asarray, dict(variables))
        self._opt_state = self._optax.init(self._variables["params"])
        n_params = sum(
            int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(self._variables["params"])
        )
        logger.info("Initialized model with %d parameters", n_params)
        self._train_step = self._build_train_step()
        self._forward = self._build_forward()
        if self.restore_on_init:
            from elasticdl_tpu.common.save_utils import (
                restore_trainer_checkpoint,
            )

            path, self.restore_on_init = self.restore_on_init, None
            restore_trainer_checkpoint(self, path)

    # ---------- step functions ----------

    def _apply_train(self, params, state, rng, features, labels,
                     slice_to=None, model=None):
        """Pure fwd+bwd; the body every strategy shares. slice_to trims
        padding rows off outputs/labels before the loss (used by sharded
        strategies that pad batches to the mesh size). `model` overrides
        self._model for strategies that train through a mesh-bound
        variant of the same architecture (e.g. ring-attention SP) whose
        param tree is identical."""
        mutable = [k for k in state]
        model = model if model is not None else self._model

        def loss_of(p):
            out = model.apply(
                {"params": p, **state},
                features,
                training=True,
                rngs={"dropout": rng},
                mutable=mutable if mutable else False,
            )
            outputs, new_state = out if mutable else (out, state)
            labels_real = labels
            if slice_to is not None:
                # Only leaves carrying the batch dim get sliced back to
                # the real rows (bit-identical CE vs single-device).
                # Reduced scalars a model emits (e.g. a MoE aux loss) WERE
                # computed over the padded batch; padding is cyclic
                # repetition of real rows, so such regularizers are
                # marginally reweighted on a task's final partial
                # minibatch — same semantics as the multi-host ragged
                # batch documented in the AllReduce trainer.
                batch_n = jax.tree_util.tree_leaves(features)[0].shape[0]

                def trim(o):
                    if getattr(o, "ndim", 0) >= 1 and o.shape[0] == batch_n:
                        return o[:slice_to]
                    return o

                outputs = jax.tree_util.tree_map(trim, outputs)
                labels_real = jax.tree_util.tree_map(trim, labels)
            return self._loss_fn(labels_real, outputs), new_state

        (loss, new_state), grads = jax.value_and_grad(
            loss_of, has_aux=True
        )(params)
        return loss, grads, new_state

    def _step_body(self, variables, opt_state, rng, features, labels,
                   slice_to=None, model=None):
        """fwd + bwd + optimizer update; shared by every on-device-update
        strategy (local and AllReduce)."""
        params = variables["params"]
        state = {k: v for k, v in variables.items() if k != "params"}
        loss, grads, new_state = self._apply_train(
            params, state, rng, features, labels, slice_to, model=model
        )
        updates, new_opt_state = self._optax.update(
            grads, opt_state, params
        )
        new_params = optax.apply_updates(params, updates)
        return {"params": new_params, **new_state}, new_opt_state, loss

    def _build_train_step(self):
        # tracked_jit (observability/profiling.py): every lowering is
        # counted/timed with its cause attributed (cold / shape_change /
        # mesh_change / donation_miss). key_argnums keeps the hot-path
        # shape signature on the batch — param shapes are static after
        # init, and flattening the full tree per step is the cost the
        # MFU cache already refused to pay.
        from elasticdl_tpu.observability.profiling import tracked_jit

        return tracked_jit(
            self._step_body, name="train_step", key_argnums=(3, 4),
            donate_argnums=(0, 1),
        )

    def _build_forward(self):
        from elasticdl_tpu.observability.profiling import tracked_jit

        def forward(variables, features):
            return self._model.apply(variables, features, training=False)

        return tracked_jit(forward, name="forward", key_argnums=(1,))

    # ---------- Trainer interface ----------

    def train_minibatch(self, features, labels):
        self.init_variables_if_needed(features)
        self._rng, step_rng = jax.random.split(self._rng)
        with datapath.get().stage("h2d", timing=self.timing):
            device_features = _to_device_batch(features)
            device_labels = _to_device_batch(labels)
        step_args = (
            self._variables,
            self._opt_state,
            step_rng,
            device_features,
            device_labels,
        )
        # Keyed on the batch only: param shapes are static after init.
        self.step_cost.observe(
            self._train_step, step_args, key_args=step_args[3:]
        )
        self._variables, self._opt_state, loss = self._train_step(
            *step_args
        )
        self._version += 1
        # Lazy device scalar: converting to float here would block the host
        # on every step and serialize dispatch (the round-1 bench ceiling).
        return True, self._version, loss

    def evaluate_minibatch(self, features, model_version=-1):
        self.init_variables_if_needed(features)
        outputs = self._forward(self._variables, _to_device_batch(features))
        # Multi-output models return pytrees; hand numpy back either way.
        return jax.tree_util.tree_map(np.asarray, outputs)

    def get_model_version(self):
        return self._version

    def export_variables(self):
        return {
            "variables": jax.device_get(self._variables),
            # Left as device arrays: callers that persist it (the saver)
            # materialize per leaf; callers that only need the structure
            # or discard it (weights-only export, restore template) skip
            # a 2x-model-size device-to-host copy.
            "opt_state": self._opt_state,
            "rng": np.asarray(self._rng),
            "version": self._version,
        }

    def restore_variables(self, exported):
        self._variables = jax.tree_util.tree_map(
            jnp.asarray, exported["variables"]
        )
        if exported.get("opt_state") is not None:
            self._opt_state = jax.tree_util.tree_map(
                jnp.asarray, exported["opt_state"]
            )
        else:
            # Pre-round-3 checkpoints carried weights only; resuming from
            # one resets the optimizer moments (the old, lossy behavior).
            logger.warning(
                "Checkpoint has no optimizer state; re-initializing it"
            )
            self._opt_state = self._optax.init(self._variables["params"])
        if exported.get("rng") is not None:
            self._rng = jnp.asarray(exported["rng"])
        self._version = exported["version"]
        self._train_step = self._build_train_step()
        self._forward = self._build_forward()


class LocalTrainer(JaxTrainer):
    """Single-chip training: the minimum end-to-end strategy (reference
    DistributionStrategy.LOCAL)."""
