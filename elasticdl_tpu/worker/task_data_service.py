"""Task-to-minibatch pipeline on the worker.

Reference counterpart (/root/reference/elasticdl/python/worker/
task_data_service.py:26-238) adapts a stream of tasks into a tf.data
generator with deferred completion accounting. TPU-first simplification:
batches are task-scoped (a minibatch never spans tasks), so "task done" is
exactly "all its minibatches processed" — the completion accounting the
reference needed a pending-task deque for becomes trivial, and a recovered
task re-runs whole.
"""

import collections
import itertools
import time

import grpc

from elasticdl_tpu.chaos import injection
from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import datapath
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("worker.task_data_service")

_WAIT_SLEEP_SECONDS = 0.5
# How long the task loop tolerates an unreachable master (restart, stall)
# before letting the failure propagate and the worker exit. Each failed
# poll already burned the rpc plane's per-call retry budget.
_MASTER_PATIENCE_SECONDS = knobs.get_float(
    "ELASTICDL_MASTER_PATIENCE_SECONDS"
)

# Only CONNECTIVITY failures are worth riding out: a stalled or
# restarting master must not kill every worker (one control-plane blip
# would turn into a full fleet relaunch). Fail-fast statuses
# (INVALID_ARGUMENT, INTERNAL, ...) are deterministic — re-sending the
# same call for two minutes cannot fix them, matching the rpc plane's
# own retryability classification.
_CONNECTIVITY_CODES = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)


# A sustained outage usually means the master PROCESS restarted (not a
# network blip) — and a grpc channel whose reconnect attempts hit the
# unbound port can wedge in UNAVAILABLE forever (see MasterClient.
# reconnect). After this long unreachable, start probing for the new
# master and swap to a fresh channel the moment it accepts.
_RECONNECT_AFTER_SECONDS = 5.0


def _ride_master_outage(call, what, give_up=None, reconnect=None):
    """Run `call()`, re-trying through connectivity failures for up to the
    patience window. On exhaustion: `give_up(error)` when provided (drop
    semantics), else re-raise. Non-connectivity errors propagate
    immediately. `reconnect()` (when provided) is invoked periodically
    during a sustained outage so the transport can be rebuilt against a
    restarted master."""
    unreachable_since = None
    last_reconnect = 0.0
    while True:
        try:
            return call()
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            if code not in _CONNECTIVITY_CODES:
                raise
            now = time.time()
            if unreachable_since is None:
                unreachable_since = now
                logger.warning(
                    "Master unreachable on %s (%s); holding on for up "
                    "to %.0fs",
                    what,
                    getattr(code, "name", code),
                    _MASTER_PATIENCE_SECONDS,
                )
            if now - unreachable_since > _MASTER_PATIENCE_SECONDS:
                if give_up is None:
                    raise
                return give_up(e)
            if (
                reconnect is not None
                and now - unreachable_since >= _RECONNECT_AFTER_SECONDS
                and now - last_reconnect >= _RECONNECT_AFTER_SECONDS
            ):
                last_reconnect = now
                if reconnect():
                    logger.info(
                        "Master accepting again; rebuilt the channel "
                        "(outage %.0fs, during %s)",
                        now - unreachable_since,
                        what,
                    )
            time.sleep(_WAIT_SLEEP_SECONDS * 2)


class TaskDataService:
    def __init__(self, master_client, data_reader):
        self._mc = master_client
        self._reader = data_reader
        # Lease batching (ELASTICDL_TASK_LEASE_BATCH > 1): amortize the
        # get/report round-trips over N tasks — leases arrive in one
        # TaskBatch, completed results accumulate locally and flush as one
        # batched report before the next lease fetch. The default of 1
        # keeps the original one-RPC-per-task protocol byte-for-byte.
        self._lease_batch = max(
            1, knobs.get_int("ELASTICDL_TASK_LEASE_BATCH")
        )
        self._leased = collections.deque()
        self._pending_reports = []
        # task_id -> lease token from the dispatched Task proto, echoed
        # with the result so a report that straddles a master restart
        # (delivered to the old master, retried against the new one)
        # counts exactly once. 0 = legacy master without tokens.
        self._lease_tokens = {}

    def _remember_lease(self, task):
        token = getattr(task, "lease_token", 0)
        if token:
            self._lease_tokens[task.task_id] = token
        return task

    def get_task(self, task_type=pb.TRAINING, wait=True):
        """Next task from the master; blocks through WAIT states (queue
        momentarily empty) and rides out transient master outages. Returns
        None when the job is finished. The whole wait — RPC round-trips
        plus WAIT-state sleeps — lands as the data plane's `task` stage
        (the worker is input-starved on control-plane latency here)."""
        with datapath.get().stage("task"):
            return self._get_task(task_type, wait)

    def _get_task(self, task_type, wait):
        if self._lease_batch > 1 and task_type == pb.TRAINING:
            return self._get_task_batched(wait)
        while True:
            task = _ride_master_outage(
                lambda: self._mc.get_task(task_type), "get_task",
                reconnect=getattr(self._mc, "reconnect", None),
            )
            if task.task_id >= 0:
                return self._remember_lease(task)
            if task.type == pb.WAIT and wait:
                time.sleep(_WAIT_SLEEP_SECONDS)
                continue
            return None

    def _get_task_batched(self, wait):
        """Serve from the local lease buffer; refill with one batched RPC
        (flushing pending result reports first, so the dispatcher's
        accounting never lags more than one buffer behind)."""
        while True:
            if self._leased:
                return self._leased.popleft()
            self.flush_reports()
            try:
                res = _ride_master_outage(
                    lambda: self._mc.get_task_batch(self._lease_batch),
                    "get_task_batch",
                    reconnect=getattr(self._mc, "reconnect", None),
                )
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code == grpc.StatusCode.UNIMPLEMENTED:
                    # Pre-batching master: drop to the single-task
                    # protocol for the rest of this worker's life.
                    logger.warning(
                        "Master lacks get_task_batch; falling back to "
                        "single-task leases"
                    )
                    self._lease_batch = 1
                    return self._get_task(pb.TRAINING, wait)
                raise
            if res.tasks:
                self._leased.extend(
                    self._remember_lease(t) for t in res.tasks
                )
                continue
            if res.finished:
                return None
            if wait:
                time.sleep(_WAIT_SLEEP_SECONDS)
                continue
            return None

    def try_get_eval_task(self):
        """Non-blocking eval-task poll for interleaving evaluation into the
        training loop."""
        task = self._mc.get_task(pb.EVALUATION)
        return self._remember_lease(task) if task.task_id >= 0 else None

    def read_batches(self, task, batch_size):
        """Yield lists of raw records for the task, batch_size at a time
        (last batch may be smaller).

        Data-plane attribution: with a prefetching reader (it marks
        itself with `datapath_starve_waits`) the producer thread already
        accounts record reads as the `read` stage, so the consumer's
        wait here is `starve` — the step could not start because no
        batch was ready. With a synchronous reader the pull IS the read.
        Records are counted here, at the delivery boundary, exactly
        once."""
        dp = datapath.get()
        wait_stage = (
            "starve"
            if getattr(self._reader, "datapath_starve_waits", False)
            else "read"
        )
        it = iter(self._reader.read_records(task))
        while True:
            with dp.stage(wait_stage) as s:
                if wait_stage == "read":
                    injection.inject_local("datapath.read")
                batch = list(itertools.islice(it, batch_size))
                s.records = len(batch)
            if not batch:
                return
            yield batch
            if len(batch) < batch_size:
                return

    def read_range(self, lease_range):
        """All records of one lease sub-range (LeaseRange carries the same
        shard_name/start/end attributes a Task does, so readers take it
        as-is)."""
        dp = datapath.get()
        with dp.stage("read") as s:
            injection.inject_local("datapath.read")
            records = list(self._reader.read_records(lease_range))
            s.records = len(records)
        return records

    def report_task(self, task_id, err_message="", exec_counters=None):
        """Report a task result, riding out a master outage the same way
        get_task does. A report that never lands is SAFE to drop after the
        patience window: the master's watchdog recovers the still-'doing'
        task and re-dispatches it — whereas letting the error propagate
        kills the worker and turns one control-plane blip into a relaunch.

        Under lease batching, successful results buffer locally and flush
        as one batched RPC (at buffer capacity or before the next lease
        fetch); failures flush immediately so the master's retry ladder
        starts without waiting out the buffer."""
        lease_token = self._lease_tokens.pop(task_id, 0)
        if self._lease_batch > 1:
            self._pending_reports.append(
                (task_id, err_message, exec_counters, lease_token)
            )
            if err_message or (
                len(self._pending_reports) >= self._lease_batch
            ):
                self.flush_reports()
            return

        def dropped(e):
            logger.warning(
                "Dropping result report for task %d after %.0fs of "
                "master unreachability; the watchdog will recover and "
                "re-dispatch it",
                task_id,
                _MASTER_PATIENCE_SECONDS,
            )

        _ride_master_outage(
            lambda: self._mc.report_task_result(
                task_id, err_message, exec_counters,
                lease_token=lease_token,
            ),
            "report_task_result",
            give_up=dropped,
            reconnect=getattr(self._mc, "reconnect", None),
        )

    def flush_reports(self):
        """Send any buffered task results in one batched report. Dropped
        after the patience window with the same watchdog-recovers
        semantics as single reports."""
        if not self._pending_reports:
            return
        reports, self._pending_reports = self._pending_reports, []

        def dropped(e):
            logger.warning(
                "Dropping %d batched result reports after %.0fs of "
                "master unreachability; the watchdog will recover and "
                "re-dispatch them",
                len(reports),
                _MASTER_PATIENCE_SECONDS,
            )

        _ride_master_outage(
            lambda: self._mc.report_task_results(reports),
            "report_task_results",
            give_up=dropped,
            reconnect=getattr(self._mc, "reconnect", None),
        )

    @property
    def data_reader(self):
        return self._reader
