"""Task-to-minibatch pipeline on the worker.

Reference counterpart (/root/reference/elasticdl/python/worker/
task_data_service.py:26-238) adapts a stream of tasks into a tf.data
generator with deferred completion accounting. TPU-first simplification:
batches are task-scoped (a minibatch never spans tasks), so "task done" is
exactly "all its minibatches processed" — the completion accounting the
reference needed a pending-task deque for becomes trivial, and a recovered
task re-runs whole.
"""

import time

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("worker.task_data_service")

_WAIT_SLEEP_SECONDS = 0.5


class TaskDataService:
    def __init__(self, master_client, data_reader):
        self._mc = master_client
        self._reader = data_reader

    def get_task(self, task_type=pb.TRAINING, wait=True):
        """Next task from the master; blocks through WAIT states (queue
        momentarily empty). Returns None when the job is finished."""
        while True:
            task = self._mc.get_task(task_type)
            if task.task_id >= 0:
                return task
            if task.type == pb.WAIT and wait:
                time.sleep(_WAIT_SLEEP_SECONDS)
                continue
            return None

    def try_get_eval_task(self):
        """Non-blocking eval-task poll for interleaving evaluation into the
        training loop."""
        task = self._mc.get_task(pb.EVALUATION)
        return task if task.task_id >= 0 else None

    def read_batches(self, task, batch_size):
        """Yield lists of raw records for the task, batch_size at a time
        (last batch may be smaller)."""
        batch = []
        for record in self._reader.read_records(task):
            batch.append(record)
            if len(batch) >= batch_size:
                yield batch
                batch = []
        if batch:
            yield batch

    def read_range(self, lease_range):
        """All records of one lease sub-range (LeaseRange carries the same
        shard_name/start/end attributes a Task does, so readers take it
        as-is)."""
        return list(self._reader.read_records(lease_range))

    def report_task(self, task_id, err_message="", exec_counters=None):
        self._mc.report_task_result(task_id, err_message, exec_counters)

    @property
    def data_reader(self):
        return self._reader
