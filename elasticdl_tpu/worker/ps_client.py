"""Worker-side client for the sharded parameter servers.

Reference counterpart: /root/reference/elasticdl/python/worker/
ps_client.py:32-246. Partitioning kept bit-compatible with the store:
dense parameters by sha256(name) mod N, embedding ids by id mod N
(common/hash_utils.py). All fan-outs use gRPC futures so the N shards work
in parallel; sparse grads are merged/deduplicated *before* the wire
(ps_client.py:135-232).
"""

import os
import time

import grpc
import numpy as np

from elasticdl_tpu.common import hash_utils, knobs, rpc, tensor_utils
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import emit_event, tracing
from elasticdl_tpu.observability.metrics import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("worker.ps_client")


class _PendingVectorPull:
    """In-flight pull_embedding_vectors fan-out; result() harvests."""

    def __init__(self, client, ids, futures, keep_wire_dtype):
        self._client = client
        self._ids = ids
        self._futures = futures
        self._keep_wire_dtype = keep_wire_dtype

    def result(self):
        out = None
        for ps_id, (positions, f) in self._futures.items():
            try:
                result = f.result()
            except grpc.RpcError as e:
                # Embedding rows are REQUIRED for this batch — no partial
                # answer is usable. Mark the shard and raise; the worker's
                # minibatch retry ladder re-pulls once the shard returns.
                self._client._mark_degraded(ps_id, e)
                raise
            self._client._mark_healthy(ps_id)
            values = tensor_utils.tensor_pb_to_ndarray(result)
            if values.dtype != np.float32 and not self._keep_wire_dtype:
                values = values.astype(np.float32)
            if out is None:
                out = np.empty(
                    (len(self._ids), values.shape[1]), dtype=values.dtype
                )
            out[positions] = values
        return out

_REG = default_registry()
_DEGRADED = _REG.gauge(
    "edl_ps_shards_degraded", "PS shards this worker currently sees as down"
)
_DROPPED_PUSHES = _REG.counter(
    "edl_ps_grad_pushes_dropped_total",
    "Per-shard gradient pushes dropped because the shard was unreachable",
)


class PSClient:
    def __init__(self, ps_addrs, worker_id=-1, wire_dtype=None):
        """ps_addrs: list of "host:port", index = ps_id.

        wire_dtype: wire codec, one of "float32" / "bfloat16" / "int8"
        (None reads the ELASTICDL_WIRE_DTYPE knob). bf16 halves the
        sparse hot path's pull/push bandwidth; int8 additionally
        block-quantizes DENSE gradients (EQuARX-style absmax blocks,
        ELASTICDL_WIRE_BLOCK_SIZE) with worker-side error-feedback
        residuals so the quantization error stays out of the training
        trajectory — embedding values/grads travel bf16 under int8
        (per-id residuals for sparse rows would need a table-sized
        shadow). Dense PARAMETER pulls always travel f32: the optimizer
        moments live in f32 on the PS and params are pulled once per
        model_steps, not per step."""
        if wire_dtype is None or wire_dtype == "":
            wire_dtype = knobs.get_str("ELASTICDL_WIRE_DTYPE")
        if wire_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(f"unsupported wire_dtype {wire_dtype!r}")
        self.wire_dtype = wire_dtype
        # Public: the trainer keys its device-side dtype plumbing off
        # the wire dtype (bf16 rows/grads stay bf16 across the
        # host<->device hop too). int8 keeps the bf16 embedding legs.
        self.bf16_wire = wire_dtype in ("bfloat16", "int8")
        self.int8_dense = wire_dtype == "int8"
        self._block_size = knobs.get_int("ELASTICDL_WIRE_BLOCK_SIZE")
        # Error-feedback residuals, one per dense grad name: what the
        # last quantization rounded away, re-injected into the next push.
        self._ef_residual = {}
        # Packed-push chunking: sub-requests of one push share a push_id
        # (salted by pid so anonymous workers on one host can't collide
        # in the PS's reassembly map).
        self._max_push_bytes = knobs.get_int("ELASTICDL_PS_MAX_PUSH_BYTES")
        self._push_salt = (os.getpid() & 0xFFFFFFFF) << 24
        self._push_seq = 0
        # Optional common.timing.Timing: when bound (the PS trainer binds
        # its own), push_gradients records its serialize/wire/apply
        # sub-phases there — the decomposition the microbench matrix and
        # a flagged BENCH run need to attribute the dominant phase.
        self.timing = None
        self._addrs = list(ps_addrs)
        self._worker_id = worker_id
        # Readiness-probe all shards CONCURRENTLY, then build channels
        # without re-probing: serial probing would cost num_dead * timeout
        # at worker startup when shards are mid-relaunch, exactly when a
        # relaunched worker should be back serving the healthy shards.
        self._probe_ready_concurrently()
        self._channels = [
            rpc.build_channel(a, ready_timeout=0) for a in self._addrs
        ]
        self._stubs = [
            rpc.Stub(ch, rpc.PSERVER_SERVICE) for ch in self._channels
        ]
        self.num_ps = len(self._stubs)
        # Per-shard pull cursors: each shard's version advances independently
        # (only pushes touching it bump it), so "what have I already got"
        # must be tracked per shard, not as one global number.
        self._dense_versions = [-1] * self.num_ps
        # Shard-failure awareness: a shard whose RPCs fail (after the rpc
        # plane's retries) is marked degraded instead of crashing the
        # worker. Degraded shards skip gradient pushes (async SGD absorbs
        # the lost update), report as uninitialized on dense pulls (the
        # trainer's re-seed path owns recovery), and flip back to healthy
        # on the first successful call.
        self._degraded = set()
        # Shards whose last dense pull answered initialized=False (or was
        # unreachable) — the targets a re-seed push actually needs; a
        # full-fan-out re-seed would re-ship every healthy shard a model
        # it ignores, on every backoff iteration of an outage.
        self.unseeded_shards = set()

    def close(self):
        for ch in self._channels:
            ch.close()

    def _probe_ready_concurrently(self):
        import concurrent.futures

        timeout = rpc.ready_timeout()
        if timeout <= 0 or not self._addrs:
            return
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=len(self._addrs)
        ) as pool:
            ready = list(
                pool.map(
                    lambda a: rpc.wait_channel_ready(a, timeout),
                    self._addrs,
                )
            )
        for ps_id, ok in enumerate(ready):
            if not ok:
                logger.warning(
                    "PS shard %d (%s) not accepting connections after "
                    "%.0fs; proceeding (retries/degradation take over)",
                    ps_id,
                    self._addrs[ps_id],
                    timeout,
                )

    # ---------- shard health ----------

    @property
    def degraded_shards(self):
        return set(self._degraded)

    def _mark_degraded(self, ps_id, err):
        if ps_id not in self._degraded:
            self._degraded.add(ps_id)
            _DEGRADED.set(len(self._degraded))
            code = err.code() if hasattr(err, "code") else None
            logger.warning(
                "PS shard %d (%s) degraded: %s",
                ps_id,
                self._addrs[ps_id],
                getattr(code, "name", code),
            )
            emit_event(
                "ps_shard_degraded",
                ps=ps_id,
                addr=self._addrs[ps_id],
                code=str(getattr(code, "name", code)),
            )

    def _mark_healthy(self, ps_id):
        if ps_id in self._degraded:
            self._degraded.discard(ps_id)
            _DEGRADED.set(len(self._degraded))
            logger.info(
                "PS shard %d (%s) healthy again",
                ps_id,
                self._addrs[ps_id],
            )
            emit_event(
                "ps_shard_recovered", ps=ps_id, addr=self._addrs[ps_id]
            )

    # ---------- partitioning ----------

    def partition_dense_names(self, names):
        """{ps_id: [names]} by stable name hash."""
        parts = {}
        for name in names:
            parts.setdefault(
                hash_utils.string_to_id(name, self.num_ps), []
            ).append(name)
        return parts

    # ---------- model init / re-seed ----------

    def push_model(self, dense_params, embedding_infos=None, version=0,
                   only_shards=None):
        """Push each PS its shard of the dense params + all table infos
        (first-worker init AND the PS-restart re-seed path).

        only_shards: restrict the fan-out to these ps_ids (the re-seed
        path targets just the unseeded shards instead of re-shipping the
        model to healthy ones that ignore it).

        A shard that rejects the push (still down mid-relaunch) is marked
        degraded and skipped — the next _sync_model re-seed retries it;
        only when EVERY targeted shard fails does the error propagate
        (nothing was seeded, so the caller cannot make progress). Returns
        the set of shards seeded."""
        parts = self.partition_dense_names(dense_params)
        futures = []
        for ps_id, stub in enumerate(self._stubs):
            if only_shards is not None and ps_id not in only_shards:
                continue
            model = pb.Model(version=version)
            for name in parts.get(ps_id, []):
                model.dense_parameters.append(
                    tensor_utils.ndarray_to_tensor_pb(
                        np.ascontiguousarray(
                            dense_params[name], dtype=np.float32
                        ),
                        name,
                    )
                )
            for info in embedding_infos or []:
                model.embedding_table_infos.append(info)
            futures.append((ps_id, stub.push_model.future(model)))
        seeded, last_err = set(), None
        for ps_id, f in futures:
            try:
                f.result()
            except grpc.RpcError as e:
                last_err = e
                self._mark_degraded(ps_id, e)
                continue
            self._mark_healthy(ps_id)
            seeded.add(ps_id)
        if not seeded and last_err is not None:
            raise last_err
        return seeded

    def push_embedding_table_infos(self, infos):
        model = pb.Model()
        model.embedding_table_infos.extend(infos)
        futures = [
            (ps_id, stub.push_embedding_table_infos.future(model))
            for ps_id, stub in enumerate(self._stubs)
        ]
        last_err, delivered = None, 0
        for ps_id, f in futures:
            try:
                f.result()
            except grpc.RpcError as e:
                # A shard that misses the infos serves no embeddings; the
                # re-seed path replays them (push_model carries the infos).
                last_err = e
                self._mark_degraded(ps_id, e)
                continue
            self._mark_healthy(ps_id)
            delivered += 1
        if not delivered and last_err is not None:
            raise last_err

    # ---------- pulls ----------

    def pull_dense_parameters(self, names, version=None):
        """Pull the given dense params from their shards.

        version=None uses the internal per-shard cursors (each shard only
        re-sends params newer than what this client already pulled);
        an explicit version overrides for all shards.

        Returns (all_initialized, max_version, {name: ndarray}); params is
        partial when some shard reported initialized=False (that shard needs
        a re-seed via push_model) OR was unreachable (marked degraded here;
        the caller's re-seed/backoff loop owns recovery — a dense pull
        blocks-with-backoff rather than crashing the worker)."""
        parts = self.partition_dense_names(names)
        futures = {
            ps_id: self._stubs[ps_id].pull_dense_parameters.future(
                pb.PullDenseParametersRequest(
                    version=self._dense_versions[ps_id]
                    if version is None
                    else version
                )
            )
            for ps_id in range(self.num_ps)
        }
        params, initialized, max_version = {}, True, 0
        for ps_id, f in futures.items():
            try:
                res = f.result()
            except grpc.RpcError as e:
                self._mark_degraded(ps_id, e)
                initialized = False
                self.unseeded_shards.add(ps_id)
                self._dense_versions[ps_id] = -1
                continue
            self._mark_healthy(ps_id)
            if not res.initialized:
                initialized = False
                self.unseeded_shards.add(ps_id)
                # Force a full re-pull from this shard once it comes back.
                self._dense_versions[ps_id] = -1
                continue
            self.unseeded_shards.discard(ps_id)
            self._dense_versions[ps_id] = res.version
            max_version = max(max_version, res.version)
            wanted = set(parts.get(ps_id, []))
            for t in res.dense_parameters:
                if t.name in wanted:
                    params[t.name] = tensor_utils.tensor_pb_to_ndarray(t)
        return initialized, max_version, params

    def pull_embedding_vectors(self, name, ids, keep_wire_dtype=False):
        """ids [k] -> [k, dim] rows, gathered across shards by id modulo and
        restored to input order.

        keep_wire_dtype=True hands bf16-wire rows back AS bf16 instead of
        widening to f32 on the host: bf16 -> f32 is exact, so a caller
        that uploads the rows to a device (the PS trainer's prefetch) can
        defer the widening to the chip and move half the bytes across the
        host->device hop — which on tunnel-attached chips is the
        prefetch phase's actual limiter (tools/ps_push_probe.py)."""
        pending = self.pull_embedding_vectors_async(
            name, ids, keep_wire_dtype=keep_wire_dtype
        )
        return pending.result() if pending is not None else None

    def pull_embedding_vectors_async(self, name, ids,
                                     keep_wire_dtype=False):
        """Issue the per-shard pull fan-out and return a handle whose
        ``result()`` harvests it — the prefetch-overlap path issues these
        for several tables (and for the NEXT batch) while the device is
        still busy with the current step. Returns None for empty ids."""
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size == 0:
            return None
        scattered = hash_utils.scatter_embedding_ids(ids, self.num_ps)
        value_dtype = pb.DT_BFLOAT16 if self.bf16_wire else pb.DT_INVALID
        futures = {
            ps_id: (
                positions,
                self._stubs[ps_id].pull_embedding_vectors.future(
                    pb.PullEmbeddingVectorsRequest(
                        name=name,
                        ids_bytes=tensor_utils.ids_to_bytes(shard_ids),
                        value_dtype=value_dtype,
                    )
                ),
            )
            for ps_id, (shard_ids, positions) in scattered.items()
        }
        return _PendingVectorPull(self, ids, futures, keep_wire_dtype)

    def pull_embedding_table(self, name, page_bytes=64 << 20, dim=None):
        """Every materialized (id, row) of a table, merged across shards —
        the export reverse-swap. Pulled in pages so a CTR-scale table
        never has to fit one gRPC message (256 MB cap); pass `dim` so the
        FIRST page is bounded too (wide tables would otherwise blow the
        cap before the row size is known). Returns (ids [n],
        values [n, dim]); (empty, None) if no rows exist."""
        if dim:
            first_page = max(1, page_bytes // (int(dim) * 4))
        else:
            first_page = 65536
        all_ids, all_values = [], []
        for ps_id, stub in enumerate(self._stubs):
            start, requested = 0, first_page
            while True:
                try:
                    res = stub.pull_embedding_table(
                        pb.PullEmbeddingTableRequest(
                            name=name, start_row=start, max_rows=requested
                        )
                    )
                except grpc.RpcError as e:
                    # Export needs every shard's rows; a partial table
                    # would silently corrupt the exported model.
                    self._mark_degraded(ps_id, e)
                    raise
                values, ids = tensor_utils.indexed_slices_pb_to_ndarrays(
                    res
                )
                if ids.size:
                    all_ids.append(ids)
                    all_values.append(values)
                if ids.size < requested:  # short page = last page
                    break
                start += ids.size
                row_bytes = values.dtype.itemsize * values.shape[1]
                requested = max(1, page_bytes // max(row_bytes, 1))
        if not all_ids:
            return np.empty(0, np.int64), None
        return np.concatenate(all_ids), np.concatenate(all_values)

    # ---------- gradient push ----------

    def push_gradients(
        self, dense_grads, sparse_grads, version, learning_rate=0.0,
        batch_size=0,
    ):
        """dense_grads: {name: ndarray}; sparse_grads:
        {table_name: (values [k, dim], ids [k])} — deduplicated here before
        partitioning. batch_size = records in the minibatch behind this
        push (feeds the checkpoint's exact consumed-record counter).
        Returns (accepted_all, max_version).

        The push travels the PACKED wire (push_gradients_packed): a slim
        span header plus one out-of-band payload assembled from zero-copy
        views over the gradient arrays — no per-tensor tobytes, no proto
        CopyFrom. Payloads over ELASTICDL_PS_MAX_PUSH_BYTES split into
        chunked sub-requests so one giant embedding slice can't stall the
        channel past its per-method deadline.

        Sub-span attribution (when ``self.timing`` is bound): the push
        splits into push_serialize (host-side dedup + quantize + span
        packing), push_apply (the slowest shard's optimizer apply,
        reported back on PushGradientsResponse.apply_seconds — shards
        apply concurrently, so the max is what gated the wait), and
        push_wire (the remaining RPC wait: serialize-join, TCP, and
        payload decode on both ends)."""
        serialize_start = time.perf_counter()
        with tracing.span("ps_push_serialize"):
            requests = self._build_packed_requests(
                dense_grads, sparse_grads, version, learning_rate,
                batch_size,
            )
        serialize_s = time.perf_counter() - serialize_start
        wait_start = time.perf_counter()
        apply_s = 0.0
        with tracing.span("ps_push_wait"):
            futures = [
                (
                    ps_id,
                    [
                        self._stubs[ps_id].push_gradients_packed.future(r)
                        for r in reqs
                    ],
                )
                for ps_id, reqs in requests.items()
            ]
            accepted, max_version = True, 0
            delivered, last_err = 0, None
            for ps_id, shard_futures in futures:
                shard_err = None
                for f in shard_futures:
                    try:
                        res = f.result()
                    except grpc.RpcError as e:
                        # Degraded shard: drop its slice of this step's
                        # gradients (async SGD tolerates a lost update
                        # the same way it tolerates staleness) and keep
                        # the healthy shards' updates. A failed CHUNK
                        # fails the whole shard slice — the PS GC's the
                        # partial reassembly by age.
                        shard_err = e
                        break
                    accepted = accepted and res.accepted
                    max_version = max(max_version, res.version)
                    apply_s = max(apply_s, res.apply_seconds)
                if shard_err is not None:
                    last_err = shard_err
                    self._mark_degraded(ps_id, shard_err)
                    _DROPPED_PUSHES.inc()
                    continue
                self._mark_healthy(ps_id)
                delivered += 1
        if self.timing is not None:
            wait_s = time.perf_counter() - wait_start
            self.timing.add("push_serialize", serialize_s)
            self.timing.add("push_apply", apply_s)
            self.timing.add("push_wire", max(wait_s - apply_s, 0.0))
        if not delivered and last_err is not None:
            # Every shard refused: no progress is being recorded anywhere;
            # surface the failure so the retry ladder (and ultimately the
            # master's task retry accounting) sees it.
            raise last_err
        return accepted, max_version

    def _build_packed_requests(self, dense_grads, sparse_grads, version,
                               learning_rate, batch_size):
        """{ps_id: [PackedPushRequest, ...]} for one gradient push.

        Dense grads pack as f32 views (zero host copies) or, under the
        int8 codec, as block-quantized spans with error feedback: the
        residual the last quantization rounded away joins this step's
        grad before quantizing, and the new round-off becomes the next
        residual — the EQuARX recipe that keeps low-bit wire codecs from
        biasing convergence. Sparse grads dedup once, then bucket by
        id-sorted shard order with ONE gather for all shards — each
        shard's rows are a contiguous block whose span is a view, where
        the proto path gathered + copied per shard."""
        worker_id_plus_one = (
            self._worker_id + 1 if self._worker_id >= 0 else 0
        )
        headers, payloads = {}, {}

        def ensure(ps_id):
            if ps_id not in headers:
                headers[ps_id] = pb.PushGradientsPackedRequest(
                    version=version,
                    learning_rate=learning_rate,
                    worker_id_plus_one=worker_id_plus_one,
                    batch_size=batch_size,
                    chunk_count=1,
                )
                payloads[ps_id] = tensor_utils.PackedPayload()
            return headers[ps_id], payloads[ps_id]

        for ps_id, names in self.partition_dense_names(
            dense_grads
        ).items():
            header, payload = ensure(ps_id)
            for name in names:
                arr = np.ascontiguousarray(
                    dense_grads[name], dtype=np.float32
                )
                if self.int8_dense:
                    residual = self._ef_residual.get(name)
                    if residual is not None:
                        arr = arr + residual
                    q, scales = tensor_utils.quantize_int8_blocks(
                        arr, self._block_size
                    )
                    dq = tensor_utils.dequantize_int8_blocks(
                        q, scales, self._block_size
                    ).reshape(arr.shape)
                    self._ef_residual[name] = arr - dq
                    header.dense.append(
                        tensor_utils.pack_quantized_span(
                            name, arr.shape, q, scales,
                            self._block_size, payload,
                        )
                    )
                else:
                    header.dense.append(
                        tensor_utils.pack_tensor_span(name, arr, payload)
                    )
        # Tables that share one input ids array (DeepFM wide/deep) dedup
        # to identical id sets: the shard bucketing (lexsort + bounds) is
        # computed once and reused across them.
        bucket_memo = {}
        for table, (values, ids) in sparse_grads.items():
            memo_key = id(ids)
            values, ids = tensor_utils.deduplicate_indexed_slices(
                np.asarray(values, dtype=np.float32),
                np.asarray(ids, dtype=np.int64),
            )
            if self.bf16_wire and values.dtype != tensor_utils.bfloat16:
                values = values.astype(tensor_utils.bfloat16)
            if self.num_ps == 1:
                # One shard: no bucketing, no gather — the deduped
                # values/ids ship as-is (spans are views over them).
                header, payload = ensure(0)
                header.sparse.append(
                    tensor_utils.pack_slices_span(
                        table, values, ids, payload
                    )
                )
                continue
            memo = bucket_memo.get(memo_key)
            if memo is not None and np.array_equal(memo[0], ids):
                ids_sorted, order, bounds = memo[1], memo[2], memo[3]
            else:
                shard = ids % self.num_ps
                order = np.lexsort((ids, shard))
                ids_sorted = ids[order]
                bounds = np.searchsorted(
                    shard[order], np.arange(self.num_ps + 1)
                )
                bucket_memo[memo_key] = (ids, ids_sorted, order, bounds)
            values_sorted = values[order]
            for ps_id in range(self.num_ps):
                lo, hi = int(bounds[ps_id]), int(bounds[ps_id + 1])
                if lo == hi:
                    continue
                header, payload = ensure(ps_id)
                header.sparse.append(
                    tensor_utils.pack_slices_span(
                        table, values_sorted[lo:hi], ids_sorted[lo:hi],
                        payload,
                    )
                )
        requests = {}
        for ps_id, header in headers.items():
            payload = payloads[ps_id]
            header.payload_total_bytes = payload.nbytes
            max_bytes = self._max_push_bytes
            if max_bytes <= 0 or payload.nbytes <= max_bytes:
                requests[ps_id] = [
                    tensor_utils.PackedPushRequest(
                        header, payload.parts, payload.nbytes
                    )
                ]
                continue
            n_chunks = -(-payload.nbytes // max_bytes)
            self._push_seq += 1
            push_id = self._push_salt | (self._push_seq & 0xFFFFFF)
            header.push_id = push_id
            header.chunk_count = n_chunks
            reqs = []
            for i in range(n_chunks):
                start = i * max_bytes
                end = min(start + max_bytes, payload.nbytes)
                if i == 0:
                    chunk_header = header  # spans ride the first chunk
                else:
                    chunk_header = pb.PushGradientsPackedRequest(
                        worker_id_plus_one=worker_id_plus_one,
                        push_id=push_id,
                        chunk_index=i,
                        chunk_count=n_chunks,
                        payload_offset=start,
                        payload_total_bytes=payload.nbytes,
                    )
                reqs.append(
                    tensor_utils.PackedPushRequest(
                        chunk_header,
                        payload.slice_parts(start, end),
                        end - start,
                    )
                )
            requests[ps_id] = reqs
        return requests
