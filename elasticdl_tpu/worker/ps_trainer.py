"""Parameter-server-strategy trainer.

Reference counterpart: /root/reference/elasticdl/python/worker/
ps_trainer.py:36-441. Behaviors kept:

- pull dense params before stepping; a shard answering initialized=False is
  re-seeded by pushing local weights (the PS crash-recovery path,
  ps_trainer.py:149-184) — verified by test_ps_restart_reseed.
- fwd/bwd is one jitted function; embedding rows are prefetched OUTSIDE the
  step and differentiated as inputs (see layers/embedding.py for why this
  replaces the reference's mid-forward py_function RPC under XLA).
- gradients partition/merge/push via PSClient; a sync-mode rejection
  (stale version) re-pulls and recomputes the minibatch
  (ps_trainer.py:372-386).

Worker-side params are a cache of PS state (async SGD): the PS owns the
model version; the worker never applies updates locally.
"""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.pytree_utils import flatten_params, unflatten_like
from elasticdl_tpu.layers.embedding import EMBEDDING_COLLECTION
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.worker.trainer import JaxTrainer, _to_device_batch

logger = get_logger("worker.ps_trainer")

DEFAULT_MAX_PUSH_RETRIES = 3


def _walk_dict(tree, path=()):
    """Yield (path_tuple, leaf) over a nested dict (flax FrozenDict or dict).
    """
    for k, v in tree.items():
        if hasattr(v, "items"):
            yield from _walk_dict(v, path + (k,))
        else:
            yield path + (k,), v


def _nest_at(paths_to_values):
    """{path_tuple: value} -> nested dict."""
    nested = {}
    for path, value in paths_to_values.items():
        node = nested
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = value
    return nested


class ParameterServerTrainer(JaxTrainer):
    def __init__(
        self,
        model,
        loss_fn,
        optimizer_spec,
        ps_client,
        embedding_inputs=None,
        use_async=True,
        max_push_retries=DEFAULT_MAX_PUSH_RETRIES,
        seed=0,
    ):
        super().__init__(model, loss_fn, optimizer_spec, seed=seed)
        self._ps = ps_client
        # callable(features) -> {table_name: ids ndarray}; required iff the
        # model contains DistributedEmbedding layers (PS mode).
        self._embedding_inputs = embedding_inputs
        self._use_async = use_async
        self._max_push_retries = max_push_retries
        self._param_names = None
        self._embedding_dims = {}  # table -> dim, derived at init
        # table -> module-scope path inside the edl_embedding collection
        # (flax nests collection entries under the owning module's path).
        self._embedding_paths = {}
        self._ps_step = None
        self._ps_forward = None

    # ---------- init ----------

    def init_variables_if_needed(self, features):
        if self._variables is not None:
            return
        super().init_variables_if_needed(features)
        # The init-created embedding collection only carried shapes; rows
        # arrive per-batch. Record each table's dim and scope path, then
        # drop the collection from state.
        emb = self._variables.pop(EMBEDDING_COLLECTION, {})
        for path, leaf in _walk_dict(emb):
            table = path[-1]  # innermost key is the table_name
            self._embedding_dims[table] = int(leaf.shape[-1])
            self._embedding_paths[table] = path
        if self._embedding_dims and self._embedding_inputs is None:
            raise ValueError(
                "model has DistributedEmbedding layers "
                f"{sorted(self._embedding_dims)} but no embedding_inputs "
                "feed was provided to ParameterServerTrainer"
            )
        _, self._param_names = flatten_params(self._variables["params"])
        # First worker seeds the PS; later pushes are ignored there.
        self._push_local_model()
        self._ps_step = self._build_ps_step()
        self._ps_forward = self._build_ps_forward()

    def _embedding_infos(self):
        return [
            pb.EmbeddingTableInfo(
                name=name, dim=dim, initializer="uniform", dtype=pb.DT_FLOAT32
            )
            for name, dim in sorted(self._embedding_dims.items())
        ]

    def _push_local_model(self):
        named, _ = flatten_params(jax.device_get(self._variables["params"]))
        self._ps.push_model(
            named, self._embedding_infos(), version=self._version
        )

    # ---------- PS sync ----------

    def _sync_model(self):
        """Pull dense params; re-seed any uninitialized shard from local
        weights (that IS the PS fault-tolerance path)."""
        # The PSClient tracks per-shard pull cursors: a shard only re-sends
        # params newer than this client's last pull from it.
        initialized, version, named = self._ps.pull_dense_parameters(
            self._param_names
        )
        if not initialized:
            logger.info("Uninitialized PS shard found; re-seeding from local")
            self._push_local_model()
            initialized, version, named = self._ps.pull_dense_parameters(
                self._param_names
            )
            if not initialized:
                raise RuntimeError("PS still uninitialized after re-seed")
        if named:
            self._variables["params"] = unflatten_like(
                self._variables["params"],
                {k: jnp.asarray(v) for k, v in named.items()},
            )
        self._version = max(self._version, version)

    def _prefetch_embeddings(self, features):
        """features -> (rows {table: [n_positions, dim]}, flat_ids
        {table: [n_positions]}). Pulls unique ids only; expands back by
        inverse so the in-jit layer does a plain reshape."""
        if not self._embedding_dims:
            return {}, {}
        by_path, flat_ids = {}, {}
        for table, ids in self._embedding_inputs(features).items():
            ids = np.asarray(ids, dtype=np.int64).reshape(-1)
            unique, inverse = np.unique(ids, return_inverse=True)
            pulled = self._ps.pull_embedding_vectors(table, unique)
            by_path[self._embedding_paths[table]] = jnp.asarray(
                pulled[inverse]
            )
            flat_ids[table] = ids
        return _nest_at(by_path), flat_ids

    # ---------- jitted steps ----------

    def _build_ps_step(self):
        def step(params, state, emb_rows, rng, features, labels):
            def loss_of(p, rows):
                mutable = [k for k in state]
                out = self._model.apply(
                    {"params": p, **state, EMBEDDING_COLLECTION: rows},
                    features,
                    training=True,
                    rngs={"dropout": rng},
                    mutable=mutable if mutable else False,
                )
                outputs, new_state = out if mutable else (out, state)
                return self._loss_fn(labels, outputs), new_state

            (loss, new_state), grads = jax.value_and_grad(
                loss_of, argnums=(0, 1), has_aux=True
            )(params, emb_rows)
            return loss, grads[0], grads[1], new_state

        return jax.jit(step)

    def _build_ps_forward(self):
        def forward(params, state, emb_rows, features):
            return self._model.apply(
                {"params": params, **state, EMBEDDING_COLLECTION: emb_rows},
                features,
                training=False,
            )

        return jax.jit(forward)

    # ---------- Trainer interface ----------

    def train_minibatch(self, features, labels):
        self.init_variables_if_needed(features)
        device_features = _to_device_batch(features)
        device_labels = _to_device_batch(labels)
        for attempt in range(self._max_push_retries):
            with self.timing.record("pull_model"):
                self._sync_model()
            with self.timing.record("prefetch_embeddings"):
                emb_rows, flat_ids = self._prefetch_embeddings(features)
            self._rng, step_rng = jax.random.split(self._rng)
            state = {
                k: v for k, v in self._variables.items() if k != "params"
            }
            with self.timing.record("train_step"):
                loss, param_grads, emb_grads, new_state = self._ps_step(
                    self._variables["params"],
                    state,
                    emb_rows,
                    step_rng,
                    device_features,
                    device_labels,
                )
            self._variables.update(new_state)
            with self.timing.record("push_gradients"):
                dense_named, _ = flatten_params(
                    jax.device_get(param_grads)
                )
                sparse = {}
                for path, g in _walk_dict(emb_grads):
                    table = path[-1]
                    sparse[table] = (
                        np.asarray(g).reshape(
                            -1, self._embedding_dims[table]
                        ),
                        flat_ids[table],
                    )
                accepted, version = self._ps.push_gradients(
                    dense_named,
                    sparse,
                    version=self._version,
                    batch_size=int(np.asarray(labels).shape[0]),
                )
            self._version = max(self._version, version)
            if accepted:
                return True, self._version, float(loss)
            logger.info(
                "Gradient push rejected as stale (attempt %d); re-pulling",
                attempt + 1,
            )
        return False, self._version, float(loss)

    def evaluate_minibatch(self, features, model_version=-1):
        self.init_variables_if_needed(features)
        self._sync_model()
        emb_rows, _ = self._prefetch_embeddings(features)
        state = {k: v for k, v in self._variables.items() if k != "params"}
        outputs = self._ps_forward(
            self._variables["params"],
            state,
            emb_rows,
            _to_device_batch(features),
        )
        return jax.tree_util.tree_map(np.asarray, outputs)

    def get_model_version(self):
        return self._version
