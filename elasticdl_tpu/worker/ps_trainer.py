"""Parameter-server-strategy trainer.

Reference counterpart: /root/reference/elasticdl/python/worker/
ps_trainer.py:36-441. Behaviors kept:

- pull dense params before stepping; a shard answering initialized=False is
  re-seeded by pushing local weights (the PS crash-recovery path,
  ps_trainer.py:149-184) — verified by test_ps_restart_reseed.
- fwd/bwd is one jitted function; embedding rows are prefetched OUTSIDE the
  step and differentiated as inputs (see layers/embedding.py for why this
  replaces the reference's mid-forward py_function RPC under XLA).
- gradients partition/merge/push via PSClient; a sync-mode rejection
  (stale version) re-pulls and recomputes the minibatch
  (ps_trainer.py:372-386).

Worker-side params are a cache of PS state (async SGD): the PS owns the
model version. With get_model_steps > 1 the worker additionally advances
its CACHED params through its own optimizer between pulls (the
reference's train_with_local_model) — the next successful pull overwrites
that local drift, so the PS remains the source of truth.
"""


import grpc
import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.pytree_utils import (
    flatten_params,
    nest_at as _nest_at,
    unflatten_like,
    walk_dict as _walk_dict,
)
from elasticdl_tpu.layers.embedding import EMBEDDING_COLLECTION
from elasticdl_tpu.observability import datapath
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.worker.trainer import JaxTrainer, _to_device_batch

logger = get_logger("worker.ps_trainer")

DEFAULT_MAX_PUSH_RETRIES = 3

def _unique_inverse(flat):
    """np.unique(flat, return_inverse=True) in the ids' NATIVE dtype —
    sorting 640k int32 ids costs ~2/3 of sorting their int64 widening
    (measured; a bitmap + rank-cumsum alternative measured slower) —
    with the unique set widened to the int64 the wire contract needs."""
    unique, inverse = np.unique(flat, return_inverse=True)
    return np.ascontiguousarray(unique, dtype=np.int64), inverse


class ParameterServerTrainer(JaxTrainer):
    def __init__(
        self,
        model,
        loss_fn,
        optimizer_spec,
        ps_client,
        embedding_inputs=None,
        embedding_threshold_bytes=None,
        embedding_device_capacity_bytes=0,
        use_async=True,
        max_push_retries=DEFAULT_MAX_PUSH_RETRIES,
        seed=0,
        pipeline_pushes=None,
        model_steps=1,
        prefetch_overlap=None,
    ):
        super().__init__(model, loss_fn, optimizer_spec, seed=seed)
        self._ps = ps_client
        # Bind this trainer's Timing to the client so push_gradients
        # decomposes into push_serialize/push_wire/push_apply sub-phases
        # alongside the trainer's own pull/prefetch/step/push phases
        # (Timing is thread-safe: the pipelined path pushes from the
        # background thread).
        if getattr(ps_client, "timing", None) is None:
            ps_client.timing = self.timing
        # bf16 wire dtype extends ACROSS the host<->device hop, not just
        # the TCP wire: prefetched rows upload as bf16 (widened to f32 on
        # the chip — exact) and the jitted step hands embedding grads
        # back as bf16 (the cast runs on device), so both transfer legs
        # move half the bytes. On tunnel-attached chips those hops are
        # the PS step's measured limiter (tools/ps_push_probe.py: d2h
        # ~38 MB/s vs a 0.25 s host-side floor); on PCIe-attached chips
        # the halving still frees host memcpy/serialize time. Precision:
        # rows already crossed the wire in bf16 (no new loss); grads
        # round to bf16 before the client's f32 dedup-sum instead of
        # after — the same order the wire cast imposes on single-
        # occurrence ids, now uniform for duplicates too.
        self._bf16_wire = bool(getattr(ps_client, "bf16_wire", False))
        # Pipelined pushes (async SGD only): the gradient device_get +
        # partition + RPC runs on a background thread while this thread
        # pulls/prefetches the NEXT batch — so the per-step critical path
        # is max(device_step, rpc) instead of their sum. One push in
        # flight keeps ordering and bounds the extra staleness at one
        # version (the same delay another worker's concurrent push would
        # cause; async SGD absorbs it by design). Sync mode keeps the
        # inline path: its stale-rejection handshake must complete before
        # the next pull.
        if pipeline_pushes is None:
            pipeline_pushes = use_async
        self._pipeline_pushes = pipeline_pushes and use_async
        self._push_executor = None
        self._push_future = None
        # Prefetch overlap (async SGD only — sync mode's exactness
        # contract excludes stale rows): the embedding lookup leaves the
        # critical path two ways. (1) Lookahead: when the caller passes
        # next_features, the NEXT batch's pull RPCs are issued right
        # after this step dispatches, so they run while the device
        # computes. (2) A versioned row cache (worker/row_cache.py)
        # serves recently pulled rows within a bounded version-staleness
        # budget — the same staleness class the pipelined push already
        # introduces. Both default on via ELASTICDL_PREFETCH_DEPTH /
        # ELASTICDL_PREFETCH_CACHE_ROWS.
        if prefetch_overlap is None:
            prefetch_overlap = (
                knobs.get_int("ELASTICDL_PREFETCH_DEPTH") > 0
            )
        self._prefetch_overlap = bool(prefetch_overlap) and use_async
        self._row_cache = None
        if (
            self._prefetch_overlap
            and knobs.get_int("ELASTICDL_PREFETCH_CACHE_ROWS") > 0
        ):
            from elasticdl_tpu.worker.row_cache import EmbeddingRowCache

            self._row_cache = EmbeddingRowCache()
        # One lookahead prefetch in flight: (features object, handle).
        self._pending_prefetch = None
        # get_model_steps (reference worker.py:314-327): pull fresh PS
        # params only every N training minibatches; in between, train
        # with the LOCAL model — gradients apply locally through the
        # worker's own optimizer while still being pushed every step.
        # Cuts the pull RPC (and its host decode) to 1/N.
        self._model_steps = max(1, int(model_steps or 1))
        self._since_pull = self._model_steps  # force a pull first
        self._local_step = None  # jitted local apply, built lazily
        # callable(features) -> {table_name: ids ndarray}. Optional: when
        # omitted, the ModelHandler auto-swaps oversized nn.Embed tables
        # to the PS and derives the feed by id capture (init below).
        self._embedding_inputs = embedding_inputs
        self._embedding_threshold_bytes = embedding_threshold_bytes
        # Upper placement tier: tables at or under this stay DEVICE-side
        # (row-sharded over the mesh on multi-device runs) instead of
        # PS-resident — see PSWrappedModel's tier table.
        self._embedding_device_capacity_bytes = (
            embedding_device_capacity_bytes
        )
        self._use_async = use_async
        self._max_push_retries = max_push_retries
        # Budget for _sync_model's re-seed/backoff loop on a degraded
        # shard before failing the minibatch up the retry ladder. The
        # bound applies between attempts: one in-flight pull can still
        # take up to its own rpc retry budget (deadline x attempts) on a
        # TCP-accepting-but-wedged peer, so the worst case is this budget
        # plus one pull's budget.
        self._degraded_block_seconds = knobs.get_float(
            "ELASTICDL_PS_DEGRADED_BLOCK_SECONDS"
        )
        self._param_names = None
        self._embedding_dims = {}  # table -> dim, derived at init
        # table -> module-scope path inside the edl_embedding collection
        # (flax nests collection entries under the owning module's path).
        self._embedding_paths = {}
        self._ps_step = None
        self._ps_forward = None
        # Set when the ModelHandler wrapped the user model (auto embedding
        # placement); export unwraps back to this original module's tree.
        self._inner_model = None
        self._embedding_vocab = {}  # table -> declared vocab (auto mode)

    # ---------- init ----------

    def init_variables_if_needed(self, features):
        if self._variables is not None:
            return
        auto = self._embedding_inputs is None
        if auto:
            # ModelHandler pass (common/model_handler.py): reroute any
            # nn.Embed over the size threshold to the PS. The wrapper is
            # discarded below if nothing swapped, so small models keep
            # their unprefixed param tree.
            from elasticdl_tpu.common.model_handler import (
                DEFAULT_THRESHOLD_BYTES,
                discover_tables,
                wrap_model_for_ps,
            )

            self._inner_model = self._model
            self._model = wrap_model_for_ps(
                self._model,
                self._embedding_threshold_bytes
                or DEFAULT_THRESHOLD_BYTES,
                device_capacity_bytes=(
                    self._embedding_device_capacity_bytes
                ),
            )
            with discover_tables() as discovered:
                super().init_variables_if_needed(features)
            # {table: (dim, vocab)} — vocab sizes the export reverse-swap.
            self._embedding_vocab = {
                t: vocab for t, (_, vocab) in discovered.items()
            }
        else:
            super().init_variables_if_needed(features)
        # The init-created embedding collection only carried shapes; rows
        # arrive per-batch. Record each table's dim and scope path, then
        # drop the collection from state.
        emb = self._variables.pop(EMBEDDING_COLLECTION, {})
        for path, leaf in _walk_dict(emb):
            table = path[-1]  # innermost key is the table_name
            self._embedding_dims[table] = int(leaf.shape[-1])
            self._embedding_paths[table] = path
        if auto and not self._embedding_dims:
            # Nothing swapped and no DistributedEmbedding layers: drop the
            # wrapper. It added exactly one 'inner' nesting level and no
            # params of its own, so stripping that level (instead of a
            # second full init/trace) restores the unprefixed tree.
            self._model = self._inner_model
            self._inner_model = None
            self._variables = {
                k: (v["inner"] if hasattr(v, "keys") and "inner" in v else v)
                for k, v in self._variables.items()
            }
            self._opt_state = self._optax.init(self._variables["params"])
            self._train_step = self._build_train_step()
            self._forward = self._build_forward()
        if self._embedding_dims and self._embedding_inputs is None:
            # Derive the feed the reference's ModelHandler made implicit:
            # capture which ids each table consumed on this first batch
            # and match them back to feature leaves.
            from elasticdl_tpu.common.model_handler import (
                derive_embedding_inputs,
            )

            self._embedding_inputs = derive_embedding_inputs(
                self._model, self._variables, features
            )
            if self._embedding_inputs is None:
                raise ValueError(
                    "model has PS-resident embedding tables "
                    f"{sorted(self._embedding_dims)} but the ids feed "
                    "could not be derived; provide embedding_inputs in "
                    "the model spec"
                )
        _, self._param_names = flatten_params(self._variables["params"])
        # First worker seeds the PS; later pushes are ignored there.
        self._push_local_model()
        self._ps_step = self._build_ps_step()
        self._ps_forward = self._build_ps_forward()

    def _embedding_infos(self):
        return [
            pb.EmbeddingTableInfo(
                name=name, dim=dim, initializer="uniform", dtype=pb.DT_FLOAT32
            )
            for name, dim in sorted(self._embedding_dims.items())
        ]

    def _push_local_model(self, only_unseeded=False):
        """only_unseeded: re-seed fan-out targets just the shards the last
        pull found uninitialized/unreachable — healthy shards would only
        discard the re-shipped model, and an outage's backoff loop calls
        this repeatedly."""
        named, _ = flatten_params(jax.device_get(self._variables["params"]))
        only_shards = None
        if only_unseeded and self._ps.unseeded_shards:
            only_shards = set(self._ps.unseeded_shards)
        self._ps.push_model(
            named,
            self._embedding_infos(),
            version=self._version,
            only_shards=only_shards,
        )

    # ---------- PS sync ----------

    def _maybe_sync_model(self):
        """Pull from the PS only when the local model is stale
        (get_model_steps-style local training): fresh pull resets the
        counter; between pulls the local optimizer keeps the dense params
        moving."""
        if self._since_pull >= self._model_steps:
            self._sync_model()
            return True
        self._since_pull += 1
        return False

    def _apply_local(self, param_grads):
        """Advance the LOCAL dense params with this step's grads (the
        reference's _update_local_model) so the next minibatch's forward
        doesn't need a pull. The PS still owns the truth — the next pull
        overwrites any local drift."""
        if self._local_step is None:
            from elasticdl_tpu.observability.profiling import tracked_jit

            def apply(params, opt_state, grads):
                updates, opt_state = self._optax.update(
                    grads, opt_state, params
                )
                import optax as _optax

                return _optax.apply_updates(params, updates), opt_state

            # key_argnums=(): params/opt_state/grads shapes are static
            # after init, and hashing three full trees per step is the
            # cost the train-step key deliberately avoids.
            # donate (params, opt_state): the caller replaces both with
            # the results, so XLA updates in place instead of
            # re-allocating a params+moments copy every local step.
            # grads are NOT donated — the pipelined path hands them to
            # the push thread after this apply.
            self._local_step = tracked_jit(
                apply, name="ps_local_apply", key_argnums=(),
                donate_argnums=(0, 1),
            )
        self._variables["params"], self._opt_state = self._local_step(
            self._variables["params"], self._opt_state, param_grads
        )

    def _sync_model(self):
        """Pull dense params; re-seed any uninitialized shard from local
        weights (that IS the PS fault-tolerance path).

        Dense pulls BLOCK with bounded backoff through a shard outage: an
        unreachable shard reports as uninitialized (PSClient marks it
        degraded instead of raising), this loop re-seeds + re-pulls with
        growing sleeps until the shard answers or the budget runs out,
        and only then raises — which hands recovery to the worker's
        minibatch retry ladder and, past that, the master's task retries."""
        import time as _time

        deadline = _time.time() + self._degraded_block_seconds
        backoff = 0.5
        while True:
            # The PSClient tracks per-shard pull cursors: a shard only
            # re-sends params newer than this client's last pull from it.
            initialized, version, named = self._ps.pull_dense_parameters(
                self._param_names
            )
            if initialized:
                break
            logger.info(
                "Uninitialized/degraded PS shard found; re-seeding from "
                "local (degraded=%s)",
                sorted(self._ps.degraded_shards),
            )
            try:
                self._push_local_model(only_unseeded=True)
                initialized, version, named = (
                    self._ps.pull_dense_parameters(self._param_names)
                )
                if initialized:
                    break
            except grpc.RpcError:
                # Every shard refused the re-seed: still mid-outage; keep
                # backing off until the budget runs out.
                pass
            if _time.time() >= deadline:
                raise RuntimeError(
                    "PS still uninitialized after re-seed (degraded "
                    f"shards: {sorted(self._ps.degraded_shards)})"
                )
            _time.sleep(backoff)
            backoff = min(backoff * 2, 4.0)
        if version < self._version:
            # Version consistency check for the relaunch path: a shard
            # that came back BEHIND this worker was restored from an older
            # checkpoint (or freshly re-seeded at a lower version). The PS
            # owns the model version — adopt its clock so this worker's
            # pushes don't arrive "from the future" forever.
            logger.warning(
                "PS model version regressed to %d (< local %d) — "
                "checkpoint-restored shard; adopting the PS version",
                version,
                self._version,
            )
            self._version = version
        if named:
            self._variables["params"] = unflatten_like(
                self._variables["params"],
                {k: jnp.asarray(v) for k, v in named.items()},
            )
        self._version = max(self._version, version)
        if self._row_cache is not None:
            self._row_cache.note_version(self._version)
        # Reset the local-training cadence only on a SUCCESSFUL pull: a
        # transient PS failure must not suppress re-pull attempts for the
        # next model_steps-1 minibatches.
        self._since_pull = 1

    def _start_prefetch(self, features, use_cache=True):
        """Issue the embedding pulls for one batch WITHOUT waiting.

        Per table: dedup the batch's ids, serve what the row cache can
        (within its staleness budget), and fire the pull RPC fan-out for
        the misses only. Returns an opaque handle for _finish_prefetch.
        The split is the overlap point: between start and finish the
        caller runs the dense pull — or, on the lookahead path, the
        whole previous step's device compute."""
        if not self._embedding_dims:
            return {}
        cache = self._row_cache if use_cache else None
        handle = {}
        # Tables often key off the SAME ids array (DeepFM's wide/deep
        # share one id space); dedup that work once per distinct array.
        uniq_memo = {}
        for table, ids in self._embedding_inputs(features).items():
            memo_key = id(ids)
            if memo_key in uniq_memo:
                flat, unique, inverse = uniq_memo[memo_key]
            else:
                # flat keeps the feature dtype (int32 ids sort faster);
                # the push path widens to int64 at the wire boundary.
                flat = np.asarray(ids).reshape(-1)
                unique, inverse = _unique_inverse(flat)
                uniq_memo[memo_key] = (flat, unique, inverse)
            hit, cached_rows = (None, None)
            miss_ids = unique
            if cache is not None:
                hit, cached_rows = cache.lookup(table, unique)
                miss_ids = unique[~hit]
            # bf16 wire: pull the rows AS bf16 and widen on the chip
            # (exact) — half the bytes across the host->device hop.
            pending = None
            if miss_ids.size:
                pending = self._ps.pull_embedding_vectors_async(
                    table, miss_ids, keep_wire_dtype=self._bf16_wire
                )
            handle[table] = (
                flat, unique, inverse, hit, cached_rows, miss_ids, pending
            )
        return handle

    def _finish_prefetch(self, handle, use_cache=True):
        """Harvest a _start_prefetch handle -> (rows pytree, flat_ids).
        Pulled miss rows enter the row cache stamped with the current
        version."""
        cache = self._row_cache if use_cache else None
        by_path, flat_ids = {}, {}
        for table, (
            flat, unique, inverse, hit, cached_rows, miss_ids, pending
        ) in handle.items():
            pulled = pending.result() if pending is not None else None
            if hit is None:  # cache not in play
                rows = pulled
            else:
                if cache is not None and pulled is not None:
                    cache.insert(table, miss_ids, pulled)
                if pulled is None:
                    rows = cached_rows  # every id hit, in unique order
                elif cached_rows is None:
                    rows = pulled  # every id missed
                else:
                    rows = np.empty(
                        (unique.size,) + pulled.shape[1:], pulled.dtype
                    )
                    rows[hit] = cached_rows
                    rows[~hit] = pulled
            by_path[self._embedding_paths[table]] = jnp.asarray(
                rows[inverse]
            )
            flat_ids[table] = flat
        return _nest_at(by_path), flat_ids

    def _prefetch_embeddings(self, features, use_cache=True):
        """features -> (rows {table: [n_positions, dim]}, flat_ids
        {table: [n_positions]}). Pulls unique ids only; expands back by
        inverse so the in-jit layer does a plain reshape. (The blocking
        wrapper over _start/_finish_prefetch — eval uses it.)"""
        if not self._embedding_dims:
            return {}, {}
        return self._finish_prefetch(
            self._start_prefetch(features, use_cache=use_cache),
            use_cache=use_cache,
        )

    def _take_pending_prefetch(self, features):
        """The lookahead handle issued for `features` last step, if the
        caller's hint matched (object identity — the hot loops hand the
        same batch objects back); a mismatch discards the handle (its
        futures complete harmlessly server-side)."""
        pending, self._pending_prefetch = self._pending_prefetch, None
        if pending is not None and pending[0] is features:
            return pending[1]
        return None

    # ---------- jitted steps ----------

    def _widen_rows(self, rows):
        """bf16-uploaded rows -> f32 on the chip (exact; the model's
        embedding math stays f32 regardless of the wire dtype)."""
        if not self._bf16_wire:
            return rows
        return jax.tree_util.tree_map(
            lambda r: r.astype(jnp.float32), rows
        )

    def _build_ps_step(self):
        def step(params, state, emb_rows, rng, features, labels):
            def loss_of(p, rows):
                mutable = [k for k in state]
                out = self._model.apply(
                    {
                        "params": p,
                        **state,
                        EMBEDDING_COLLECTION: self._widen_rows(rows),
                    },
                    features,
                    training=True,
                    rngs={"dropout": rng},
                    mutable=mutable if mutable else False,
                )
                outputs, new_state = out if mutable else (out, state)
                return self._loss_fn(labels, outputs), new_state

            # Differentiating through the bf16->f32 widen makes the row
            # cotangents come out bf16 automatically: the device casts,
            # and device_get in the push moves half the bytes.
            # (Design note: expanding unique rows by the batch inverse
            # INSIDE the jit — so the backward would segment-sum the
            # cotangents into pre-deduped [n_unique, dim] grads — was
            # tried and reverted: XLA's scatter-add costs ~5x the native
            # hash dedup on a CPU host. Host-side dedup stays.)
            (loss, new_state), grads = jax.value_and_grad(
                loss_of, argnums=(0, 1), has_aux=True
            )(params, emb_rows)
            return loss, grads[0], grads[1], new_state

        # Keyed on (emb_rows, features, labels): per-batch embedding row
        # counts are the shape axis that actually varies in PS mode.
        from elasticdl_tpu.observability.profiling import tracked_jit

        # Donate the mutable-state collections (new_state aliases state)
        # and the prefetched embedding rows (the row cotangents have the
        # rows' exact shape and dtype — the bf16 wire keeps both legs
        # bf16 — so XLA writes the grads into the rows' buffers instead
        # of allocating a second copy of the step's largest input).
        # params/features/labels stay un-donated: params live on in
        # self._variables between pulls, and the sync-mode retry loop
        # re-feeds the same device batch after a stale rejection.
        return tracked_jit(
            step, name="ps_step", key_argnums=(2, 4, 5),
            donate_argnums=(1, 2),
        )

    def _build_ps_forward(self):
        from elasticdl_tpu.observability.profiling import tracked_jit

        def forward(params, state, emb_rows, features):
            return self._model.apply(
                {
                    "params": params,
                    **state,
                    EMBEDDING_COLLECTION: self._widen_rows(emb_rows),
                },
                features,
                training=False,
            )

        return tracked_jit(
            forward, name="ps_forward", key_argnums=(2, 3)
        )

    # ---------- Trainer interface ----------

    def _push_payload(self, param_grads, emb_grads, flat_ids, version,
                      batch_size):
        """Materialize grads off-device, partition, and push. Runs inline
        (sync mode) or on the push thread (pipelined async mode), where
        the device_get doubles as the wait for the step's compute."""
        with self.timing.record("push_gradients"):
            # ONE batched D2H for the whole gradient tree: the per-leaf
            # np.asarray below used to issue a separate blocking
            # transfer per embedding table (hot-path-sync).
            param_grads, emb_grads = jax.device_get(
                (param_grads, emb_grads)
            )
            dense_named, _ = flatten_params(param_grads)
            sparse = {}
            for path, g in _walk_dict(emb_grads):
                table = path[-1]
                sparse[table] = (
                    np.asarray(g).reshape(
                        -1, self._embedding_dims[table]
                    ),
                    flat_ids[table],
                )
            accepted, version = self._ps.push_gradients(
                dense_named,
                sparse,
                version=version,
                batch_size=batch_size,
            )
        self._version = max(self._version, version)
        if self._row_cache is not None:
            # Our apply bumped the PS clock: age the cache so rows drop
            # out once they exceed the staleness budget. (Thread-safe —
            # this runs on the push thread in pipelined mode.)
            self._row_cache.note_version(self._version)
        return accepted, version

    def _flush_pushes(self):
        """Wait for the in-flight background push (read-your-writes for
        eval/export pulls; also the error-propagation point — a failed
        push raises here and the worker's retry machinery takes over)."""
        future, self._push_future = self._push_future, None
        if future is not None:
            future.result()

    def train_minibatch(self, features, labels, next_features=None):
        """next_features: optional hint — the NEXT batch the caller will
        train on. With prefetch overlap on (async pipelined mode), its
        embedding pulls are issued while this step's device compute and
        push run, taking the lookup off the next call's critical path."""
        self.init_variables_if_needed(features)
        if self._pipeline_pushes:
            return self._train_minibatch_pipelined(
                features, labels, next_features
            )
        with datapath.get().stage("h2d", timing=self.timing):
            device_features = _to_device_batch(features)
            device_labels = _to_device_batch(labels)
        for attempt in range(self._max_push_retries):
            # Issue the embedding pulls BEFORE the dense pull waits:
            # both fan-outs ride the wire together instead of in series.
            with self.timing.record("prefetch_issue"):
                handle = self._start_prefetch(features)
            with self.timing.record("pull_model"):
                if attempt == 0:
                    self._maybe_sync_model()
                else:
                    # A stale rejection means the local model diverged
                    # from the PS: the retry must re-pull regardless of
                    # the local-training cadence.
                    self._sync_model()
            with self.timing.record("prefetch_embeddings"):
                emb_rows, flat_ids = self._finish_prefetch(handle)
            self._rng, step_rng = jax.random.split(self._rng)
            state = {
                k: v for k, v in self._variables.items() if k != "params"
            }
            step_args = (
                self._variables["params"],
                state,
                emb_rows,
                step_rng,
                device_features,
                device_labels,
            )
            self.step_cost.observe(
                self._ps_step, step_args, key_args=step_args[4:]
            )
            with self.timing.record("train_step"):
                loss, param_grads, emb_grads, new_state = self._ps_step(
                    *step_args
                )
            self._variables.update(new_state)
            accepted, _ = self._push_payload(
                param_grads,
                emb_grads,
                flat_ids,
                self._version,
                int(np.asarray(labels).shape[0]),
            )
            if accepted:
                # Local apply only for ACCEPTED steps: a stale-rejected
                # attempt re-pulls anyway, and folding its grads into the
                # local Adam moments once per retry would bias them.
                if self._model_steps > 1:
                    self._apply_local(param_grads)
                # Lazy loss (Trainer contract): float() here would block
                # the host on the device every step; callers materialize
                # at the logging boundary.
                return True, self._version, loss
            logger.info(
                "Gradient push rejected as stale (attempt %d); re-pulling",
                attempt + 1,
            )
        return False, self._version, loss

    def _train_minibatch_pipelined(self, features, labels,
                                   next_features=None):
        """Async-SGD step with the push AND the embedding lookup off the
        critical path: while the device still computes step N, this
        thread already pulls params for step N+1, and step N+1's
        embedding pulls were issued LAST call (lookahead) — the
        reference's hot loop serializes a pull, a mid-forward lookup
        RPC, the step, and the push (ps_trainer.py:372-401)."""
        import concurrent.futures

        if self._push_executor is None:
            self._push_executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="edl-ps-push"
            )
        with datapath.get().stage("h2d", timing=self.timing):
            device_features = _to_device_batch(features)
            device_labels = _to_device_batch(labels)
        # These RPCs overlap the PREVIOUS step's device compute.
        handle = self._take_pending_prefetch(features)
        if handle is None:
            with self.timing.record("prefetch_issue"):
                handle = self._start_prefetch(features)
        with self.timing.record("pull_model"):
            self._maybe_sync_model()
        with self.timing.record("prefetch_embeddings"):
            emb_rows, flat_ids = self._finish_prefetch(handle)
        self._rng, step_rng = jax.random.split(self._rng)
        state = {
            k: v for k, v in self._variables.items() if k != "params"
        }
        step_args = (
            self._variables["params"],
            state,
            emb_rows,
            step_rng,
            device_features,
            device_labels,
        )
        self.step_cost.observe(
            self._ps_step, step_args, key_args=step_args[4:]
        )
        with self.timing.record("train_step_dispatch"):
            loss, param_grads, emb_grads, new_state = self._ps_step(
                *step_args
            )
        self._variables.update(new_state)
        if self._model_steps > 1:
            self._apply_local(param_grads)
        # One push in flight: wait out the previous (raising its errors),
        # then hand this step's grads to the push thread. Its device_get
        # blocks there until the step's compute finishes.
        self._flush_pushes()
        self._push_future = self._push_executor.submit(
            self._push_payload,
            param_grads,
            emb_grads,
            flat_ids,
            self._version,
            int(np.asarray(labels).shape[0]),
        )
        # Lookahead: issue the NEXT batch's embedding pulls now — they
        # ride the wire while this step's device compute and push finish,
        # so the next call's prefetch phase is just a harvest.
        if self._prefetch_overlap and next_features is not None:
            with self.timing.record("prefetch_issue"):
                self._pending_prefetch = (
                    next_features, self._start_prefetch(next_features)
                )
        # Lazy loss: materializing here would re-serialize the pipeline.
        return True, self._version, loss

    def evaluate_minibatch(self, features, model_version=-1):
        self.init_variables_if_needed(features)
        self._flush_pushes()  # read-your-writes for the eval pull
        self._sync_model()
        # use_cache=False: eval reads the freshest rows — the bounded
        # staleness the training loop absorbs has no place in metrics.
        emb_rows, _ = self._prefetch_embeddings(features, use_cache=False)
        state = {k: v for k, v in self._variables.items() if k != "params"}
        outputs = self._ps_forward(
            self._variables["params"],
            state,
            emb_rows,
            _to_device_batch(features),
        )
        return jax.tree_util.tree_map(np.asarray, outputs)

    def get_model_version(self):
        return self._version

    def close(self):
        try:
            self._flush_pushes()
        finally:
            if self._push_executor is not None:
                self._push_executor.shutdown(wait=True)
                self._push_executor = None

    def export_variables(self):
        """Export with the reverse swap (reference model_handler.py:242-268):
        pull final dense params AND full embedding tables from the PS, stuff
        tables back into the ORIGINAL model's param tree as plain
        `embedding` params, and strip the ModelHandler wrapper's nesting so
        the checkpoint loads into the user's stock model."""
        if self._variables is None:
            return None
        self._flush_pushes()  # the export must include the last push
        self._sync_model()
        variables = jax.device_get(dict(self._variables))
        params = variables["params"]
        if self._inner_model is not None:
            params = params.get("inner", params)
            ps_tables = {}
            for table, dim in self._embedding_dims.items():
                ids, values = self._ps.pull_embedding_table(
                    table, dim=dim
                )
                if values is not None:
                    ps_tables[table] = (ids, values)
            from elasticdl_tpu.common.model_handler import (
                stuff_export_params,
            )

            params = stuff_export_params(
                params, ps_tables, default_vocab=self._embedding_vocab
            )
            variables = {
                k: (v.get("inner", v) if hasattr(v, "get") else v)
                for k, v in variables.items()
            }
        variables["params"] = params
        return {"variables": variables, "version": self._version}
