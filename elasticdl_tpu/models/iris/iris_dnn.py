"""Iris DNN (reference /root/reference/model_zoo/odps_iris_dnn_model/ —
4 numeric features -> 2x Dense -> 3-way softmax; its feed parses CSV-style
string rows, exercising the CSV reader path)."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import accuracy_metric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.ops import optimizers


class IrisDNN(nn.Module):
    @nn.compact
    def __call__(self, x, training: bool = False):
        x = nn.relu(nn.Dense(16)(x))
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(3)(x)


def custom_model():
    return IrisDNN()


def loss(labels, predictions):
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(
            predictions, labels.reshape(-1).astype(jnp.int32)
        )
    )


def optimizer(lr=0.1):
    return optimizers.adagrad(learning_rate=lr)


def feed(records, mode, metadata):
    """Records are CSV row tuples of strings (CSVDataReader output):
    sepal_len, sepal_w, petal_len, petal_w, label."""
    rows = np.asarray(
        [[float(v) for v in row] for row in records], np.float32
    )
    features = rows[:, :4]
    labels = rows[:, 4] if mode != Modes.PREDICTION else None
    return features, labels


def eval_metrics_fn():
    return {"accuracy": accuracy_metric()}


def make_csv(path, n=150, seed=0):
    """Synthetic separable iris-like CSV."""
    rng = np.random.default_rng(seed)
    centers = np.asarray(
        [[5.0, 3.4, 1.5, 0.2], [5.9, 2.8, 4.3, 1.3], [6.6, 3.0, 5.6, 2.1]],
        np.float32,
    )
    with open(path, "w") as f:
        for _ in range(n):
            label = rng.integers(0, 3)
            row = centers[label] + rng.normal(scale=0.15, size=4)
            f.write(
                ",".join(f"{v:.3f}" for v in row) + f",{label}\n"
            )
    return path
