"""CIFAR-10 CNN zoo model.

Reference counterpart: /root/reference/model_zoo/cifar10/
cifar10_functional_api.py:16-103 — three (Conv-BN-relu)x2 + MaxPool +
Dropout stages at 32/64/128 channels, flatten, softmax head; Adam with LR
schedule callback. NHWC layout for MXU-friendly convs.
"""

import flax.linen as nn
import jax.numpy as jnp
import optax

from elasticdl_tpu.common.evaluation_utils import accuracy_metric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.ops import optimizers

NUM_CLASSES = 10


class Cifar10CNN(nn.Module):
    num_classes: int = NUM_CLASSES

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.reshape(x.shape[0], 32, 32, 3)
        for channels in (32, 64, 128):
            for _ in range(2):
                x = nn.Conv(channels, (3, 3), padding="SAME")(x)
                x = nn.BatchNorm(
                    use_running_average=not training,
                    epsilon=1e-6,
                    momentum=0.9,
                )(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = nn.Dropout(0.2, deterministic=not training)(x)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(self.num_classes)(x)


def custom_model():
    return Cifar10CNN()


def loss(labels, predictions):
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(
            predictions, labels.reshape(-1)
        )
    )


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    image = batch["image"]
    features = image.astype("float32")
    if image.dtype == "uint8":
        # Real pickle-converted records (data/gen/cifar10_pickle.py)
        # carry raw 0-255 bytes; synthetic float records are unit-scale.
        features = features / 255.0
    labels = batch["label"] if mode != Modes.PREDICTION else None
    return features, labels


def eval_metrics_fn():
    return {"accuracy": accuracy_metric()}
