"""Census wide & deep declared entirely as feature columns.

Reference counterpart: /root/reference/model_zoo/census_model_sqlflow/
wide_and_deep/ — the SQLFlow-generated census model whose feature handling
is a declarative transform graph (vocab lookups, bucketize, hash, embed)
parameterized by analyzer statistics. Here the same shape is expressed
with elasticdl_tpu.preprocessing.feature_column specs, with boundaries and
vocabularies overridable through the analyzer env contract
(preprocessing/analyzer_utils.py) exactly as an external analysis job
would publish them.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import MeanMetric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples, encode_example
from elasticdl_tpu.ops import optimizers
from elasticdl_tpu.preprocessing import analyzer_utils
from elasticdl_tpu.preprocessing import feature_column as fc

WORKCLASS_VOCAB = [
    "Private",
    "Self-emp-not-inc",
    "Self-emp-inc",
    "Federal-gov",
    "Local-gov",
    "State-gov",
    "Without-pay",
    "Never-worked",
]
EDUCATION_VOCAB = [
    "Bachelors",
    "HS-grad",
    "11th",
    "Masters",
    "9th",
    "Some-college",
    "Assoc-acdm",
    "Assoc-voc",
    "Doctorate",
    "Prof-school",
]


def build_columns():
    """Analyzer-statistics-driven column specs (env-overridable)."""
    age_boundaries = analyzer_utils.get_bucket_boundaries(
        "age", [25, 35, 45, 55, 65]
    )
    hours_boundaries = analyzer_utils.get_bucket_boundaries(
        "hours", [20, 35, 45]
    )
    workclass = fc.categorical_column_with_vocabulary_list(
        "workclass", analyzer_utils.get_vocabulary(
            "workclass", WORKCLASS_VOCAB
        )
    )
    education = fc.categorical_column_with_vocabulary_list(
        "education", analyzer_utils.get_vocabulary(
            "education", EDUCATION_VOCAB
        )
    )
    occupation = fc.categorical_column_with_hash_bucket("occupation", 50)
    age_bucket = fc.bucketized_column("age", age_boundaries)
    hours_bucket = fc.bucketized_column("hours", hours_boundaries)

    wide = tuple(
        fc.indicator_column(cat)
        for cat in (workclass, education, occupation, age_bucket,
                    hours_bucket)
    )
    deep = (
        fc.embedding_column(workclass, 8),
        fc.embedding_column(education, 8),
        fc.embedding_column(occupation, 8),
        fc.embedding_column(age_bucket, 8),
        fc.embedding_column(hours_bucket, 8),
        fc.numeric_column(
            "age",
            normalizer_fn=lambda x: (
                x - analyzer_utils.get_avg("age", 38.0)
            ) / analyzer_utils.get_stddev("age", 13.0),
        ),
        fc.numeric_column(
            "hours",
            normalizer_fn=lambda x: (
                x - analyzer_utils.get_avg("hours", 40.0)
            ) / analyzer_utils.get_stddev("hours", 12.0),
        ),
    )
    return wide, deep


class WideDeepFC(nn.Module):
    wide_columns: tuple
    deep_columns: tuple

    @nn.compact
    def __call__(self, features, training: bool = False):
        wide = fc.DenseFeatures(self.wide_columns, name="wide")(features)
        deep = fc.DenseFeatures(self.deep_columns, name="deep")(features)
        for width in (32, 16):
            deep = nn.relu(nn.Dense(width)(deep))
        logit = nn.Dense(1)(jnp.concatenate([wide, deep], axis=-1))
        return logit.reshape(-1)


_WIDE, _DEEP = None, None


def _columns():
    global _WIDE, _DEEP
    if _WIDE is None:
        _WIDE, _DEEP = build_columns()
    return _WIDE, _DEEP


def custom_model():
    wide, deep = _columns()
    return WideDeepFC(wide, deep)


def loss(labels, logits):
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(
            logits.reshape(-1), labels.reshape(-1).astype(jnp.float32)
        )
    )


def optimizer(lr=0.01):
    return optimizers.adam(learning_rate=lr)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    wide, deep = _columns()
    # Host-side pass: hash/vocab string columns become int ids; the model
    # sees only numbers (required under jit).
    features = fc.DenseFeatures(wide + deep).preprocess(batch)
    labels = (
        batch["label"].astype(np.float32)
        if mode != Modes.PREDICTION
        else None
    )
    features.pop("label", None)
    return features, labels


def eval_metrics_fn():
    def correct(outputs, labels):
        preds = (np.asarray(outputs).reshape(-1) > 0).astype(np.float32)
        return (preds == np.asarray(labels).reshape(-1)).astype(
            np.float32
        )

    return {"accuracy": MeanMetric(correct)}


def make_records(n, seed=0):
    """Synthetic census-like rows with a learnable relationship."""
    rng = np.random.default_rng(seed)
    w_work = rng.normal(size=len(WORKCLASS_VOCAB) + 1)
    w_edu = rng.normal(size=len(EDUCATION_VOCAB) + 1)
    records = []
    for _ in range(n):
        wi = int(rng.integers(0, len(WORKCLASS_VOCAB)))
        ei = int(rng.integers(0, len(EDUCATION_VOCAB)))
        age = float(rng.uniform(18, 80))
        hours = float(rng.uniform(5, 60))
        score = w_work[wi] + w_edu[ei] + 0.03 * (age - 45)
        records.append(
            encode_example(
                {
                    "workclass": WORKCLASS_VOCAB[wi],
                    "education": EDUCATION_VOCAB[ei],
                    "occupation": f"occ{int(rng.integers(0, 30))}",
                    "age": np.float32(age),
                    "hours": np.float32(hours),
                    "label": np.int64(score > 0),
                }
            )
        )
    return records
