"""MobileNetV2 zoo model (ImageNet shapes).

Reference counterpart: the MobileNetV2 benchmark configs in
/root/reference/docs/benchmark/ftlib_benchmark.md:79-92,138-156 (CIFAR-10
CPU scaling and ImageNet GPU scaling — 150 img/s on one P100), trained
through stock keras.applications in the reference zoo style. TPU-first:
NHWC, bf16 activations with fp32 batch-norm statistics, inverted residual
blocks as plain flax modules XLA fuses end-to-end.
"""

from functools import partial

import flax.linen as nn
import jax.numpy as jnp
import optax

from elasticdl_tpu.common.evaluation_utils import accuracy_metric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.ops import optimizers

# (expansion t, out channels c, repeats n, stride s) — the V2 paper table.
_BLOCKS = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def _round_channels(c, multiplier, divisor=8):
    c = c * multiplier
    rounded = max(divisor, int(c + divisor / 2) // divisor * divisor)
    if rounded < 0.9 * c:
        rounded += divisor
    return int(rounded)


class InvertedResidual(nn.Module):
    out_channels: int
    stride: int
    expansion: int
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x, training: bool = False):
        dtype = jnp.dtype(self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not training,
            momentum=0.9,
            dtype=jnp.float32,
        )
        in_channels = x.shape[-1]
        hidden = in_channels * self.expansion
        y = x
        if self.expansion != 1:
            y = nn.Conv(
                hidden, (1, 1), use_bias=False, dtype=dtype
            )(y)
            y = nn.relu6(norm()(y).astype(dtype))
        y = nn.Conv(
            hidden,
            (3, 3),
            strides=(self.stride, self.stride),
            padding="SAME",
            feature_group_count=hidden,
            use_bias=False,
            dtype=dtype,
        )(y)
        y = nn.relu6(norm()(y).astype(dtype))
        y = nn.Conv(
            self.out_channels, (1, 1), use_bias=False, dtype=dtype
        )(y)
        y = norm()(y).astype(dtype)
        if self.stride == 1 and in_channels == self.out_channels:
            y = y + x
        return y


class MobileNetV2(nn.Module):
    num_classes: int = 1000
    width_multiplier: float = 1.0
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x, training: bool = False):
        dtype = jnp.dtype(self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not training,
            momentum=0.9,
            dtype=jnp.float32,
        )
        x = x.astype(dtype)
        x = nn.Conv(
            _round_channels(32, self.width_multiplier),
            (3, 3),
            strides=(2, 2),
            padding="SAME",
            use_bias=False,
            dtype=dtype,
        )(x)
        x = nn.relu6(norm()(x).astype(dtype))
        for t, c, n, s in _BLOCKS:
            channels = _round_channels(c, self.width_multiplier)
            for i in range(n):
                x = InvertedResidual(
                    out_channels=channels,
                    stride=s if i == 0 else 1,
                    expansion=t,
                    dtype=self.dtype,
                )(x, training=training)
        head = _round_channels(
            1280, max(1.0, self.width_multiplier)
        )
        x = nn.Conv(head, (1, 1), use_bias=False, dtype=dtype)(x)
        x = nn.relu6(norm()(x).astype(dtype))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def custom_model():
    return MobileNetV2()


def loss(labels, predictions):
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(
            predictions, labels.reshape(-1)
        )
    )


def optimizer(lr=0.05):
    return optimizers.momentum(learning_rate=lr, momentum_value=0.9)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    features = batch["image"].astype("float32")
    labels = batch["label"] if mode != Modes.PREDICTION else None
    return features, labels


def eval_metrics_fn():
    return {"accuracy": accuracy_metric()}
