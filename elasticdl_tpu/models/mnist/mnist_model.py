"""MNIST CNN — the zoo's hello-world model.

Reference counterpart: /root/reference/model_zoo/mnist/
mnist_functional_api.py:21-103 (Conv 32 / Conv 64 / BatchNorm / MaxPool /
Dense 1024 / Dense 10, SGD(0.01), sparse softmax CE). Rebuilt as a flax
module; compute stays NHWC + bfloat16-friendly so XLA tiles the convs onto
the MXU.
"""

import flax.linen as nn
import jax.numpy as jnp
import optax

from elasticdl_tpu.common.evaluation_utils import accuracy_metric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.ops import optimizers


class MnistCNN(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, training: bool = False):
        # Accept [B, 28*28] or [B, 28, 28]; conv in NHWC.
        x = x.reshape(x.shape[0], 28, 28, 1)
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(x)
        x = nn.BatchNorm(use_running_average=not training)(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(1024)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


def custom_model():
    return MnistCNN()


def loss(labels, predictions):
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(
            predictions, labels.reshape(-1)
        )
    )


def optimizer(lr=0.01):
    return optimizers.momentum(learning_rate=lr)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    image = batch["image"]
    features = image.astype("float32")
    if image.dtype == "uint8":
        # Real IDX-converted records (data/gen/mnist_idx.py) carry raw
        # 0-255 bytes; normalize so the conv stack sees unit-scale input
        # (the reference normalized in its feature transform too).
        # Synthetic float records are already unit-scale.
        features = features / 255.0
    labels = batch["label"] if mode != Modes.PREDICTION else None
    return features, labels


def eval_metrics_fn():
    return {"accuracy": accuracy_metric()}
