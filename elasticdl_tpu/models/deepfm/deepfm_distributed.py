"""DeepFM with PS-resident embedding tables.

Reference counterpart: /root/reference/model_zoo/deepfm_edl_embedding/
deepfm_edl_embedding.py:19-58 — same architecture as the functional DeepFM
but the first-order weights and FM factors live in the parameter server via
the distributed embedding layer, so the (potentially huge) vocabulary never
materializes in device memory. `embedding_inputs` feeds the PS trainer's
prefetch (see worker/ps_trainer.py).
"""

import flax.linen as nn
import jax.numpy as jnp

from elasticdl_tpu.layers.embedding import DistributedEmbedding
from elasticdl_tpu.models.deepfm.deepfm_functional import (  # noqa: F401
    EMB_DIM,
    FIELDS,
    eval_metrics_fn,
    feed,
    loss,
    make_records,
    optimizer,
)


class DeepFMDistributed(nn.Module):
    emb_dim: int = EMB_DIM

    @nn.compact
    def __call__(self, ids, training: bool = False):
        linear_emb = DistributedEmbedding(
            table_name="fm_linear", dim=1
        )(ids)  # [B, F, 1]
        v = DistributedEmbedding(
            table_name="fm_factors", dim=self.emb_dim
        )(ids)  # [B, F, D]
        linear = jnp.sum(linear_emb, axis=(1, 2))
        sum_sq = jnp.square(jnp.sum(v, axis=1))
        sq_sum = jnp.sum(jnp.square(v), axis=1)
        fm = 0.5 * jnp.sum(sum_sq - sq_sum, axis=1)
        deep = v.reshape(ids.shape[0], -1)
        for width in (64, 32):
            deep = nn.relu(nn.Dense(width)(deep))
        deep = nn.Dense(1)(deep).reshape(-1)
        return linear + fm + deep


def custom_model():
    return DeepFMDistributed()


def embedding_inputs(features):
    """Both PS tables key off the same field-id array."""
    return {"fm_linear": features, "fm_factors": features}
