"""DeepFM zoo model (local trainable tables).

Reference counterpart: /root/reference/model_zoo/deepfm_functional_api/
deepfm_functional_api.py (frappe-style: fixed number of id fields; linear
first-order term + FM second-order interaction + deep MLP, summed into a
sigmoid logit). The FM term uses the (sum^2 - sum-of-squares)/2 identity —
one fused elementwise expression under XLA.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import MeanMetric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples, encode_example
from elasticdl_tpu.ops import optimizers

VOCAB = 5000
FIELDS = 10
EMB_DIM = 8


class DeepFM(nn.Module):
    vocab: int = VOCAB
    emb_dim: int = EMB_DIM

    @nn.compact
    def __call__(self, ids, training: bool = False):
        # ids: [B, FIELDS] int
        ids = ids.astype(jnp.int32)
        first_order = self.param(
            "w_linear", nn.initializers.zeros, (self.vocab, 1)
        )
        factors = self.param(
            "v_factors",
            nn.initializers.normal(stddev=0.01),
            (self.vocab, self.emb_dim),
        )
        linear = jnp.sum(
            jnp.take(first_order, ids, axis=0), axis=(1, 2)
        )  # [B]
        v = jnp.take(factors, ids, axis=0)  # [B, F, D]
        sum_sq = jnp.square(jnp.sum(v, axis=1))
        sq_sum = jnp.sum(jnp.square(v), axis=1)
        fm = 0.5 * jnp.sum(sum_sq - sq_sum, axis=1)  # [B]
        deep = v.reshape(ids.shape[0], -1)
        for width in (64, 32):
            deep = nn.relu(nn.Dense(width)(deep))
        deep = nn.Dense(1)(deep).reshape(-1)
        return linear + fm + deep


def custom_model():
    return DeepFM()


def loss(labels, logits):
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(
            logits.reshape(-1), labels.reshape(-1).astype(jnp.float32)
        )
    )


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    labels = (
        batch["label"].astype(np.float32)
        if mode != Modes.PREDICTION
        else None
    )
    return batch["ids"].astype(np.int64), labels


def eval_metrics_fn():
    def correct(outputs, labels):
        preds = (np.asarray(outputs).reshape(-1) > 0).astype(np.float32)
        return (preds == np.asarray(labels).reshape(-1)).astype(np.float32)

    return {"accuracy": MeanMetric(correct)}


def make_records(n, seed=0, vocab=VOCAB, fields=FIELDS):
    """Synthetic CTR rows: label from a sparse linear ground truth."""
    rng = np.random.default_rng(seed)
    weights = rng.normal(scale=1.0, size=vocab).astype(np.float32)
    ids = rng.integers(0, vocab, size=(n, fields))
    scores = weights[ids].sum(axis=1)
    labels = (scores > 0).astype(np.int64)
    return [
        encode_example(
            {"ids": ids[i].astype(np.int64), "label": labels[i]}
        )
        for i in range(n)
    ]
