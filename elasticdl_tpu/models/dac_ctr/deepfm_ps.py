"""Criteo DeepFM with PS-RESIDENT embedding tables — the BASELINE.json
north-star deployment shape ("large embedding_service + elastic worker
preemption").

Reference counterpart: /root/reference/model_zoo/dac_ctr/deepfm_model.py
served through the EDL embedding layer (model_zoo/deepfm_edl_embedding/
deepfm_edl_embedding.py:19-58). Same architecture and feature transform as
models/dac_ctr/deepfm (the device-resident variant benchmarks dense
compute; this one exercises the sparse pull/push path): the wide [V,1] and
deep [V,D] tables live in the parameter server, only looked-up rows ever
reach the chip. The dense side (DNN + linear) stays an ordinary param tree
pulled/pushed per step — a few KB next to the tables' ~180 MB.
"""

import flax.linen as nn
import jax.numpy as jnp

from elasticdl_tpu.layers.embedding import DistributedEmbedding
from elasticdl_tpu.models.dac_ctr.common import (
    ctr_loss,
    ctr_metrics,
    deepfm_head,
)
from elasticdl_tpu.models.dac_ctr.transform import feed  # noqa: F401
from elasticdl_tpu.ops import optimizers


class DeepFMCriteoPS(nn.Module):
    deep_dim: int = 8
    dnn_hidden_units: tuple = (16, 4)

    @nn.compact
    def __call__(self, features, training: bool = False):
        ids = features["ids"].astype(jnp.int32)  # [B, F]
        dense = features["dense"].astype(jnp.float32)  # [B, 13]
        linear = DistributedEmbedding(table_name="wide", dim=1)(ids)[
            ..., 0
        ]  # [B, F]
        field_embs = DistributedEmbedding(
            table_name="deep", dim=self.deep_dim
        )(ids)  # [B, F, D]
        dense_logit = nn.Dense(1, use_bias=False, name="dense_linear")(
            dense
        )
        linear_logits = jnp.concatenate([linear, dense_logit], axis=1)
        return deepfm_head(
            linear_logits, field_embs, dense, self.dnn_hidden_units
        )


def custom_model():
    return DeepFMCriteoPS()


def embedding_inputs(features):
    """Both PS tables key off the shared offset id space."""
    return {"wide": features["ids"], "deep": features["ids"]}


loss = ctr_loss


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def eval_metrics_fn():
    return ctr_metrics()
