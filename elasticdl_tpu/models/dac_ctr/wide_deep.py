"""Criteo wide & deep variant.

Reference counterpart: /root/reference/model_zoo/dac_ctr/
wide_deep_model.py:20-107 (wide = dim-1 embeddings + dense linear; deep =
field embeddings + standardized dense through a DNN; logits = sum of parts).
"""

import jax.numpy as jnp
import flax.linen as nn

from elasticdl_tpu.models.dac_ctr.common import (
    CTREmbeddings,
    DNN,
    ctr_loss,
    ctr_metrics,
)
from elasticdl_tpu.models.dac_ctr.transform import feed  # noqa: F401
from elasticdl_tpu.ops import optimizers


class WideDeep(nn.Module):
    deep_dim: int = 8
    dnn_hidden_units: tuple = (16, 4)

    @nn.compact
    def __call__(self, features, training: bool = False):
        linear_logits, field_embs, dense = CTREmbeddings(
            deep_dim=self.deep_dim
        )(features)
        dnn_input = jnp.concatenate(
            [dense, field_embs.reshape(field_embs.shape[0], -1)], axis=1
        )
        dnn_out = DNN(self.dnn_hidden_units)(dnn_input)
        dnn_logit = nn.Dense(1, use_bias=False)(dnn_out)
        return jnp.sum(
            jnp.concatenate([linear_logits, dnn_logit], axis=1), axis=1
        )


def custom_model():
    return WideDeep()


loss = ctr_loss


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def eval_metrics_fn():
    return ctr_metrics()
