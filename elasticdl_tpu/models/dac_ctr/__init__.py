"""Criteo display-ads CTR model family (wide&deep / deepfm / dcn / xdeepfm).

Reference counterpart: /root/reference/model_zoo/dac_ctr/ — the reference's
north-star sparse benchmark (BASELINE.json: DeepFM-Criteo examples/sec/chip).
"""
