"""Criteo Deep & Cross (DCN) variant.

Reference counterpart: /root/reference/model_zoo/dac_ctr/dcn_model.py
(cross layers over the concatenated [dense, field-embedding] vector plus a
deep tower). The cross layer keeps the standard rank-1 form
x_{l+1} = x_0 * (x_l . w_l) + b_l + x_l — elementwise + one dot, which XLA
fuses into a couple of MXU/VPU ops.
"""

import jax.numpy as jnp
import flax.linen as nn

from elasticdl_tpu.models.dac_ctr.common import (
    CTREmbeddings,
    DNN,
    ctr_loss,
    ctr_metrics,
)
from elasticdl_tpu.models.dac_ctr.transform import feed  # noqa: F401
from elasticdl_tpu.ops import optimizers


class CrossNetwork(nn.Module):
    num_layers: int = 3

    @nn.compact
    def __call__(self, x0):
        x = x0
        dim = x0.shape[-1]
        for i in range(self.num_layers):
            w = self.param(
                f"w{i}", nn.initializers.normal(stddev=0.01), (dim,)
            )
            b = self.param(f"b{i}", nn.initializers.zeros, (dim,))
            x = x0 * jnp.dot(x, w)[:, None] + b + x
        return x


class DCN(nn.Module):
    deep_dim: int = 8
    num_cross_layers: int = 3
    dnn_hidden_units: tuple = (16, 4)

    @nn.compact
    def __call__(self, features, training: bool = False):
        linear_logits, field_embs, dense = CTREmbeddings(
            deep_dim=self.deep_dim
        )(features)
        x0 = jnp.concatenate(
            [dense, field_embs.reshape(field_embs.shape[0], -1)], axis=1
        )
        cross_out = CrossNetwork(self.num_cross_layers)(x0)
        deep_out = DNN(self.dnn_hidden_units)(x0)
        head = jnp.concatenate([cross_out, deep_out], axis=1)
        logit = nn.Dense(1, use_bias=False)(head).reshape(-1)
        return jnp.sum(linear_logits, axis=1) + logit


def custom_model():
    return DCN()


loss = ctr_loss


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def eval_metrics_fn():
    return ctr_metrics()
