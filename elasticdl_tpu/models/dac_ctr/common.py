"""Shared flax pieces for the dac_ctr family.

Reference counterpart: /root/reference/model_zoo/dac_ctr/utils.py (DNN layer
+ lookup_embedding_func building one Keras Embedding per group). TPU-first:
one wide table [V,1] and one deep table [V,D] over the shared offset id
space; a single take per table serves all 39 fields, and per-field sums
(when a group has several columns) fold into the same gather.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import AUCMetric, MeanMetric
from elasticdl_tpu.models.dac_ctr.transform import TOTAL_IDS


class DNN(nn.Module):
    hidden_units: tuple = (16, 4)

    @nn.compact
    def __call__(self, x):
        for width in self.hidden_units:
            x = nn.relu(nn.Dense(width)(x))
        return x


class CTREmbeddings(nn.Module):
    """wide [V,1] + deep [V,D] tables over the shared offset vocabulary.

    Returns (linear_logits [B, F(+1)], field_embs [B, F, D], dense [B, 13]):
    everything any head (wide&deep / FM / CIN / cross) consumes.

    shard_mesh: when set, the tables' rows are DEVICE-SHARDED over that
    mesh's `shard_axis` and looked up with on-chip collectives
    (parallel/sharded_embedding.py) — the TPU-first middle tier for tables
    that exceed one chip's HBM but fit the slice. Param names stay
    "wide"/"deep" (vocab padded up to the axis size), so checkpoints
    transfer between placements.
    """

    deep_dim: int = 8
    vocab: int = TOTAL_IDS
    shard_mesh: object = None
    shard_axis: str = "data"

    @nn.compact
    def __call__(self, features):
        ids = features["ids"].astype(jnp.int32)  # [B, F]
        dense = features["dense"].astype(jnp.float32)  # [B, 13]
        vocab = self.vocab
        if self.shard_mesh is not None:
            from elasticdl_tpu.parallel.sharded_embedding import (
                padded_vocab,
            )

            vocab = padded_vocab(
                vocab, self.shard_mesh.shape[self.shard_axis]
            )
        wide_table = self.param(
            "wide", nn.initializers.zeros, (vocab, 1)
        )
        deep_table = self.param(
            "deep",
            nn.initializers.normal(stddev=0.01),
            (vocab, self.deep_dim),
        )
        if self.shard_mesh is not None:
            from elasticdl_tpu.parallel.sharded_embedding import (
                sharded_embedding_lookup,
            )

            linear = sharded_embedding_lookup(
                wide_table, ids, self.shard_mesh, self.shard_axis
            )[..., 0]
            field_embs = sharded_embedding_lookup(
                deep_table, ids, self.shard_mesh, self.shard_axis
            )
        else:
            linear = jnp.take(wide_table, ids, axis=0)[..., 0]  # [B, F]
            field_embs = jnp.take(deep_table, ids, axis=0)  # [B, F, D]
        dense_logit = nn.Dense(1, use_bias=False, name="dense_linear")(
            dense
        )  # [B, 1]
        linear_logits = jnp.concatenate([linear, dense_logit], axis=1)
        return linear_logits, field_embs, dense


def deepfm_head(linear_logits, field_embs, dense, dnn_hidden_units=(16, 4)):
    """The DeepFM output assembly shared by the device-resident and
    PS-resident variants: first-order logits + FM second-order term +
    DNN over [dense, flattened field embeddings]. Call inside the
    owning module's @nn.compact so the Dense/DNN params keep their
    scope names."""
    fm = fm_interaction(field_embs)
    dnn_input = jnp.concatenate(
        [dense, field_embs.reshape(field_embs.shape[0], -1)], axis=1
    )
    dnn_logit = nn.Dense(1, use_bias=False)(
        DNN(dnn_hidden_units)(dnn_input)
    )
    return jnp.sum(linear_logits, axis=1) + fm + dnn_logit.reshape(-1)


def fm_interaction(field_embs):
    """Second-order FM term via the (sum^2 - sum of squares)/2 identity:
    [B, F, D] -> [B]."""
    sum_sq = jnp.square(jnp.sum(field_embs, axis=1))
    sq_sum = jnp.sum(jnp.square(field_embs), axis=1)
    return 0.5 * jnp.sum(sum_sq - sq_sum, axis=1)


def ctr_loss(labels, logits):
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(
            logits.reshape(-1), labels.reshape(-1).astype(jnp.float32)
        )
    )


class _LogitAUC(AUCMetric):
    """AUCMetric over fixed [0,1] thresholds, fed raw logits: squash first."""

    def update(self, outputs, labels):
        probs = 1.0 / (1.0 + np.exp(-np.asarray(outputs, np.float64)))
        super().update(probs, labels)


def ctr_metrics():
    return {
        "auc": _LogitAUC(),
        "accuracy": MeanMetric(
            lambda outputs, labels: (
                (np.asarray(outputs).reshape(-1) > 0)
                == np.asarray(labels).reshape(-1).astype(bool)
            ).astype(np.float64)
        ),
    }

