"""Criteo-shaped feature schema: 13 integer features + 26 categorical.

Reference counterpart: /root/reference/model_zoo/dac_ctr/feature_config.py
(the reference ships means/stddevs/boundaries measured on the real Criteo
DAC dump). This environment is air-gapped, so the data is synthetic
(data/gen/criteo.py) and the statistics below describe THAT generator —
same schema, our own numbers. Shapes kept: heavy-tailed counts for the
I-features, categorical cardinalities spanning 3..10M with a 1M hashing
cap (reference MAX_HASHING_BUCKET_SIZE).
"""

import numpy as np

NUM_DENSE = 13
NUM_CATEGORICAL = 26

DENSE_FEATURES = [f"I{i}" for i in range(1, NUM_DENSE + 1)]
CATEGORICAL_FEATURES = [f"C{i}" for i in range(1, NUM_CATEGORICAL + 1)]
FEATURE_NAMES = DENSE_FEATURES + CATEGORICAL_FEATURES
LABEL_KEY = "label"

# The synthetic generator draws I_k ~ round(lognormal(mu_k, sigma_k)) - 1
# (so -1 "missing" occurs); these are the exact normalization constants for
# that family, playing the role of the reference's measured FEATURES_AVGS /
# FEATURES_STDDEVS.
DENSE_LOG_MU = np.linspace(0.0, 6.0, NUM_DENSE)
DENSE_LOG_SIGMA = np.full(NUM_DENSE, 1.25)
DENSE_MEAN = np.exp(DENSE_LOG_MU + DENSE_LOG_SIGMA**2 / 2) - 1.0
DENSE_STD = np.sqrt(
    (np.exp(DENSE_LOG_SIGMA**2) - 1.0)
    * np.exp(2 * DENSE_LOG_MU + DENSE_LOG_SIGMA**2)
)

# Bucket boundaries: a geometric ladder per feature, covering its lognormal
# mass (counterpart of the reference's hand-measured FEATURE_BOUNDARIES).
DENSE_BOUNDARIES = [
    list(
        np.unique(
            np.round(
                np.exp(mu + sigma * np.array([-1.0, -0.5, 0.0, 0.5, 1.0, 1.5, 2.0]))
            )
        )
    )
    for mu, sigma in zip(DENSE_LOG_MU, DENSE_LOG_SIGMA)
]

# Categorical cardinalities: same magnitude spread as real Criteo (a few
# huge id spaces, many small ones), our own values.
CATEGORICAL_CARDINALITY = {
    "C1": 1400,
    "C2": 550,
    "C3": 9_500_000,
    "C4": 2_100_000,
    "C5": 300,
    "C6": 24,
    "C7": 12_000,
    "C8": 620,
    "C9": 3,
    "C10": 90_000,
    "C11": 5_500,
    "C12": 7_800_000,
    "C13": 3_200,
    "C14": 27,
    "C15": 15_000,
    "C16": 5_000_000,
    "C17": 10,
    "C18": 5_600,
    "C19": 2_200,
    "C20": 4,
    "C21": 6_500_000,
    "C22": 18,
    "C23": 15,
    "C24": 270_000,
    "C25": 100,
    "C26": 140_000,
}

MAX_HASHING_BUCKET_SIZE = 1_000_000


def hash_bins(feature: str) -> int:
    return min(CATEGORICAL_CARDINALITY[feature], MAX_HASHING_BUCKET_SIZE)


# Feature groups: like the reference's default FEATURE_GROUPS, every feature
# is its own group/field (I4 has no boundaries in the reference and is
# dropped from the id path there; we keep all 13).
FEATURE_GROUPS = [[name] for name in DENSE_FEATURES + CATEGORICAL_FEATURES]
