"""Criteo DeepFM variant — the BASELINE.json sparse north-star config.

Reference counterpart: /root/reference/model_zoo/dac_ctr/
deepfm_model.py:20-109 (linear + FM over field embeddings + DNN). The FM
second-order term uses the (sum^2 - sum-of-squares)/2 identity — one fused
elementwise expression under XLA.
"""

import jax.numpy as jnp
import flax.linen as nn

from elasticdl_tpu.models.dac_ctr.common import (
    CTREmbeddings,
    DNN,
    ctr_loss,
    ctr_metrics,
    fm_interaction,
)
from elasticdl_tpu.models.dac_ctr.transform import feed  # noqa: F401
from elasticdl_tpu.ops import optimizers


class DeepFMCriteo(nn.Module):
    deep_dim: int = 8
    dnn_hidden_units: tuple = (16, 4)

    @nn.compact
    def __call__(self, features, training: bool = False):
        linear_logits, field_embs, dense = CTREmbeddings(
            deep_dim=self.deep_dim
        )(features)
        fm = fm_interaction(field_embs)  # [B]
        dnn_input = jnp.concatenate(
            [dense, field_embs.reshape(field_embs.shape[0], -1)], axis=1
        )
        dnn_logit = nn.Dense(1, use_bias=False)(
            DNN(self.dnn_hidden_units)(dnn_input)
        )
        return (
            jnp.sum(linear_logits, axis=1) + fm + dnn_logit.reshape(-1)
        )


def custom_model():
    return DeepFMCriteo()


loss = ctr_loss


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def eval_metrics_fn():
    return ctr_metrics()
