"""Criteo DeepFM variant — the BASELINE.json sparse north-star config.

Reference counterpart: /root/reference/model_zoo/dac_ctr/
deepfm_model.py:20-109 (linear + FM over field embeddings + DNN). The FM
second-order term uses the (sum^2 - sum-of-squares)/2 identity — one fused
elementwise expression under XLA.
"""

import flax.linen as nn

from elasticdl_tpu.models.dac_ctr.common import (
    CTREmbeddings,
    ctr_loss,
    ctr_metrics,
    deepfm_head,
)
from elasticdl_tpu.models.dac_ctr.transform import feed  # noqa: F401
from elasticdl_tpu.ops import optimizers


class DeepFMCriteo(nn.Module):
    deep_dim: int = 8
    dnn_hidden_units: tuple = (16, 4)
    vocab: int = None  # default: the full Criteo offset id space
    shard_mesh: object = None  # device-shard the tables over this mesh
    shard_axis: str = "data"

    @nn.compact
    def __call__(self, features, training: bool = False):
        from elasticdl_tpu.models.dac_ctr.transform import TOTAL_IDS

        linear_logits, field_embs, dense = CTREmbeddings(
            deep_dim=self.deep_dim,
            vocab=self.vocab or TOTAL_IDS,
            shard_mesh=self.shard_mesh,
            shard_axis=self.shard_axis,
        )(features)
        return deepfm_head(
            linear_logits, field_embs, dense, self.dnn_hidden_units
        )


def custom_model():
    return DeepFMCriteo()


def custom_sharded_model(mesh, axis="data", vocab=None):
    """DeepFM with DEVICE-SHARDED embedding tables: rows across the mesh,
    lookups by on-chip collectives (parallel/sharded_embedding.py) — how
    this framework beats the reference's embedding_service when the
    tables fit the slice's aggregate HBM instead of re-hosting them."""
    return DeepFMCriteo(shard_mesh=mesh, shard_axis=axis, vocab=vocab)


def sharded_param_specs(params, axis="data"):
    """PartitionSpecs for custom_sharded_model: the two tables row-sharded
    over `axis`, everything else replicated (feed through NamedSharding
    for jit in_shardings)."""
    import jax
    from jax.sharding import PartitionSpec as P

    def spec(path, _):
        names = [str(getattr(k, "key", k)) for k in path]
        if names[-1] in ("wide", "deep"):
            return P(axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


loss = ctr_loss


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def eval_metrics_fn():
    return ctr_metrics()
