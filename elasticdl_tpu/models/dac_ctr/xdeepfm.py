"""Criteo xDeepFM variant (CIN + DNN + linear).

Reference counterpart: /root/reference/model_zoo/dac_ctr/xdeepfm_model.py.
The Compressed Interaction Network computes, per layer,
X^{k+1}[b,h,d] = sum_{i,j} W^k[h,i,j] X^k[b,i,d] X^0[b,j,d] — expressed
here as one einsum per layer so XLA maps it onto the MXU instead of the
reference's conv1d-over-outer-product trick.
"""

import jax.numpy as jnp
import flax.linen as nn

from elasticdl_tpu.models.dac_ctr.common import (
    CTREmbeddings,
    DNN,
    ctr_loss,
    ctr_metrics,
)
from elasticdl_tpu.models.dac_ctr.transform import feed  # noqa: F401
from elasticdl_tpu.ops import optimizers


class CIN(nn.Module):
    layer_sizes: tuple = (16, 16)

    @nn.compact
    def __call__(self, x0):
        # x0: [B, F, D] field embeddings.
        xk = x0
        pooled = []
        for li, h in enumerate(self.layer_sizes):
            w = self.param(
                f"w{li}",
                nn.initializers.normal(stddev=0.01),
                (h, xk.shape[1], x0.shape[1]),
            )
            xk = jnp.einsum("hij,bid,bjd->bhd", w, xk, x0)
            pooled.append(jnp.sum(xk, axis=2))  # [B, h]
        return jnp.concatenate(pooled, axis=1)


class XDeepFM(nn.Module):
    deep_dim: int = 8
    cin_layer_sizes: tuple = (16, 16)
    dnn_hidden_units: tuple = (16, 4)

    @nn.compact
    def __call__(self, features, training: bool = False):
        linear_logits, field_embs, dense = CTREmbeddings(
            deep_dim=self.deep_dim
        )(features)
        cin_out = CIN(self.cin_layer_sizes)(field_embs)
        dnn_out = DNN(self.dnn_hidden_units)(
            jnp.concatenate(
                [dense, field_embs.reshape(field_embs.shape[0], -1)],
                axis=1,
            )
        )
        head = jnp.concatenate([cin_out, dnn_out], axis=1)
        logit = nn.Dense(1, use_bias=False)(head).reshape(-1)
        return jnp.sum(linear_logits, axis=1) + logit


def custom_model():
    return XDeepFM()


loss = ctr_loss


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def eval_metrics_fn():
    return ctr_metrics()
