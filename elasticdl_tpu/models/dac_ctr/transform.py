"""Host-side Criteo feature transform: raw records -> device-ready arrays.

Reference counterpart: /root/reference/model_zoo/dac_ctr/
feature_transform.py:36-118 (Normalizer on the 13 I-features; Discretization
on I + Hashing on C, offset-concatenated into per-group id tensors).
TPU-first difference: the reference keeps 39 separate Keras embedding
lookups; here ALL groups share one offset id space so the model does a
single [B, F] gather into one table — one HBM-friendly take instead of 39
small ones.

The transform runs in `feed` on the host (numpy); the device only ever sees
{"dense": [B,13] float32, "ids": [B,F] int32}.
"""

import numpy as np

from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.models.dac_ctr import feature_config as fc
from elasticdl_tpu.preprocessing.layers import (
    Discretization,
    Hashing,
    Normalizer,
)

_normalizers = {
    name: Normalizer(subtractor=fc.DENSE_MEAN[i], divisor=fc.DENSE_STD[i])
    for i, name in enumerate(fc.DENSE_FEATURES)
}
_bucketizers = {
    name: Discretization(fc.DENSE_BOUNDARIES[i])
    for i, name in enumerate(fc.DENSE_FEATURES)
}
_hashers = {
    name: Hashing(fc.hash_bins(name)) for name in fc.CATEGORICAL_FEATURES
}


def _id_space_sizes():
    sizes = []
    for name in fc.DENSE_FEATURES:
        sizes.append(len(_bucketizers[name].bins) + 1)
    for name in fc.CATEGORICAL_FEATURES:
        sizes.append(fc.hash_bins(name))
    return np.asarray(sizes, dtype=np.int64)


ID_SPACE_SIZES = _id_space_sizes()
ID_OFFSETS = np.concatenate([[0], np.cumsum(ID_SPACE_SIZES)[:-1]])
TOTAL_IDS = int(ID_SPACE_SIZES.sum())
NUM_FIELDS = len(ID_SPACE_SIZES)  # 39


def transform_batch(features_by_name):
    """dict name->[B] raw arrays  ->  {"dense": [B,13] f32, "ids": [B,F] i32}
    with ids already offset into the shared vocabulary."""
    some = next(iter(features_by_name.values()))
    batch = np.asarray(some).shape[0]

    dense = np.empty((batch, fc.NUM_DENSE), np.float32)
    ids = np.empty((batch, NUM_FIELDS), np.int64)
    col = 0
    for i, name in enumerate(fc.DENSE_FEATURES):
        raw = np.asarray(features_by_name[name], np.float32).reshape(batch)
        dense[:, i] = _normalizers[name](np.maximum(raw, 0.0))
        ids[:, col] = _bucketizers[name](raw) + ID_OFFSETS[col]
        col += 1
    for name in fc.CATEGORICAL_FEATURES:
        raw = np.asarray(features_by_name[name]).reshape(batch)
        ids[:, col] = _hashers[name](raw) + ID_OFFSETS[col]
        col += 1
    return {"dense": dense, "ids": ids.astype(np.int32)}


def feed(records, mode, metadata):
    """The zoo-contract feed shared by every dac_ctr variant."""
    from elasticdl_tpu.common.model_utils import Modes

    batch = batch_examples(records)
    labels = (
        batch.pop(fc.LABEL_KEY).astype(np.int64).reshape(-1)
        if fc.LABEL_KEY in batch
        else None
    )
    features = transform_batch(batch)
    if mode == Modes.PREDICTION:
        return features, None
    return features, labels
