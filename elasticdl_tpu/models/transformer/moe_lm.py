"""Switch-MoE decoder LM: the flagship family's expert-parallel variant.

No reference counterpart (the reference zoo is CNNs/recsys; SURVEY.md §2.10
records no EP upstream). Every `moe_every`-th Block swaps its dense FFN for
a SwitchMoE layer (layers/moe.py: top-1 routing, fixed capacity, one-hot
einsum dispatch so shapes stay static under jit).

Output contract: training=True returns {"logits", "aux_loss"} — aux_loss is
the Switch load-balancing term ALREADY scaled by the config's
aux_loss_weight, so the spec `loss` just adds it; training=False returns
plain logits, keeping the evaluation/prediction wire paths (chunked metric
folds, output processors) identical to the dense LM's. `param_specs` shards
expert weights over the "model" mesh axis (the worker's
--model_parallel_size axis doubles as the expert axis), composing EP with
DP in the elastic AllReduce trainer.

Padding caveat: on a padded final partial minibatch the trainer slices
batch-dim outputs back to real rows before the CE, but the (scalar)
aux_loss was computed over the padded batch — padding rows are cyclic
repeats, so the regularizer is marginally reweighted there, matching the
multi-host ragged-batch semantics the AllReduce trainer documents.
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from elasticdl_tpu.layers.moe import SwitchMoE, moe_param_specs
from elasticdl_tpu.models.transformer import transformer_lm as tlm
from elasticdl_tpu.models.transformer.transformer_lm import (
    MultiHeadAttention,
    embed_input,
    head_output,
)


@dataclasses.dataclass(frozen=True)
class MoELMConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    max_len: int = 256
    num_experts: int = 4
    moe_every: int = 2  # every k-th block is an expert block
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    dropout: float = 0.0
    attention: Optional[object] = None
    activation_dtype: str = "bfloat16"
    remat: bool = False
    remat_policy: Optional[str] = None

    def __post_init__(self):
        tlm.validate_remat_policy(self.remat, self.remat_policy)
        if self.moe_every < 1:
            raise ValueError(
                f"moe_every must be >= 1, got {self.moe_every} (use the "
                f"dense transformer_lm for a model with no expert blocks)"
            )
        if self.num_experts < 2:
            raise ValueError(
                f"num_experts must be >= 2, got {self.num_experts}"
            )


class MoEBlock(nn.Module):
    """Transformer block whose FFN is a routed expert layer; returns
    (x, aux_loss)."""

    config: MoELMConfig

    @nn.compact
    def __call__(self, x, training=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.activation_dtype)
        h = nn.LayerNorm(dtype=dtype)(x)
        x = x + MultiHeadAttention(cfg)(h, training)
        h = nn.LayerNorm(dtype=dtype)(x)
        out, aux = SwitchMoE(
            num_experts=cfg.num_experts,
            d_hidden=4 * cfg.d_model,
            capacity_factor=cfg.capacity_factor,
            dtype=cfg.activation_dtype,
        )(h)
        if cfg.dropout:
            # Same regularization as the dense Block's FFN output.
            out = nn.Dropout(
                cfg.dropout, deterministic=not training
            )(out)
        return x + out, aux


class MoETransformerLM(nn.Module):
    config: MoELMConfig = MoELMConfig()

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        cfg = self.config
        x = embed_input(cfg, tokens)
        block_cls, moe_cls = tlm.Block, MoEBlock
        if cfg.remat:
            kwargs = {"static_argnums": (2,)}
            if cfg.remat_policy:
                import jax

                kwargs["policy"] = getattr(
                    jax.checkpoint_policies, cfg.remat_policy
                )
            block_cls = nn.remat(tlm.Block, **kwargs)
            moe_cls = nn.remat(MoEBlock, **kwargs)
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layers):
            if (i + 1) % cfg.moe_every == 0:
                x, aux = moe_cls(cfg)(x, training)
                aux_total = aux_total + aux
            else:
                x = block_cls(cfg)(x, training)
        logits = head_output(cfg, x)
        if not training:
            return logits
        return {
            # Pre-scaled by the INSTANCE config so sweeping
            # aux_loss_weight actually takes effect in the spec loss.
            "logits": logits,
            "aux_loss": cfg.aux_loss_weight * aux_total,
        }


# ---------- model spec contract ----------


def custom_model(config: MoELMConfig = None):
    return MoETransformerLM(config or MoELMConfig())


def loss(labels, outputs):
    """Next-token CE + Switch load-balancing aux (the model pre-scales the
    aux term by its instance config's aux_loss_weight)."""
    return tlm.loss(labels, outputs["logits"]) + outputs["aux_loss"]


def optimizer():
    return tlm.optimizer()


def feed(records, mode, metadata):
    return tlm.feed(records, mode, metadata)


def param_specs(variables):
    """DP x EP layout for the elastic trainer: expert weight tensors shard
    over the "model" mesh axis (one axis serves TP in the dense LM and EP
    here), router + dense blocks + embeddings replicated."""
    # moe_param_specs walks the whole tree: w_in/w_out tensors shard over
    # the axis, every other leaf (router, dense blocks, embeddings, head)
    # replicates — exactly the DP x EP layout.
    return {
        k: moe_param_specs(v, expert_axis="model")
        for k, v in variables.items()
    }


def eval_metrics_fn():
    # Evaluation sees plain logits (training=False output), so the dense
    # LM's metrics apply unchanged.
    return tlm.eval_metrics_fn()
