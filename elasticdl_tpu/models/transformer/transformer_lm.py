"""Decoder-only transformer LM — the long-context flagship model.

No reference counterpart (the reference zoo is CNNs/recsys; long-context is
this framework's extension). TPU-first choices: bfloat16 activations with
float32 params/softmax, flash attention (ops/flash_attention.py) on the
local path, and a pluggable attention callable so the DP+SP training step
can drop in ring attention or Ulysses (parallel/ring_attention.py,
parallel/ulysses.py) over a ("data", "seq") mesh — see
__graft_entry__.dryrun_multichip for the sharded wiring.

Model spec contract (common/model_utils.py): custom_model / loss /
optimizer / feed / eval_metrics_fn.
"""

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import MeanMetric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.ops import optimizers
from elasticdl_tpu.ops.flash_attention import flash_attention

VOCAB = 256
D_MODEL = 128
N_HEADS = 4
N_LAYERS = 2
MAX_LEN = 256


@dataclasses.dataclass(frozen=True)
class LMConfig:
    vocab: int = VOCAB
    d_model: int = D_MODEL
    n_heads: int = N_HEADS
    n_layers: int = N_LAYERS
    max_len: int = MAX_LEN
    dropout: float = 0.0
    # attention(q, k, v) with causal masking baked in; None -> local flash.
    attention: Optional[Callable] = None
    # bfloat16 activations keep the MXU in its native dtype.
    activation_dtype: str = "bfloat16"
    # Rematerialize each block in the backward pass: trades ~1/3 more FLOPs
    # for O(layers) instead of O(layers x activations) memory — the standard
    # long-context recipe (jax.checkpoint).
    remat: bool = False
    # Name of a jax.checkpoint_policies policy refining WHAT remat saves
    # (None = recompute everything). "dots_with_no_batch_dims_saveable"
    # keeps matmul outputs resident so the backward pass skips re-running
    # the MXU-heavy projections — spends HBM to win step time when the
    # activations still fit.
    remat_policy: Optional[str] = None

    def __post_init__(self):
        validate_remat_policy(self.remat, self.remat_policy)


def validate_remat_policy(remat, remat_policy):
    """Config-time validation shared by the dense and MoE LM configs."""
    if remat_policy is None:
        return
    if not remat:
        raise ValueError(
            "remat_policy is set but remat=False — the policy would be "
            "silently ignored; enable remat or drop the policy"
        )
    if not hasattr(jax.checkpoint_policies, remat_policy):
        raise ValueError(
            f"unknown remat_policy {remat_policy!r} (see "
            f"jax.checkpoint_policies)"
        )


def flagship_config(max_len: int = 4096) -> "LMConfig":
    """The >=100M-param long-context config validated on a real chip
    (tools/validate_flagship.py): 151M transformer params + 34M embeddings,
    head_dim 128 (the fast Pallas flash-attention tile).

    remat is OFF by default: the round-4 sweep on one TPU v5e (16 GB)
    measured the full activation set fitting at batch 4/S=4096 AND batch
    2/S=8192, with remat=False beating the best remat policy by ~14%
    tokens/sec at both lengths (60.1k -> 68.7k @4096; 47.8k -> 54.9k
    @8192) — recompute was pure FLOP overhead, not a memory necessity, at
    single-chip flagship scale. Re-enable remat (policy
    "dots_with_no_batch_dims_saveable" measured best) for bigger batches,
    longer contexts, or shared-HBM multi-model settings where activations
    stop fitting."""
    return LMConfig(
        vocab=32768,
        d_model=1024,
        n_heads=8,
        n_layers=12,
        max_len=max_len,
        remat=False,
    )


def _default_attention(q, k, v):
    return flash_attention(q, k, v, True)


class MultiHeadAttention(nn.Module):
    config: LMConfig

    @nn.compact
    def __call__(self, x, training=False):
        cfg = self.config
        head_dim = cfg.d_model // cfg.n_heads
        dtype = jnp.dtype(cfg.activation_dtype)
        qkv = nn.DenseGeneral(
            (3, cfg.n_heads, head_dim), dtype=dtype, name="qkv"
        )(x)
        # [B, S, 3, H, Dh] -> three [B, H, S, Dh]
        q, k, v = jnp.moveaxis(qkv, 2, 0)
        q = jnp.swapaxes(q, 1, 2)
        k = jnp.swapaxes(k, 1, 2)
        v = jnp.swapaxes(v, 1, 2)
        attend = cfg.attention or _default_attention
        # Softmax path in float32 for stability; back to compute dtype.
        out = attend(
            q.astype(jnp.float32),
            k.astype(jnp.float32),
            v.astype(jnp.float32),
        ).astype(dtype)
        out = jnp.swapaxes(out, 1, 2).reshape(*x.shape[:2], cfg.d_model)
        return nn.Dense(cfg.d_model, dtype=dtype, name="proj")(out)


class Block(nn.Module):
    config: LMConfig

    @nn.compact
    def __call__(self, x, training=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.activation_dtype)
        h = nn.LayerNorm(dtype=dtype)(x)
        x = x + MultiHeadAttention(cfg)(h, training)
        h = nn.LayerNorm(dtype=dtype)(x)
        h = nn.Dense(4 * cfg.d_model, dtype=dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, dtype=dtype)(h)
        if cfg.dropout:
            h = nn.Dropout(cfg.dropout, deterministic=not training)(h)
        return x + h


def embed_input(cfg, tokens):
    """Token + positional embedding. A plain function (not a submodule) so
    both TransformerLM and the pipelined build (parallel/pipeline.py) share
    one implementation without changing either's param tree — flax registers
    the named submodules on whichever module's compact scope is active."""
    dtype = jnp.dtype(cfg.activation_dtype)
    s = tokens.shape[1]
    if s > cfg.max_len:
        # Without this, the positional gather would silently clamp
        # out-of-range indices under XLA and corrupt positions.
        raise ValueError(
            f"sequence length {s} exceeds max_len {cfg.max_len}"
        )
    x = nn.Embed(cfg.vocab, cfg.d_model, dtype=dtype, name="tok_emb")(
        tokens.astype(jnp.int32)
    )
    pos = nn.Embed(cfg.max_len, cfg.d_model, dtype=dtype,
                   name="pos_emb")(jnp.arange(s))
    return x + pos[None]


def head_output(cfg, x):
    """Final LayerNorm + LM head; shared with the pipelined build (see
    embed_input). Logits in float32: softmax/CE stay out of bfloat16."""
    x = nn.LayerNorm(dtype=jnp.dtype(cfg.activation_dtype))(x)
    return nn.Dense(cfg.vocab, dtype=jnp.float32, name="lm_head")(x)


class TransformerLM(nn.Module):
    config: LMConfig = LMConfig()

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        cfg = self.config
        x = embed_input(cfg, tokens)
        if cfg.remat:
            kwargs = {"static_argnums": (2,)}
            if cfg.remat_policy:
                kwargs["policy"] = getattr(
                    jax.checkpoint_policies, cfg.remat_policy
                )
            block_cls = nn.remat(Block, **kwargs)
        else:
            block_cls = Block
        for _ in range(cfg.n_layers):
            x = block_cls(cfg)(x, training)
        return head_output(cfg, x)


# ---------- model spec contract ----------


def custom_model(config: LMConfig = None):
    return TransformerLM(config or LMConfig())


def loss(labels, logits):
    """Next-token CE; labels [B, S] int, logits [B, S, V]."""
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(
            logits, labels.astype(jnp.int32)
        )
    )


def optimizer():
    return optimizers.adam(learning_rate=3e-4)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    tokens = batch["tokens"].astype(np.int32)  # [B, S+1]
    features = tokens[:, :-1]
    labels = tokens[:, 1:] if mode != Modes.PREDICTION else None
    return features, labels


def param_specs(variables):
    """Model-spec hook for hybrid DP x TP (worker --model_parallel_size):
    Megatron-style PartitionSpecs over the "model" mesh axis for the param
    collection, everything else (batch stats etc.) replicated."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel.tensor_parallel import (
        transformer_param_specs,
    )

    return {
        k: (
            transformer_param_specs(v)
            if k == "params"
            else jax.tree_util.tree_map(lambda _: P(), v)
        )
        for k, v in variables.items()
    }


def context_parallel_model(mesh, axis_name="seq", batch_axis="data",
                           head_axis=None, impl="zigzag", config=None):
    """Model-spec hook for sequence/context parallelism (worker
    --context_parallel_size): rebuild the LM with its attention bound to
    `mesh`'s sequence axis — zigzag ring (balanced causal ring,
    parallel/ring_attention.py), plain ring, or Ulysses all-to-all
    (parallel/ulysses.py). The attention callable is parameterless, so
    the param tree is IDENTICAL to the plain LM's: elastic transitions
    between SP worlds and pure-DP worlds carry (params, opt_state)
    untouched, and checkpoints are interchangeable. head_axis names a
    tensor-parallel mesh axis to also shard heads over (ring only) for
    the 3-D DP x TP x SP composition."""
    cfg = config or LMConfig()
    if impl == "zigzag":
        from elasticdl_tpu.parallel.ring_attention import (
            make_zigzag_ring_attention,
        )

        attn = make_zigzag_ring_attention(
            mesh, axis_name=axis_name, causal=True,
            batch_axis=batch_axis, head_axis=head_axis,
        )
    elif impl == "ring":
        from elasticdl_tpu.parallel.ring_attention import (
            make_ring_attention,
        )

        attn = make_ring_attention(
            mesh, axis_name=axis_name, causal=True,
            batch_axis=batch_axis, head_axis=head_axis,
        )
    elif impl == "ulysses":
        if head_axis is not None:
            raise ValueError(
                "ulysses re-shards heads itself (all-to-all) and cannot "
                "also shard them over a tensor-parallel axis; use "
                "impl='zigzag' for the 3-D composition"
            )
        from elasticdl_tpu.parallel.ulysses import make_ulysses_attention

        attn = make_ulysses_attention(
            mesh, axis_name=axis_name, causal=True, batch_axis=batch_axis
        )
    else:
        raise ValueError(f"unknown context-parallel impl {impl!r}")
    return custom_model(dataclasses.replace(cfg, attention=attn))


def pipeline_spec(mesh, n_stages, num_microbatches, schedule="1f1b",
                  batch_axis=None, virtual_stages=2, config=None):
    """Model-spec stage hook for pipeline parallelism (worker
    --pipeline_stages N --pipeline_schedule {gpipe,1f1b,interleaved}), the
    staged twin of the param_specs hook: returns a
    parallel.pipeline.PipelineBuild binding this LM's Block stack to the
    requested schedule on `mesh`'s "stage" axis. All three schedules share
    one param tree ({embed, stages[rows], head}), so checkpoints and
    optimizer state transfer between them, and the schedule-free apply_fn
    (make_lm_sequential) evaluates/predicts on any mesh."""
    from elasticdl_tpu.parallel import pipeline as plib

    cfg = config or LMConfig()
    total_rows = n_stages
    if schedule == "interleaved":
        from elasticdl_tpu.parallel.pipeline_interleaved import (
            make_lm_pipeline_interleaved,
        )

        total_rows = n_stages * virtual_stages
        init_fn, lg_fn = make_lm_pipeline_interleaved(
            cfg, mesh, n_stages, virtual_stages, num_microbatches,
            batch_axis=batch_axis,
        )
    elif schedule == "1f1b":
        init_fn, lg_fn = plib.make_lm_pipeline_1f1b(
            cfg, mesh, n_stages, num_microbatches, batch_axis=batch_axis
        )
    elif schedule == "gpipe":
        init_fn, train_apply = plib.make_lm_pipeline(
            cfg, mesh, n_stages, num_microbatches, batch_axis=batch_axis
        )

        def lg_fn(params, tokens, labels, rng=None):
            def loss_of(p):
                rngs = {"dropout": rng} if rng is not None else None
                return loss(
                    labels, train_apply(p, tokens, training=True, rngs=rngs)
                )

            return jax.value_and_grad(loss_of)(params)

    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    apply_fn = plib.make_lm_sequential(cfg, total_rows)

    def param_specs_fn(params):
        return plib.lm_pipeline_param_specs(params)

    return plib.PipelineBuild(init_fn, lg_fn, apply_fn, param_specs_fn)


def token_ce(outputs, labels):
    """Per-token CE from logits (numpy; eval-metric building block, also
    reused by the MoE variant on its logits field)."""
    logits = np.asarray(outputs, np.float32)
    labels = np.asarray(labels).astype(np.int64)
    logits = logits - logits.max(-1, keepdims=True)
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    return -np.take_along_axis(logp, labels[..., None], -1)


def eval_metrics_fn():
    return {"token_ce": MeanMetric(token_ce)}
