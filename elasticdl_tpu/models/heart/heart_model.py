"""Heart-disease binary classifier (reference /root/reference/model_zoo/
heart_functional_api/ — mixed numeric + categorical columns through
normalizer/bucketize/hash transforms into a small MLP)."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import MeanMetric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples, encode_example
from elasticdl_tpu.ops import optimizers
from elasticdl_tpu.preprocessing.layers import (
    Discretization,
    Hashing,
    Normalizer,
)

NUMERIC = ["age", "trestbps", "chol", "thalach", "oldpeak"]
_norms = {
    "age": Normalizer(54.0, 9.0),
    "trestbps": Normalizer(131.0, 17.0),
    "chol": Normalizer(246.0, 51.0),
    "thalach": Normalizer(149.0, 22.0),
    "oldpeak": Normalizer(1.0, 1.1),
}
_thal_hash = Hashing(8)
_age_bucket = Discretization([40, 50, 60])
THAL_BINS = 8
AGE_BINS = 4


class HeartModel(nn.Module):
    @nn.compact
    def __call__(self, features, training: bool = False):
        numeric = features["numeric"]  # [B, 5] normalized
        thal = nn.Embed(THAL_BINS, 4)(features["thal_id"].astype(jnp.int32))
        age = nn.Embed(AGE_BINS, 4)(features["age_bucket"].astype(jnp.int32))
        x = jnp.concatenate([numeric, thal, age], axis=-1)
        x = nn.relu(nn.Dense(32)(x))
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(1)(x).reshape(-1)


def custom_model():
    return HeartModel()


def loss(labels, logits):
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(
            logits.reshape(-1), labels.reshape(-1).astype(jnp.float32)
        )
    )


def optimizer(lr=0.01):
    return optimizers.adam(learning_rate=lr)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    numeric = np.stack(
        [_norms[name](batch[name].astype(np.float32)) for name in NUMERIC],
        axis=1,
    )
    features = {
        "numeric": numeric.astype(np.float32),
        "thal_id": _thal_hash(batch["thal"].astype(np.int64)),
        "age_bucket": _age_bucket(batch["age"].astype(np.float32)),
    }
    labels = (
        batch["label"].astype(np.float32)
        if mode != Modes.PREDICTION
        else None
    )
    return features, labels


def eval_metrics_fn():
    def correct(outputs, labels):
        preds = (np.asarray(outputs).reshape(-1) > 0).astype(np.float32)
        return (preds == np.asarray(labels).reshape(-1)).astype(np.float32)

    return {"accuracy": MeanMetric(correct)}


def make_records(n, seed=0):
    rng = np.random.default_rng(seed)
    records = []
    for _ in range(n):
        age = rng.uniform(29, 77)
        chol = rng.uniform(150, 400)
        thalach = rng.uniform(90, 200)
        label = int(0.03 * age + 0.004 * chol - 0.02 * thalach > 0)
        records.append(
            encode_example(
                {
                    "age": np.float32(age),
                    "trestbps": np.float32(rng.uniform(100, 170)),
                    "chol": np.float32(chol),
                    "thalach": np.float32(thalach),
                    "oldpeak": np.float32(rng.uniform(0, 4)),
                    "thal": np.int64(rng.integers(0, 30)),
                    "label": np.int64(label),
                }
            )
        )
    return records
