"""Census DNN zoo model (reference /root/reference/model_zoo/
census_dnn_model/ — embeddings for categorical features + MLP)."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import MeanMetric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.models.census.wide_deep import (
    TOTAL_IDS,
    feed,  # noqa: F401  (same feature pipeline)
    make_records,  # noqa: F401
)
from elasticdl_tpu.ops import optimizers

EMB_DIM = 16


class CensusDNN(nn.Module):
    @nn.compact
    def __call__(self, features, training: bool = False):
        ids = features["ids"]
        table = self.param(
            "emb",
            nn.initializers.uniform(scale=0.05),
            (TOTAL_IDS, EMB_DIM),
        )
        x = jnp.take(table, ids.astype(jnp.int32), axis=0).reshape(
            ids.shape[0], -1
        )
        for width in (64, 32):
            x = nn.relu(nn.Dense(width)(x))
        return nn.Dense(1)(x).reshape(-1)


def custom_model():
    return CensusDNN()


def loss(labels, logits):
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(
            logits.reshape(-1), labels.reshape(-1).astype(jnp.float32)
        )
    )


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def eval_metrics_fn():
    def correct(outputs, labels):
        preds = (np.asarray(outputs).reshape(-1) > 0).astype(np.float32)
        return (preds == np.asarray(labels).reshape(-1)).astype(np.float32)

    return {"accuracy": MeanMetric(correct)}
