"""Census wide & deep zoo model.

Reference counterpart: /root/reference/model_zoo/census_wide_deep_model/
wide_deep_functional_api.py — categorical features hashed/bucketized into
id groups, a wide linear part (dim-1 embeddings summed) plus a deep part
(dim-8 embeddings -> MLP), summed into a sigmoid logit. The feature
transforms come from the preprocessing package (hashing/discretization),
applied host-side in `feed` so the device sees pure id/float arrays.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import MeanMetric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.ops import optimizers
from elasticdl_tpu.preprocessing.layers import Discretization, Hashing

# Feature config: 4 categorical (hashed) + 2 numeric (bucketized) features.
CATEGORICAL_BINS = {"workclass": 30, "education": 30, "occupation": 50,
                    "relationship": 20}
AGE_BOUNDARIES = [25, 35, 45, 55, 65]
HOURS_BOUNDARIES = [20, 35, 45]

_hashers = {name: Hashing(bins) for name, bins in CATEGORICAL_BINS.items()}
_age_bucket = Discretization(AGE_BOUNDARIES)
_hours_bucket = Discretization(HOURS_BOUNDARIES)

# Offsets concatenate all id spaces into one vocabulary for the shared
# wide/deep embedding tables (reference: ConcatenateWithOffset +
# Embedding(input_dim=total)).
_GROUPS = list(CATEGORICAL_BINS) + ["age_bucket", "hours_bucket"]
_SIZES = list(CATEGORICAL_BINS.values()) + [
    len(AGE_BOUNDARIES) + 1,
    len(HOURS_BOUNDARIES) + 1,
]
OFFSETS = np.concatenate([[0], np.cumsum(_SIZES)[:-1]])
TOTAL_IDS = int(np.sum(_SIZES))
DEEP_DIM = 8


class WideDeep(nn.Module):
    @nn.compact
    def __call__(self, features, training: bool = False):
        ids = features["ids"]  # [B, n_groups] offset ids
        wide_table = self.param(
            "wide", nn.initializers.zeros, (TOTAL_IDS, 1)
        )
        deep_table = self.param(
            "deep",
            nn.initializers.uniform(scale=0.05),
            (TOTAL_IDS, DEEP_DIM),
        )
        wide = jnp.sum(
            jnp.take(wide_table, ids.astype(jnp.int32), axis=0), axis=1
        )  # [B, 1]
        deep = jnp.take(
            deep_table, ids.astype(jnp.int32), axis=0
        ).reshape(ids.shape[0], -1)
        for width in (16, 16, 16):
            deep = nn.relu(nn.Dense(width)(deep))
        deep = nn.Dense(1)(deep)
        return (wide + deep).reshape(-1)


def custom_model():
    return WideDeep()


def loss(labels, logits):
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(
            logits.reshape(-1), labels.reshape(-1).astype(jnp.float32)
        )
    )


def optimizer(lr=0.01):
    return optimizers.adam(learning_rate=lr)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    cols = []
    for i, name in enumerate(CATEGORICAL_BINS):
        cols.append(_hashers[name](batch[name]) + OFFSETS[i])
    cols.append(
        _age_bucket(batch["age"]) + OFFSETS[len(CATEGORICAL_BINS)]
    )
    cols.append(
        _hours_bucket(batch["hours"]) + OFFSETS[len(CATEGORICAL_BINS) + 1]
    )
    ids = np.stack([np.asarray(c).reshape(-1) for c in cols], axis=1)
    labels = (
        batch["label"].astype(np.float32)
        if mode != Modes.PREDICTION
        else None
    )
    return {"ids": ids.astype(np.int64)}, labels


def eval_metrics_fn():
    def correct(outputs, labels):
        preds = (np.asarray(outputs).reshape(-1) > 0).astype(np.float32)
        return (preds == np.asarray(labels).reshape(-1)).astype(np.float32)

    return {"accuracy": MeanMetric(correct)}


def make_records(n, seed=0):
    """Synthetic census-like rows with a learnable relationship between
    the hashed groups and the label."""
    from elasticdl_tpu.data.example import encode_example

    rng = np.random.default_rng(seed)
    weights = rng.normal(size=TOTAL_IDS).astype(np.float32)
    records = []
    for _ in range(n):
        row = {
            name: np.int64(rng.integers(0, 1000))
            for name in CATEGORICAL_BINS
        }
        row["age"] = np.float32(rng.uniform(18, 80))
        row["hours"] = np.float32(rng.uniform(5, 60))
        feats, _ = feed_row(row)
        score = weights[feats].sum()
        row["label"] = np.int64(score > 0)
        records.append(encode_example(row))
    return records


def feed_row(row):
    cols = []
    for i, name in enumerate(CATEGORICAL_BINS):
        cols.append(
            int(_hashers[name](np.asarray([row[name]]))[0]) + OFFSETS[i]
        )
    cols.append(
        int(_age_bucket(np.asarray([row["age"]]))[0])
        + OFFSETS[len(CATEGORICAL_BINS)]
    )
    cols.append(
        int(_hours_bucket(np.asarray([row["hours"]]))[0])
        + OFFSETS[len(CATEGORICAL_BINS) + 1]
    )
    return np.asarray(cols, np.int64), None
