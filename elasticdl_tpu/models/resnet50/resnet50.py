"""ResNet-50 zoo model (the throughput-benchmark workhorse).

Reference counterparts: /root/reference/model_zoo/imagenet_resnet50/ and
resnet50_subclass/ (bottleneck-v1 architecture; the reference benchmarks
report img/s on it, BASELINE.md). TPU-first: NHWC, bfloat16 activations
with float32 BatchNorm statistics and float32 logits — the standard
TPU ResNet recipe, MXU-native convs.
"""

from functools import partial

import flax.linen as nn
import jax.numpy as jnp
import optax

from elasticdl_tpu.common.evaluation_utils import accuracy_metric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.ops import optimizers

NUM_CLASSES = 1000
STAGE_SIZES = [3, 4, 6, 3]  # ResNet-50


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x, training: bool = False):
        dtype = jnp.dtype(self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not training,
            momentum=0.9,
            epsilon=1e-5,
            dtype=jnp.float32,
        )
        conv = partial(nn.Conv, use_bias=False, dtype=dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y).astype(dtype)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 padding="SAME")(y)
        y = norm()(y).astype(dtype)
        y = nn.relu(y)
        y = conv(4 * self.filters, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y).astype(dtype)
        if residual.shape != y.shape:
            residual = conv(
                4 * self.filters,
                (1, 1),
                strides=(self.strides, self.strides),
                name="proj",
            )(residual)
            residual = norm(name="proj_bn")(residual).astype(dtype)
        return nn.relu(residual + y)


class ResNet50(nn.Module):
    num_classes: int = NUM_CLASSES
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x, training: bool = False):
        dtype = jnp.dtype(self.dtype)
        x = x.astype(dtype)
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not training,
            momentum=0.9,
            epsilon=1e-5,
            dtype=jnp.float32,
        )(x).astype(dtype)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, block_count in enumerate(STAGE_SIZES):
            for block in range(block_count):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    filters=64 * 2**stage,
                    strides=strides,
                    dtype=self.dtype,
                )(x, training)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def custom_model():
    return ResNet50()


def loss(labels, predictions):
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(
            predictions, labels.reshape(-1)
        )
    )


def optimizer(lr=0.1):
    return optimizers.momentum(learning_rate=lr, momentum_value=0.9)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    features = batch["image"].astype("float32")
    labels = batch["label"] if mode != Modes.PREDICTION else None
    return features, labels


def eval_metrics_fn():
    return {"accuracy": accuracy_metric()}
