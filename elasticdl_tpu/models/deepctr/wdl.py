"""WDL (wide & deep learning) declared deepctr-style via feature specs.

Reference counterpart: /root/reference/model_zoo/deepctr/wdl.py — the
deepctr-library zoo entry builds its model from SparseFeat/DenseFeat specs
(hash buckets over the 26 Criteo categoricals, 13 numeric features) and
lets the library assemble WDL. Here the same declarative shape uses
elasticdl_tpu.preprocessing.feature_column: hashed categorical ->
embedding columns for the deep tower, indicator-free wide tower as dim-1
embeddings, numeric columns log-normalized. Embedding tables are stock
nn.Embed, so the ModelHandler PS-swaps any of them that exceed the size
threshold under ParameterServerStrategy.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.evaluation_utils import AUCMetric
from elasticdl_tpu.common.model_utils import Modes
from elasticdl_tpu.data.example import batch_examples
from elasticdl_tpu.ops import optimizers
from elasticdl_tpu.preprocessing import feature_column as fc

NUM_DENSE = 13
NUM_SPARSE = 26
HASH_BUCKETS = 10000
EMB_DIM = 4

SPARSE_KEYS = [f"C{i}" for i in range(1, NUM_SPARSE + 1)]
DENSE_KEYS = [f"I{i}" for i in range(1, NUM_DENSE + 1)]


def _log_norm(x):
    return jnp.log1p(jnp.maximum(x, 0.0))


def build_columns():
    cats = {
        key: fc.categorical_column_with_hash_bucket(key, HASH_BUCKETS)
        for key in SPARSE_KEYS
    }
    deep = tuple(
        fc.embedding_column(cats[key], EMB_DIM) for key in SPARSE_KEYS
    ) + tuple(
        fc.numeric_column(key, normalizer_fn=_log_norm)
        for key in DENSE_KEYS
    )
    # Wide tower: dim-1 embeddings = a learned weight per hash bucket
    # (deepctr's linear feature columns).
    wide = tuple(
        fc.embedding_column(cats[key], 1) for key in SPARSE_KEYS
    ) + tuple(
        fc.numeric_column(key, normalizer_fn=_log_norm)
        for key in DENSE_KEYS
    )
    return wide, deep


class WDL(nn.Module):
    wide_columns: tuple
    deep_columns: tuple
    hidden_units: tuple = (128, 64)

    @nn.compact
    def __call__(self, features, training: bool = False):
        wide = fc.DenseFeatures(self.wide_columns, name="wide")(features)
        deep = fc.DenseFeatures(self.deep_columns, name="deep")(features)
        for width in self.hidden_units:
            deep = nn.relu(nn.Dense(width)(deep))
        logit = jnp.sum(wide, axis=-1) + nn.Dense(1)(deep).reshape(-1)
        return logit


_WIDE, _DEEP = build_columns()


def custom_model():
    return WDL(_WIDE, _DEEP)


def loss(labels, logits):
    return jnp.mean(
        optax.sigmoid_binary_cross_entropy(
            logits.reshape(-1), labels.reshape(-1).astype(jnp.float32)
        )
    )


def optimizer(lr=0.001):
    return optimizers.adam(learning_rate=lr)


def feed(records, mode, metadata):
    batch = batch_examples(records)
    # Raw integer Criteo ids hash in-graph (feature_column._jnp_int_hash);
    # preprocess only rewrites string-typed columns.
    features = {
        key: batch[key] for key in DENSE_KEYS + SPARSE_KEYS
    }
    features = fc.DenseFeatures(_WIDE + _DEEP).preprocess(features)
    labels = (
        batch["label"].astype(np.float32)
        if mode != Modes.PREDICTION
        else None
    )
    return features, labels


def eval_metrics_fn():
    return {"auc": AUCMetric()}
