// Native optimizer kernels for the host-resident parameter-server store.
//
// Behavioral counterpart of the reference's C++ update rules
// (/root/reference/elasticdl/go/pkg/kernel/capi/kernel_api.cc:6-96) and its
// Go row-loop sparse variants (go/pkg/kernel/kernel.go:35-199), redesigned
// for this framework's slab storage: embedding tables live in one contiguous
// [capacity, dim] float buffer per table, so sparse updates are a single C
// call taking (row_indices, k, dim) and looping rows natively instead of one
// cgo call per row.
//
// Plain restrict-qualified loops; g++ -O3 auto-vectorizes these memory-bound
// elementwise updates as well as Eigen expression maps do.

#include <cmath>
#include <cstdint>

extern "C" {

// ---------- dense ----------

void edl_sgd(const float* __restrict g, float* __restrict p, float lr,
             int64_t n) {
  for (int64_t i = 0; i < n; ++i) p[i] -= lr * g[i];
}

void edl_momentum(const float* __restrict g, float* __restrict p,
                  float* __restrict vel, float lr, float mu, int nesterov,
                  int64_t n) {
  if (nesterov) {
    for (int64_t i = 0; i < n; ++i) {
      vel[i] = mu * vel[i] + g[i];
      p[i] -= lr * (g[i] + mu * vel[i]);
    }
  } else {
    for (int64_t i = 0; i < n; ++i) {
      vel[i] = mu * vel[i] + g[i];
      p[i] -= lr * vel[i];
    }
  }
}

// step is 1-based; lr is pre-scaled here by the bias correction so the hot
// loop stays multiply-add only. max_sq == nullptr means plain Adam; non-null
// enables amsgrad.
void edl_adam(const float* __restrict g, float* __restrict p,
              float* __restrict m, float* __restrict v,
              float* __restrict max_sq, float lr, int64_t step, float b1,
              float b2, float eps, int64_t n) {
  const float corrected_lr =
      lr * std::sqrt(1.0f - std::pow(b2, (float)step)) /
      (1.0f - std::pow(b1, (float)step));
  const float one_m_b1 = 1.0f - b1;
  const float one_m_b2 = 1.0f - b2;
  if (max_sq) {
    for (int64_t i = 0; i < n; ++i) {
      m[i] = b1 * m[i] + one_m_b1 * g[i];
      v[i] = b2 * v[i] + one_m_b2 * g[i] * g[i];
      max_sq[i] = max_sq[i] > v[i] ? max_sq[i] : v[i];
      p[i] -= corrected_lr * m[i] / (std::sqrt(max_sq[i]) + eps);
    }
  } else {
    for (int64_t i = 0; i < n; ++i) {
      m[i] = b1 * m[i] + one_m_b1 * g[i];
      v[i] = b2 * v[i] + one_m_b2 * g[i] * g[i];
      p[i] -= corrected_lr * m[i] / (std::sqrt(v[i]) + eps);
    }
  }
}

void edl_adagrad(const float* __restrict g, float* __restrict p,
                 float* __restrict accum, float lr, float eps, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    accum[i] += g[i] * g[i];
    p[i] -= lr * g[i] / (std::sqrt(accum[i]) + eps);
  }
}

// ---------- row-indexed (sparse) over a [capacity, dim] table slab ----------
// grads: [k, dim]; rows[j] selects the slab row updated by grads[j].
// Duplicate rows are legal and applied sequentially in order.

void edl_sgd_indexed(const float* __restrict grads,
                     const int64_t* __restrict rows, int64_t k, int64_t dim,
                     float* __restrict table, float lr) {
  for (int64_t j = 0; j < k; ++j) {
    float* p = table + rows[j] * dim;
    const float* g = grads + j * dim;
    for (int64_t i = 0; i < dim; ++i) p[i] -= lr * g[i];
  }
}

void edl_momentum_indexed(const float* __restrict grads,
                          const int64_t* __restrict rows, int64_t k,
                          int64_t dim, float* __restrict table,
                          float* __restrict vel_table, float lr, float mu,
                          int nesterov) {
  for (int64_t j = 0; j < k; ++j) {
    const int64_t off = rows[j] * dim;
    float* p = table + off;
    float* vel = vel_table + off;
    const float* g = grads + j * dim;
    if (nesterov) {
      for (int64_t i = 0; i < dim; ++i) {
        vel[i] = mu * vel[i] + g[i];
        p[i] -= lr * (g[i] + mu * vel[i]);
      }
    } else {
      for (int64_t i = 0; i < dim; ++i) {
        vel[i] = mu * vel[i] + g[i];
        p[i] -= lr * vel[i];
      }
    }
  }
}

void edl_adam_indexed(const float* __restrict grads,
                      const int64_t* __restrict rows, int64_t k, int64_t dim,
                      float* __restrict table, float* __restrict m_table,
                      float* __restrict v_table,
                      float* __restrict max_sq_table, float lr, int64_t step,
                      float b1, float b2, float eps) {
  const float corrected_lr =
      lr * std::sqrt(1.0f - std::pow(b2, (float)step)) /
      (1.0f - std::pow(b1, (float)step));
  const float one_m_b1 = 1.0f - b1;
  const float one_m_b2 = 1.0f - b2;
  for (int64_t j = 0; j < k; ++j) {
    const int64_t off = rows[j] * dim;
    float* p = table + off;
    float* m = m_table + off;
    float* v = v_table + off;
    const float* g = grads + j * dim;
    if (max_sq_table) {
      float* ms = max_sq_table + off;
      for (int64_t i = 0; i < dim; ++i) {
        m[i] = b1 * m[i] + one_m_b1 * g[i];
        v[i] = b2 * v[i] + one_m_b2 * g[i] * g[i];
        ms[i] = ms[i] > v[i] ? ms[i] : v[i];
        p[i] -= corrected_lr * m[i] / (std::sqrt(ms[i]) + eps);
      }
    } else {
      for (int64_t i = 0; i < dim; ++i) {
        m[i] = b1 * m[i] + one_m_b1 * g[i];
        v[i] = b2 * v[i] + one_m_b2 * g[i] * g[i];
        p[i] -= corrected_lr * m[i] / (std::sqrt(v[i]) + eps);
      }
    }
  }
}

void edl_adagrad_indexed(const float* __restrict grads,
                         const int64_t* __restrict rows, int64_t k,
                         int64_t dim, float* __restrict table,
                         float* __restrict accum_table, float lr, float eps) {
  for (int64_t j = 0; j < k; ++j) {
    const int64_t off = rows[j] * dim;
    float* p = table + off;
    float* a = accum_table + off;
    const float* g = grads + j * dim;
    for (int64_t i = 0; i < dim; ++i) {
      a[i] += g[i] * g[i];
      p[i] -= lr * g[i] / (std::sqrt(a[i]) + eps);
    }
  }
}

// ---------- table maintenance ----------

// Gather rows out of a slab into out[k, dim] (embedding lookup hot path).
void edl_gather_rows(const float* __restrict table,
                     const int64_t* __restrict rows, int64_t k, int64_t dim,
                     float* __restrict out) {
  for (int64_t j = 0; j < k; ++j) {
    const float* src = table + rows[j] * dim;
    float* dst = out + j * dim;
    for (int64_t i = 0; i < dim; ++i) dst[i] = src[i];
  }
}

// Scatter rows into a slab (checkpoint restore / worker re-seed path).
void edl_scatter_rows(float* __restrict table,
                      const int64_t* __restrict rows, int64_t k, int64_t dim,
                      const float* __restrict values) {
  for (int64_t j = 0; j < k; ++j) {
    float* dst = table + rows[j] * dim;
    const float* src = values + j * dim;
    for (int64_t i = 0; i < dim; ++i) dst[i] = src[i];
  }
}

// xorshift64* uniform init in [lo, hi) — the lazy per-id embedding init
// (reference lazily seeds rows uniform [-0.05, 0.05],
// go/pkg/common/embedding_table.go:41-58).
void edl_uniform_init(float* __restrict dst, int64_t n, float lo, float hi,
                      uint64_t seed) {
  uint64_t s = seed ? seed : 0x9E3779B97F4A7C15ull;
  const float scale = (hi - lo) / 16777216.0f;  // 2^24
  for (int64_t i = 0; i < n; ++i) {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    uint64_t r = s * 0x2545F4914F6CDD1Dull;
    dst[i] = lo + scale * (float)(r >> 40);  // top 24 bits
  }
}

}  // extern "C"
