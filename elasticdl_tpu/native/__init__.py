"""ctypes bindings for the native optimizer/table kernels.

The reference reaches its C++ kernels through Go's cgo
(/root/reference/elasticdl/go/pkg/kernel/kernel.go:16-18); here the Python
parameter server calls the shared library directly via ctypes — no binding
codegen, no copy: numpy arrays pass as raw pointers.

`lib()` lazily builds libedl_kernels.so with the package Makefile on first
use (g++ is in the base image), so a fresh checkout needs no explicit build
step; set EDL_NO_NATIVE=1 to force the pure-numpy fallbacks in
elasticdl_tpu/ps/optimizer.py.
"""

import ctypes
import os
import subprocess
import threading

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libedl_kernels.so")
_lock = threading.Lock()
_lib = None


def _f32p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i64p(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _declare(lib):
    f32p = ctypes.POINTER(ctypes.c_float)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i64 = ctypes.c_int64
    f32 = ctypes.c_float
    sigs = {
        "edl_sgd": [f32p, f32p, f32, i64],
        "edl_momentum": [f32p, f32p, f32p, f32, f32, ctypes.c_int, i64],
        "edl_adam": [f32p, f32p, f32p, f32p, f32p, f32, i64, f32, f32, f32,
                     i64],
        "edl_adagrad": [f32p, f32p, f32p, f32, f32, i64],
        "edl_sgd_indexed": [f32p, i64p, i64, i64, f32p, f32],
        "edl_momentum_indexed": [f32p, i64p, i64, i64, f32p, f32p, f32, f32,
                                 ctypes.c_int],
        "edl_adam_indexed": [f32p, i64p, i64, i64, f32p, f32p, f32p, f32p,
                             f32, i64, f32, f32, f32],
        "edl_adagrad_indexed": [f32p, i64p, i64, i64, f32p, f32p, f32, f32],
        "edl_gather_rows": [f32p, i64p, i64, i64, f32p],
        "edl_scatter_rows": [f32p, i64p, i64, i64, f32p],
        "edl_uniform_init": [f32p, i64, f32, f32, ctypes.c_uint64],
        "edl_uniform_init_rows": [f32p, i64, i64, i64, f32, f32,
                                  ctypes.c_uint64],
        "edl_normal_init_rows": [f32p, i64, i64, i64, f32, f32,
                                 ctypes.c_uint64, ctypes.c_int],
        "edl_idmap_free": [ctypes.c_void_p],
        "edl_idmap_export_ids": [ctypes.c_void_p, i64, i64, i64p],
    }
    for name, argtypes in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = None
    # Record-file reader (recordio.cc) returns byte counts / error codes.
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.edl_records_read.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong, u8p,
        ctypes.c_longlong, i64p,
    ]
    lib.edl_records_read.restype = ctypes.c_longlong
    # id->row map handle functions (non-void returns).
    lib.edl_idmap_new.argtypes = [i64]
    lib.edl_idmap_new.restype = ctypes.c_void_p
    lib.edl_idmap_size.argtypes = [ctypes.c_void_p]
    lib.edl_idmap_size.restype = i64
    lib.edl_idmap_rows_for_ids.argtypes = [
        ctypes.c_void_p, i64p, i64, ctypes.c_int, i64p,
    ]
    lib.edl_idmap_rows_for_ids.restype = i64
    lib.edl_dedup_sum.argtypes = [i64p, f32p, i64, i64, i64p, f32p]
    lib.edl_dedup_sum.restype = i64
    return lib


def build():
    subprocess.run(
        ["make", "-s", "-C", _HERE], check=True, capture_output=True
    )


def lib():
    """The loaded shared library, building it on first call. Returns None
    when natives are disabled or the toolchain is unavailable."""
    global _lib
    if _lib is not None:
        return _lib or None
    with _lock:
        if _lib is not None:
            return _lib or None
        if os.environ.get("EDL_NO_NATIVE"):
            _lib = False
            return None
        try:
            sources = ("kernels.cc", "recordio.cc", "idmap.cc")
            if not os.path.exists(_SO) or any(
                os.path.getmtime(_SO)
                < os.path.getmtime(os.path.join(_HERE, src))
                for src in sources
            ):
                build()
            _lib = _declare(ctypes.CDLL(_SO))
            logger.info("Loaded native kernels from %s", _SO)
        except Exception as e:
            logger.warning(
                "Native kernels unavailable (%s); numpy fallbacks in use", e
            )
            _lib = False
    return _lib or None


def available():
    return lib() is not None
