// Native id->row map and sparse-gradient codec kernels for the PS store.
//
// The reference keeps its production PS entirely compiled — Go gRPC serving
// (/root/reference/elasticdl/go/pkg/ps/server.go:176-206) over C++ Eigen
// kernels (go/pkg/kernel/capi/kernel_api.cc:6-96) — so the push/pull hot
// loop never touches an interpreter. This file is the missing half of that
// story for the TPU build: the per-id work that remained in Python
// (EmbeddingTable.rows_for_ids' dict loop, lazy row init, IndexedSlices
// dedup/merge) moves behind single C calls over contiguous buffers.
//
// EdlIdMap is an open-addressing (linear probe, power-of-two, splitmix64)
// int64 -> row-index hash map that also keeps the insertion-ordered id list:
// row i was created by the i-th distinct id ever seen, so exporting a page
// of rows is a straight slab slice. INT64_MIN is the reserved empty-slot
// sentinel (embedding ids are hashes/offsets, never INT64_MIN).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

constexpr int64_t kEmpty = INT64_MIN;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

struct IdMap {
  std::vector<int64_t> slot_ids;   // kEmpty or the id stored in this slot
  std::vector<int64_t> slot_rows;  // row index parallel to slot_ids
  std::vector<int64_t> order;      // insertion-ordered ids (row i -> order[i])
  uint64_t mask = 0;

  explicit IdMap(int64_t cap_hint) {
    uint64_t cap = 64;
    while ((int64_t)cap < cap_hint * 2) cap <<= 1;
    slot_ids.assign(cap, kEmpty);
    slot_rows.assign(cap, 0);
    mask = cap - 1;
  }

  void grow() {
    const uint64_t cap = (mask + 1) << 1;
    std::vector<int64_t> ids(cap, kEmpty), rows(cap, 0);
    const uint64_t m = cap - 1;
    for (uint64_t i = 0; i <= mask; ++i) {
      if (slot_ids[i] == kEmpty) continue;
      uint64_t j = splitmix64((uint64_t)slot_ids[i]) & m;
      while (ids[j] != kEmpty) j = (j + 1) & m;
      ids[j] = slot_ids[i];
      rows[j] = slot_rows[i];
    }
    slot_ids.swap(ids);
    slot_rows.swap(rows);
    mask = m;
  }

  // Row index for id, creating the next row if absent (and allowed).
  int64_t row_for(int64_t id, bool create) {
    uint64_t j = splitmix64((uint64_t)id) & mask;
    while (slot_ids[j] != kEmpty) {
      if (slot_ids[j] == id) return slot_rows[j];
      j = (j + 1) & mask;
    }
    if (!create) return -1;
    const int64_t row = (int64_t)order.size();
    slot_ids[j] = id;
    slot_rows[j] = row;
    order.push_back(id);
    // Keep load factor under 1/2 so probes stay short.
    if ((uint64_t)order.size() * 2 > mask) grow();
    return row;
  }
};

}  // namespace

extern "C" {

void* edl_idmap_new(int64_t cap_hint) {
  return new IdMap(cap_hint > 0 ? cap_hint : 1);
}

void edl_idmap_free(void* h) { delete (IdMap*)h; }

int64_t edl_idmap_size(void* h) { return (int64_t)((IdMap*)h)->order.size(); }

// rows_out[i] = row index of ids[i]; unseen ids get fresh sequential rows
// when create_missing, else -1. Returns the map size AFTER the call, so the
// caller knows the new rows are exactly [old_size, returned_size).
int64_t edl_idmap_rows_for_ids(void* h, const int64_t* ids, int64_t n,
                               int create_missing, int64_t* rows_out) {
  IdMap* m = (IdMap*)h;
  const bool create = create_missing != 0;
  for (int64_t i = 0; i < n; ++i) rows_out[i] = m->row_for(ids[i], create);
  return (int64_t)m->order.size();
}

// Insertion-ordered ids [start, start+count) -> out (checkpoint export).
void edl_idmap_export_ids(void* h, int64_t start, int64_t count,
                          int64_t* out) {
  IdMap* m = (IdMap*)h;
  for (int64_t i = 0; i < count; ++i) out[i] = m->order[start + i];
}

// ---------- bulk lazy row init ----------
// Same per-row seed schedule as EmbeddingTable._init_row (table_seed *
// 0x9E3779B1 + row + 1) feeding the same xorshift64* generator as
// edl_uniform_init (kernels.cc), so one bulk call over the fresh row range
// is bitwise-identical to the old one-ctypes-call-per-row path.

void edl_uniform_init(float*, int64_t, float, float, uint64_t);  // kernels.cc

void edl_uniform_init_rows(float* slab, int64_t dim, int64_t start_row,
                           int64_t n_rows, float lo, float hi,
                           uint64_t table_seed) {
  for (int64_t r = start_row; r < start_row + n_rows; ++r) {
    const uint64_t seed = table_seed * 0x9E3779B1ull + (uint64_t)r + 1;
    edl_uniform_init(slab + r * dim, dim, lo, hi, seed);
  }
}

// Box-Muller over the same xorshift64* stream; truncated resamples outside
// mean +/- 2*stddev (the reference's truncated_normal contract,
// go/pkg/common/initializer.go).
void edl_normal_init_rows(float* slab, int64_t dim, int64_t start_row,
                          int64_t n_rows, float mean, float stddev,
                          uint64_t table_seed, int truncated) {
  const double two_pi = 6.283185307179586;
  for (int64_t r = start_row; r < start_row + n_rows; ++r) {
    uint64_t s = table_seed * 0x9E3779B1ull + (uint64_t)r + 1;
    if (!s) s = 0x9E3779B97F4A7C15ull;
    float* dst = slab + r * dim;
    auto next_u01 = [&s]() {
      s ^= s >> 12;
      s ^= s << 25;
      s ^= s >> 27;
      const uint64_t v = s * 0x2545F4914F6CDD1Dull;
      // (0, 1]: avoid log(0).
      return ((double)(v >> 40) + 1.0) / 16777216.0;
    };
    for (int64_t i = 0; i < dim; ++i) {
      double z;
      do {
        const double u1 = next_u01(), u2 = next_u01();
        z = std::sqrt(-2.0 * std::log(u1)) * std::cos(two_pi * u2);
      } while (truncated && std::fabs(z) > 2.0);
      dst[i] = (float)(mean + stddev * z);
    }
  }
}

// ---------- IndexedSlices dedup/merge ----------
// Sum rows with duplicate ids; output ids sorted ascending (the np.unique
// contract the Python codec had). out_ids/out_vals are caller-allocated at
// worst-case size n. Returns the number of unique ids.
//
// Sort is an adaptive LSD radix (11-bit digits) over sign-flipped keys:
// embedding ids live in a few-million-wide vocabulary, so 2-3 counting
// passes beat a comparator sort by ~3x on the 640k-id pushes the DeepFM
// bench generates.

namespace {

void radix_argsort(const int64_t* keys, int64_t n, std::vector<int64_t>& idx) {
  idx.resize(n);
  for (int64_t i = 0; i < n; ++i) idx[i] = i;
  // Order-preserving rebase: key - min fits uint64 for any int64 range and
  // keeps the digit count proportional to the actual id spread, not the
  // type width.
  int64_t mn = keys[0], mx = keys[0];
  for (int64_t i = 1; i < n; ++i) {
    if (keys[i] < mn) mn = keys[i];
    if (keys[i] > mx) mx = keys[i];
  }
  const uint64_t span = (uint64_t)mx - (uint64_t)mn;
  constexpr int kBits = 11;
  constexpr int64_t kBuckets = 1 << kBits;
  std::vector<int64_t> tmp(n), hist(kBuckets);
  for (int shift = 0; shift == 0 || (shift < 64 && (span >> shift));
       shift += kBits) {
    std::fill(hist.begin(), hist.end(), 0);
    for (int64_t i = 0; i < n; ++i) {
      const uint64_t k = (uint64_t)keys[idx[i]] - (uint64_t)mn;
      ++hist[(k >> shift) & (kBuckets - 1)];
    }
    int64_t sum = 0;
    for (int64_t b = 0; b < kBuckets; ++b) {
      const int64_t c = hist[b];
      hist[b] = sum;
      sum += c;
    }
    for (int64_t i = 0; i < n; ++i) {
      const uint64_t k = (uint64_t)keys[idx[i]] - (uint64_t)mn;
      tmp[hist[(k >> shift) & (kBuckets - 1)]++] = idx[i];
    }
    idx.swap(tmp);
  }
}

}  // namespace

int64_t edl_dedup_sum(const int64_t* ids, const float* vals, int64_t n,
                      int64_t dim, int64_t* out_ids, float* out_vals) {
  if (n == 0) return 0;
  std::vector<int64_t> idx;
  radix_argsort(ids, n, idx);
  int64_t u = -1, last = 0;
  for (int64_t k = 0; k < n; ++k) {
    const int64_t i = idx[k];
    const float* src = vals + i * dim;
    if (u < 0 || ids[i] != last) {
      ++u;
      last = ids[i];
      out_ids[u] = last;
      float* dst = out_vals + u * dim;
      for (int64_t d = 0; d < dim; ++d) dst[d] = src[d];
    } else {
      float* dst = out_vals + u * dim;
      for (int64_t d = 0; d < dim; ++d) dst[d] += src[d];
    }
  }
  return u + 1;
}

}  // extern "C"
