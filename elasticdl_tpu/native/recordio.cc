// Native batch range-reads for .edlr record files (data/recordfile.py).
//
// The reference's data plane leans on a native RecordIO library for its
// range reads (/root/reference/elasticdl/python/data/reader/
// recordio_reader.py:27-62 over the pyrecordio C extension); this is the
// equivalent for the .edlr format: one mmap, one sequential scan over the
// requested record range, CRC32 verification (format v2) and payload
// copy-out done in C instead of per-record Python struct unpacking.
//
// Layout (little-endian; see recordfile.py):
//   [magic "EDLR"][u32 version]
//   v1 record: [u32 len][payload]
//   v2 record: [u32 len][u32 crc32(payload)][payload]
//   footer: [u64 offset]*num  [u64 num][u64 index_offset][magic "EDLI"]
//
// Error codes (negative returns): -1 io/open, -2 corrupt header/footer,
// -3 range out of bounds, -4 output buffer too small, -5 crc mismatch,
// -6 unsupported version.

#include <zlib.h>

#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr long long kErrIo = -1;
constexpr long long kErrCorrupt = -2;
constexpr long long kErrRange = -3;
constexpr long long kErrBuffer = -4;
constexpr long long kErrCrc = -5;
constexpr long long kErrVersion = -6;

constexpr size_t kHeaderSize = 8;    // magic + u32 version
constexpr size_t kFooterTail = 20;   // u64 num + u64 index_offset + magic

struct Mapped {
  const unsigned char* p = nullptr;
  size_t n = 0;
  int fd = -1;

  ~Mapped() {
    if (p != nullptr) munmap(const_cast<unsigned char*>(p), n);
    if (fd >= 0) close(fd);
  }
};

uint32_t le32(const unsigned char* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;  // targets are little-endian (x86/ARM TPU hosts)
}

uint64_t le64(const unsigned char* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

bool map_file(const char* path, Mapped* m) {
  m->fd = open(path, O_RDONLY);
  if (m->fd < 0) return false;
  struct stat st;
  if (fstat(m->fd, &st) != 0 || st.st_size < 0) return false;
  m->n = static_cast<size_t>(st.st_size);
  if (m->n == 0) return false;
  void* p = mmap(nullptr, m->n, PROT_READ, MAP_PRIVATE, m->fd, 0);
  if (p == MAP_FAILED) return false;
  m->p = static_cast<const unsigned char*>(p);
  return true;
}

struct Parsed {
  uint32_t version;
  uint64_t num_records;
  uint64_t index_offset;
};

long long parse(const Mapped& m, Parsed* out) {
  if (m.n < kHeaderSize + kFooterTail) return kErrCorrupt;
  if (std::memcmp(m.p, "EDLR", 4) != 0) return kErrCorrupt;
  out->version = le32(m.p + 4);
  if (out->version != 1 && out->version != 2) return kErrVersion;
  const unsigned char* tail = m.p + m.n - kFooterTail;
  if (std::memcmp(tail + 16, "EDLI", 4) != 0) return kErrCorrupt;
  out->num_records = le64(tail);
  out->index_offset = le64(tail + 8);
  // The whole offset index must sit between the records and the tail.
  if (out->index_offset > m.n - kFooterTail ||
      out->num_records > (m.n - kFooterTail - out->index_offset) / 8) {
    return kErrCorrupt;
  }
  return 0;
}

}  // namespace

extern "C" {

// Copies the payloads of records [start, start+count) contiguously into
// out_buf (capacity cap bytes) and each payload length into out_lens
// (count entries). Returns total payload bytes, or a negative error code.
long long edl_records_read(const char* path, long long start,
                           long long count, unsigned char* out_buf,
                           long long cap, long long* out_lens) {
  if (start < 0 || count < 0) return kErrRange;
  Mapped m;
  if (!map_file(path, &m)) return kErrIo;
  Parsed f;
  long long rc = parse(m, &f);
  if (rc < 0) return rc;
  if (static_cast<uint64_t>(start) + static_cast<uint64_t>(count) >
      f.num_records) {
    return kErrRange;
  }
  if (count == 0) return 0;

  const unsigned char* index = m.p + f.index_offset;
  uint64_t off = le64(index + 8 * static_cast<uint64_t>(start));
  const uint64_t rec_header = (f.version == 2) ? 8 : 4;
  long long total = 0;
  for (long long i = 0; i < count; ++i) {
    // Subtract-form bounds checks: `off + len` could wrap uint64 on a
    // corrupt index/length and slip past an addition-form comparison.
    if (off >= f.index_offset || rec_header > f.index_offset - off) {
      return kErrCorrupt;
    }
    uint32_t len = le32(m.p + off);
    uint32_t want_crc = (f.version == 2) ? le32(m.p + off + 4) : 0;
    off += rec_header;
    if (len > f.index_offset - off) return kErrCorrupt;
    if (f.version == 2) {
      uint32_t got =
          static_cast<uint32_t>(crc32(0L, m.p + off, len));
      if (got != want_crc) return kErrCrc;
    }
    if (total + static_cast<long long>(len) > cap) return kErrBuffer;
    std::memcpy(out_buf + total, m.p + off, len);
    out_lens[i] = static_cast<long long>(len);
    total += len;
    off += len;
  }
  return total;
}

}  // extern "C"
