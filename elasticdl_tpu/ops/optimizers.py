"""Optimizer descriptors usable on both sides of the wire.

The reference extracts (opt_type, opt_args) from a live Keras optimizer to
re-instantiate it inside the Go parameter server
(/root/reference/elasticdl/python/common/model_utils.py:227,
go/pkg/ps/optimizer.go:329-390). Here the model zoo exports an OptimizerSpec
directly; the worker materializes it as an optax transform (for local /
AllReduce training where the update runs on-TPU), and the parameter server
materializes the same spec against its host-resident store via the native
C++ kernels (elasticdl_tpu/native).
"""

import optax

# name -> (constructor kwargs accepted, default values)
_SUPPORTED = {
    "sgd": {"learning_rate": 0.1},
    "momentum": {"learning_rate": 0.1, "momentum": 0.9, "nesterov": False},
    "adam": {
        "learning_rate": 0.001,
        "beta_1": 0.9,
        "beta_2": 0.999,
        "epsilon": 1e-8,
        "amsgrad": False,
    },
    "adagrad": {"learning_rate": 0.1, "initial_accumulator_value": 0.1,
                "epsilon": 1e-7},
}


class OptimizerSpec:
    def __init__(self, name, **hyperparams):
        name = name.lower()
        if name not in _SUPPORTED:
            raise ValueError(
                f"unsupported optimizer {name!r}; choose from "
                f"{sorted(_SUPPORTED)}"
            )
        unknown = set(hyperparams) - set(_SUPPORTED[name])
        if unknown:
            raise ValueError(f"unknown {name} hyperparams: {sorted(unknown)}")
        self.name = name
        self.hyperparams = {**_SUPPORTED[name], **hyperparams}

    @property
    def learning_rate(self):
        return self.hyperparams["learning_rate"]

    def to_optax(self) -> optax.GradientTransformation:
        h = self.hyperparams
        if self.name == "sgd":
            return optax.sgd(h["learning_rate"])
        if self.name == "momentum":
            return optax.sgd(
                h["learning_rate"],
                momentum=h["momentum"],
                nesterov=h["nesterov"],
            )
        if self.name == "adam":
            if h["amsgrad"]:
                return optax.amsgrad(
                    h["learning_rate"],
                    b1=h["beta_1"],
                    b2=h["beta_2"],
                    eps=h["epsilon"],
                )
            return optax.adam(
                h["learning_rate"],
                b1=h["beta_1"],
                b2=h["beta_2"],
                eps=h["epsilon"],
            )
        if self.name == "adagrad":
            return optax.adagrad(
                h["learning_rate"],
                initial_accumulator_value=h["initial_accumulator_value"],
                eps=h["epsilon"],
            )
        raise AssertionError(self.name)

    def to_flags(self):
        """(name, hyperparams) for re-instantiation inside a PS process."""
        return self.name, dict(self.hyperparams)

    def __repr__(self):
        return f"OptimizerSpec({self.name}, {self.hyperparams})"


def sgd(learning_rate=0.1):
    return OptimizerSpec("sgd", learning_rate=learning_rate)


def momentum(learning_rate=0.1, momentum_value=0.9, nesterov=False):
    return OptimizerSpec(
        "momentum",
        learning_rate=learning_rate,
        momentum=momentum_value,
        nesterov=nesterov,
    )


def adam(learning_rate=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-8,
         amsgrad=False):
    return OptimizerSpec(
        "adam",
        learning_rate=learning_rate,
        beta_1=beta_1,
        beta_2=beta_2,
        epsilon=epsilon,
        amsgrad=amsgrad,
    )


def adagrad(learning_rate=0.1, initial_accumulator_value=0.1, epsilon=1e-7):
    return OptimizerSpec(
        "adagrad",
        learning_rate=learning_rate,
        initial_accumulator_value=initial_accumulator_value,
        epsilon=epsilon,
    )
