"""Flash attention for TPU via Pallas — fused forward AND backward — with an
XLA reference fallback.

No reference-framework counterpart (the reference is DP-only and has no
attention ops; SURVEY.md §5 marks long-context as absent upstream) — this is
a capability extension required for long-context training.

Design: the standard blockwise online-softmax scheme over a
(batch*heads, q_blocks, k_blocks) grid. K/V stream through VMEM one
[block_k, D] tile at a time (the k index is the minormost grid axis, so
consecutive steps revisit the same q/output block while new K/V tiles DMA
in), running (max, sum, acc) live in VMEM scratch, and the S x S score
matrix never materializes — in EITHER pass:

- forward emits the per-row log-sum-exp as a residual, lane-replicated to
  [bh, S, 128] (the (8,128) tiling makes a plain 1-D row vector an illegal
  block; lane replication is the canonical TPU layout for row stats, cf.
  jax.experimental.pallas.ops.tpu.flash_attention's MIN_BLOCK_SIZE scratch).
- backward runs two streaming kernels: dq over (bh, q_blocks, k_blocks)
  and combined dk/dv over (bh, k_blocks, q_blocks), each recomputing P
  one [block_q, block_k] tile at a time from the saved lse, so backward
  memory is O(S) + tiles, not O(S^2).
- delta = rowsum(dout * out) is precomputed in one cheap fused XLA
  elementwise pass and streamed like lse.

Causal masking skips fully-masked tiles (pl.when), so upper-triangle tiles
cost no FLOPs. Under ring/Ulysses sequence parallelism
(parallel/ring_attention.py) the per-device S is the block, so VMEM bounds
the per-shard sequence, not the global one.
"""

import functools
import os

import jax
import jax.numpy as jnp

# Block-size sweep on TPU v5e (S=4096, bf16, causal fwd+bwd, D=64):
# 1024x1024 tiles run 5.49 ms/step vs 5.93 (512x512) and 6.76 (256x256),
# and 1.5x faster than the full-matrix XLA path (8.26 ms) — bigger tiles
# amortize grid overhead and fill the MXU; blocks auto-clamp to S for
# short sequences.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
NEG_INF = -1e30
LANES = 128  # lane replication for row statistics (lse, delta)


def _use_pallas():
    if os.environ.get("EDL_FORCE_PALLAS_INTERPRET"):
        return True
    return jax.default_backend() == "tpu"


def _interpret():
    return bool(os.environ.get("EDL_FORCE_PALLAS_INTERPRET"))


# ---------- reference path (also the correctness oracle in tests) ----------


def reference_attention(q, k, v, causal=False):
    """[B, H, S, D] full attention in plain XLA."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


# ---------- shared tile helpers ----------


def _last_kj(i, block_q, block_k, num_k_blocks, causal):
    """Index of the last k tile the i-th q tile attends to."""
    if not causal:
        return num_k_blocks - 1
    return jnp.minimum(
        (((i + 1) * block_q - 1) // block_k), num_k_blocks - 1
    )


def _first_qi(j, block_q, block_k, causal):
    """Index of the first q tile that sees the j-th k tile."""
    if not causal:
        return 0
    return (j * block_k) // block_q


def _causal_mask_scores(scores, i, j, block_q, block_k):
    """Mask score tile (i, j) below the global causal diagonal."""
    q_pos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(q_pos >= k_pos, scores, NEG_INF)


# ---------- forward kernel ----------


def _fwd_kernel(
    q_ref, k_ref, v_ref, *refs,
    block_q, block_k, num_k_blocks, causal, scale, emit_lse,
):
    from jax.experimental import pallas as pl

    if emit_lse:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
        lse_ref = None
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (minormost: iterates fastest)
    last_j = _last_kj(i, block_q, block_k, num_k_blocks, causal)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    # Tiles fully above the causal diagonal contribute nothing: skip. (The
    # k/v index maps also clamp to last_j, so skipped steps re-address the
    # already-resident tile and cost no DMA either.)
    relevant = (j <= last_j) if causal else True

    @pl.when(relevant)
    def _accumulate():
        q = q_ref[:].astype(jnp.float32) * scale
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        scores = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            scores = _causal_mask_scores(scores, i, j, block_q, block_k)
        m_prev = m_scr[:, :1]  # [block_q, 1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == last_j)
    def _finalize():
        m = m_scr[:, :1]
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        if lse_ref is not None:
            lse = m + jnp.log(l_safe)
            lse_ref[:] = jnp.broadcast_to(lse, lse_ref.shape)


def _flash_forward(q, k, v, causal, block_q, block_k, emit_lse):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    bh = b * h
    num_q, num_k = s // block_q, s // block_k
    kernel = functools.partial(
        _fwd_kernel,
        block_q=block_q,
        block_k=block_k,
        num_k_blocks=num_k,
        causal=causal,
        scale=d**-0.5,
        emit_lse=emit_lse,
    )

    def kv_index(b_, i, j):
        # Clamp past-diagonal steps to the last relevant tile: an unchanged
        # block index between consecutive grid steps skips the DMA.
        return (b_, _last_kj_clamped(i, j), 0)

    def _last_kj_clamped(i, j):
        return (
            jnp.minimum(j, _last_kj(i, block_q, block_k, num_k, causal))
            if causal
            else j
        )

    out_specs = [
        pl.BlockSpec(
            (None, block_q, d), lambda b_, i, j: (b_, i, 0),
            memory_space=pltpu.VMEM,
        ),
    ]
    out_shape = [jax.ShapeDtypeStruct((bh, s, d), q.dtype)]
    if emit_lse:
        out_specs.append(
            pl.BlockSpec(
                (None, block_q, LANES), lambda b_, i, j: (b_, i, 0),
                memory_space=pltpu.VMEM,
            )
        )
        out_shape.append(
            jax.ShapeDtypeStruct((bh, s, LANES), jnp.float32)
        )
    res = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec(
                (None, block_q, d), lambda b_, i, j: (b_, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, block_k, d), kv_index, memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (None, block_k, d), kv_index, memory_space=pltpu.VMEM
            ),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(
        q.reshape(bh, s, d), k.reshape(bh, s, d), v.reshape(bh, s, d)
    )
    if not emit_lse:
        return res[0].reshape(b, h, s, d), None
    out, lse = res
    # Keep the residual compact between passes: one lane is the value.
    return out.reshape(b, h, s, d), lse[:, :, 0].reshape(b, h, s)


# ---------- backward kernels ----------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, block_q, block_k, num_k_blocks, causal, scale,
):
    from jax.experimental import pallas as pl

    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # k block (fastest)
    last_j = _last_kj(i, block_q, block_k, num_k_blocks, causal)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    relevant = (j <= last_j) if causal else True

    @pl.when(relevant)
    def _accumulate():
        q = q_ref[:].astype(jnp.float32)
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, :1]  # [block_q, 1]
        delta = delta_ref[:, :1]
        scores = scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            scores = _causal_mask_scores(scores, i, j, block_q, block_k)
        p = jnp.exp(scores - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_scr[:] = dq_scr[:] + scale * jnp.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    @pl.when(j == last_j)
    def _finalize():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, block_q, block_k, num_q_blocks, causal, scale,
):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)  # k block
    i = pl.program_id(2)  # q block (fastest)
    first_i = _first_qi(j, block_q, block_k, causal)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    # q tiles strictly above the diagonal see none of this k tile. (The
    # q-side index maps clamp to first_i, so skipped steps cost no DMA.)
    relevant = (i >= first_i) if causal else True

    @pl.when(relevant)
    def _accumulate():
        q = q_ref[:].astype(jnp.float32)
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, :1]
        delta = delta_ref[:, :1]
        scores = scale * jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            scores = _causal_mask_scores(scores, i, j, block_q, block_k)
        p = jnp.exp(scores - lse)  # [block_q, block_k]
        dv_scr[:] = dv_scr[:] + jnp.dot(
            p.T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_scr[:] = dk_scr[:] + scale * jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32
        )

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    bh = b * h
    num_q, num_k = s // block_q, s // block_k
    scale = d**-0.5

    q3, k3, v3 = (x.reshape(bh, s, d) for x in (q, k, v))
    g3 = g.reshape(bh, s, d)
    # delta = rowsum(dout * out): one fused elementwise+reduce XLA pass.
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).reshape(bh, s)
    lse_fat = jnp.broadcast_to(
        lse.reshape(bh, s)[:, :, None], (bh, s, LANES)
    )
    delta_fat = jnp.broadcast_to(delta[:, :, None], (bh, s, LANES))

    # dq: grid (bh, q, k) — q-indexed tiles are major, k-indexed minor.
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            block_q=block_q,
            block_k=block_k,
            num_k_blocks=num_k,
            causal=causal,
            scale=scale,
        ),
        grid=(bh, num_q, num_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_k, d), lambda b_, i, j: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_k, d), lambda b_, i, j: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_q, d), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_q, LANES), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_q, LANES), lambda b_, i, j: (b_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (None, block_q, d), lambda b_, i, j: (b_, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q3, k3, v3, g3, lse_fat, delta_fat)

    # dk/dv: grid (bh, k, q) — k-indexed tiles are major, q-indexed minor.
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            block_q=block_q,
            block_k=block_k,
            num_q_blocks=num_q,
            causal=causal,
            scale=scale,
        ),
        grid=(bh, num_k, num_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b_, j, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_q, d), lambda b_, j, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_q, LANES), lambda b_, j, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_q, LANES), lambda b_, j, i: (b_, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((None, block_k, d), lambda b_, j, i: (b_, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, g3, lse_fat, delta_fat)

    return (
        dq.reshape(b, h, s, d),
        dk.reshape(b, h, s, d),
        dv.reshape(b, h, s, d),
    )


# ---------- public API with custom VJP ----------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q, k, v, causal=False, block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K
):
    """Attention over [B, H, S, D]; S must be a multiple of the (clamped)
    block sizes on the Pallas path (the reference path has no constraint)."""
    bq, bk = _clamp_blocks(q.shape[2], block_q, block_k)
    if _pallas_ok(q.shape[2], bq, bk):
        out, _ = _flash_forward(q, k, v, causal, bq, bk, emit_lse=False)
        return out
    return reference_attention(q, k, v, causal)


def _fit_block(s, requested):
    """Largest block <= requested that divides S (halving down to 128), so
    raising the default block size never kicks divisible-by-512 sequence
    lengths off the Pallas kernel onto the O(S^2) fallback."""
    b = min(requested, s)
    while b > 128 and s % b:
        b //= 2
    return b


def _clamp_blocks(s, block_q, block_k):
    return _fit_block(s, block_q), _fit_block(s, block_k)


def _pallas_ok(s, block_q, block_k):
    return _use_pallas() and s % block_q == 0 and s % block_k == 0


def _fwd(q, k, v, causal, block_q, block_k):
    bq, bk = _clamp_blocks(q.shape[2], block_q, block_k)
    if _pallas_ok(q.shape[2], bq, bk):
        out, lse = _flash_forward(q, k, v, causal, bq, bk, emit_lse=True)
        return out, (q, k, v, out, lse)
    out = reference_attention(q, k, v, causal)
    return out, (q, k, v, out, None)


def _bwd(causal, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    bq, bk = _clamp_blocks(q.shape[2], block_q, block_k)
    if lse is not None:
        return _flash_backward(q, k, v, out, lse, g, causal, bq, bk)
    return _bwd_xla(q, k, v, out, g, causal)


def _bwd_xla(q, k, v, out, g, causal):
    """Full-matrix XLA backward (fallback path only): scores recomputed,
    then dV = P^T g;  dP = g V^T;  dS = P * (dP - rowsum(g * out));
    dQ = dS K * scale;  dK = dS^T Q * scale."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, NEG_INF)
    lse = jax.nn.logsumexp(scores, axis=-1)
    p = jnp.exp(scores - lse[..., None])
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g, v)
    delta = jnp.sum(g * out, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
