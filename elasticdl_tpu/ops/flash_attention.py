"""Flash attention for TPU via Pallas, with an XLA reference fallback.

No reference-framework counterpart (the reference is DP-only and has no
attention ops; SURVEY.md §5 marks long-context as absent upstream) — this is
a capability extension required for long-context training. Design follows
the standard blockwise online-softmax scheme: grid over (batch*heads,
q_blocks); the kernel streams K/V blocks from VMEM, keeping running
(max, sum, acc) so the S x S score matrix never materializes
(/opt/skills/guides/pallas_guide.md: MXU tiling + VMEM residency).

The backward pass uses the saved log-sum-exp to recompute P blockwise in
plain XLA — correct and O(S^2) compute but not O(S^2) memory per block pair;
a fused Pallas backward is future work. Under ring/Ulysses sequence
parallelism (parallel/ring_attention.py) the per-device S is the block, so
this bound is the per-shard sequence, not the global one.
"""

import functools
import os

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _use_pallas():
    if os.environ.get("EDL_FORCE_PALLAS_INTERPRET"):
        return True
    return jax.default_backend() == "tpu"


# ---------- reference path (also the correctness oracle in tests) ----------


def reference_attention(q, k, v, causal=False):
    """[B, H, S, D] full attention in plain XLA."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


# ---------- pallas kernel ----------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale):
    # q_ref: [block_q, D]; k_ref/v_ref: [S, D] for this (batch, head).
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    q_block_idx = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = s // block_k

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        scores = jnp.dot(
            q, k_blk.T, preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            q_pos = q_block_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=1)
        acc_new = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    if causal:
        # Blocks fully above the diagonal contribute nothing; stop at the
        # last k-block this q-block can see: ceil((i+1)*block_q / block_k).
        last = jnp.minimum(
            num_k_blocks,
            ((q_block_idx + 1) * block_q + block_k - 1) // block_k,
        )
        m, l, acc = jax.lax.fori_loop(0, last, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(
            0, num_k_blocks, body, (m0, l0, acc0)
        )
    # lse is NOT emitted: a 1-D per-row output violates the TPU (8, 128)
    # block-tiling constraint, and the backward recomputes scores anyway —
    # it rederives lse there for free (see _bwd).
    o_ref[:] = (acc / l[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    bh = b * h
    scale = d**-0.5
    q3 = q.reshape(bh, s, d)
    k3 = k.reshape(bh, s, d)
    v3 = v.reshape(bh, s, d)
    grid = (bh, s // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            # Leading None squeezes the (batch*head) dim off the refs.
            pl.BlockSpec(
                (None, block_q, d),
                lambda i, j: (i, j, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, s, d), lambda i, j: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, s, d), lambda i, j: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (None, block_q, d),
            lambda i, j: (i, j, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=bool(os.environ.get("EDL_FORCE_PALLAS_INTERPRET")),
    )(q3, k3, v3)
    return out.reshape(b, h, s, d)


# ---------- public API with custom VJP ----------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q, k, v, causal=False, block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K
):
    """Attention over [B, H, S, D]; S must be a multiple of the block sizes
    on the Pallas path (the reference path has no constraint)."""
    return _forward_impl(q, k, v, causal, block_q, block_k)


def _forward_impl(q, k, v, causal, block_q, block_k):
    s = q.shape[2]
    if _use_pallas() and s % block_q == 0 and s % block_k == 0:
        return _flash_forward(q, k, v, causal, block_q, block_k)
    return reference_attention(q, k, v, causal)


def _fwd(q, k, v, causal, block_q, block_k):
    out = _forward_impl(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out)


def _bwd(causal, block_q, block_k, residuals, g):
    """Standard flash backward: scores recomputed (so lse comes for free),
    then dV = P^T g;  dP = g V^T;  dS = P * (dP - rowsum(g * out));
    dQ = dS K * scale;  dK = dS^T Q * scale."""
    q, k, v, out = residuals
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, NEG_INF)
    lse = jax.nn.logsumexp(scores, axis=-1)
    p = jnp.exp(scores - lse[..., None])
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g, v)
    delta = jnp.sum(g * out, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
