"""CLI for the simulated fleet: `python -m elasticdl_tpu.fleet`.

Runs one harness for a fixed wall-clock window and prints the stats
dict as JSON — the quickest way to eyeball push-vs-pull master cost at
a given scale without going through the bench runner:

    python -m elasticdl_tpu.fleet --pods 200 --seconds 10 --mode push
    python -m elasticdl_tpu.fleet --pods 200 --seconds 10 --mode pull
"""

import argparse
import json
import sys

from elasticdl_tpu.fleet.harness import (
    FleetHarness,
    churn_schedule,
    preemption_wave_schedule,
)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.fleet",
        description="Run a simulated fleet against a real master.",
    )
    parser.add_argument("--pods", type=int, default=50,
                        help="total simulated pods (workers + PS)")
    parser.add_argument("--ps", type=int, default=0,
                        help="how many of --pods are parameter servers")
    parser.add_argument("--seconds", type=float, default=10.0,
                        help="wall-clock run time")
    parser.add_argument("--mode", choices=("push", "pull"),
                        default="push")
    parser.add_argument("--tick-interval", type=float, default=0.25,
                        help="pod scheduler tick interval (s)")
    parser.add_argument("--push-interval", type=float, default=0.5,
                        help="per-pod telemetry push interval (s)")
    parser.add_argument("--kills", type=int, default=0,
                        help="pods killed (and relaunched) by chaos")
    parser.add_argument("--stragglers", type=int, default=0,
                        help="pods slowed 4x for a chaos window")
    parser.add_argument("--preemption-wave", type=float, default=0.0,
                        help="kill this fraction of pods in ONE tick "
                             "(overrides --kills/--stragglers)")
    parser.add_argument("--lease-batch", type=int, default=1,
                        help="tasks leased/reported per RPC (batched "
                             "protocol when > 1)")
    parser.add_argument("--policy", action="store_true",
                        help="run the real policy engine against the "
                             "simulated fleet")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    n_ps = min(args.ps, args.pods)
    schedule = None
    if args.preemption_wave > 0:
        schedule = preemption_wave_schedule(
            args.pods, fraction=args.preemption_wave, seed=args.seed
        )
    elif args.kills or args.stragglers:
        schedule = churn_schedule(
            args.pods, kills=args.kills, stragglers=args.stragglers,
            seed=args.seed,
        )
    harness = FleetHarness(
        n_workers=args.pods - n_ps,
        n_ps=n_ps,
        mode=args.mode,
        tick_interval=args.tick_interval,
        push_interval=args.push_interval,
        schedule=schedule,
        seed=args.seed,
        lease_batch=args.lease_batch,
        policy=args.policy,
    )
    try:
        harness.start()
        harness.run(args.seconds)
        stats = harness.stats()
    finally:
        harness.stop()
    json.dump(stats, sys.stdout, indent=2, default=str)
    sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
