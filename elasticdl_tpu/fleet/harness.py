"""Fleet harness internals: SimPod, relay tree, FleetMaster, FleetHarness.

Execution model: N pods are sharded over a handful of carrier threads;
each carrier sweeps its pods once per tick interval. A pod tick is a few
registry mutations plus at most one task RPC — cheap enough that one
process carries 500 pods while the master under test does real work.
The master is real: a TaskDispatcher + MasterServicer behind rpc.serve,
a TelemetryAggregator ticked by its own thread, and a MetricsExporter
answering /api/summary, all on the process-default registry (which is
exactly where the edl_master_* control-plane series live).

Chaos: the harness asks the shared FaultSchedule once per pod per tick
with the synthetic method name "fleet.tick.pod-NNNN", so rules select
pods by method substring and windows count in ticks. `unavailable`
means dead for the window (pull mode leaves the advert behind — the
stale-endpoint path — and the pod relaunches after the window with a
new incarnation pid); `latency` inflates the pod's simulated step time
for the window (a straggler). Role-targeted rules don't apply here:
FaultRule.matches_role reads the process-global ELASTICDL_ROLE, and
every simulated pod shares this process.
"""

import json
import math
import os
import threading
import time
import random

from elasticdl_tpu.chaos.injection import FaultSchedule
from elasticdl_tpu.common import rpc
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.observability.aggregator import TelemetryAggregator
from elasticdl_tpu.observability.exporter import MetricsExporter
from elasticdl_tpu.observability.metrics import (
    MetricsRegistry,
    default_registry,
)
from elasticdl_tpu.observability.push import TelemetryPusher
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("fleet.harness")

# Simulated pods never collide with real pids: fake pid space starts at
# 10_000_000 + index * 1000 + incarnation.
_PID_BASE = 10_000_000


def pod_method_name(index):
    """The synthetic 'method' a pod's tick presents to the FaultSchedule
    (rules select pods by substring of this)."""
    return f"fleet.tick.pod-{index:04d}"


def churn_schedule(n_pods, kills=0, stragglers=0, start_tick=5,
                   window_ticks=6, straggler_factor=None, seed=0):
    """A seeded FaultSchedule for a fleet: `kills` pods go dead for
    `window_ticks` ticks (then relaunch), `stragglers` pods run slow for
    a window. Deterministic in (n_pods, counts, seed)."""
    rng = random.Random(seed)
    victims = rng.sample(range(n_pods), min(n_pods, kills + stragglers))
    rules = []
    for i, pod in enumerate(victims):
        kind = "unavailable" if i < kills else "latency"
        rules.append(
            {
                "method": f"pod-{pod:04d}",
                "kind": kind,
                "start": start_tick + rng.randrange(window_ticks),
                "count": window_ticks,
                "side": "client",
            }
        )
    return FaultSchedule(rules, seed=seed)


def preemption_wave_schedule(n_pods, fraction=0.2, at_tick=5,
                             window_ticks=6, seed=0):
    """A seeded FaultSchedule killing fraction*n_pods pods in ONE tick
    (the spot/maintenance preemption wave), all relaunching together
    after `window_ticks`. Deterministic in (n_pods, fraction, seed)."""
    rng = random.Random(seed)
    n_victims = max(1, int(round(n_pods * fraction)))
    victims = rng.sample(range(n_pods), min(n_pods, n_victims))
    rules = [
        {
            "method": f"pod-{pod:04d}",
            "kind": "unavailable",
            "start": at_tick,
            "count": window_ticks,
            "side": "client",
        }
        for pod in victims
    ]
    return FaultSchedule(rules, seed=seed)


class Relay:
    """One stage of the push-aggregation tree: buffers snapshots and
    forwards them to `sink` (another Relay's submit, or the root's RPC)
    once `batch` have gathered — callers also flush() on a cadence so a
    quiet subtree never strands a snapshot."""

    def __init__(self, sink, batch=16):
        self._sink = sink
        self._batch = max(1, batch)
        self._buf = []
        self._lock = threading.Lock()
        self.forwards = 0

    def submit(self, snapshots):
        flush_now = None
        with self._lock:
            self._buf.extend(snapshots)
            if len(self._buf) >= self._batch:
                flush_now, self._buf = self._buf, []
        if flush_now:
            self.forwards += 1
            self._sink(flush_now)

    def flush(self):
        with self._lock:
            pending, self._buf = self._buf, []
        if pending:
            self.forwards += 1
            self._sink(pending)


def build_relay_chain(report, n_leaves, fanout=16):
    """Relay levels for n_leaves pushers: leaves feed level-1 relays,
    each level batches `fanout` and feeds the next, the root forwards
    to `report` (the ReportTelemetry call). Depth is ceil(log_fanout n)
    — the O(log n) fan-in inversion. Returns (leaf_relays, all_relays);
    flush bottom-up via the `all_relays` list order."""
    fanout = max(2, fanout)
    levels = max(
        1, math.ceil(math.log(max(2, n_leaves), fanout))
    )
    all_relays = []
    root = Relay(report, batch=fanout)
    all_relays.append(root)
    current = [root]
    for _ in range(levels - 1):
        wanted = min(n_leaves, len(current) * fanout)
        nxt = [
            Relay(current[i % len(current)].submit, batch=fanout)
            for i in range(wanted)
        ]
        # Prepend: flushing all_relays in order must drain leaves first.
        all_relays[:0] = nxt
        current = nxt
    return current, all_relays


class SimPod:
    """One simulated worker or PS: a real registry with the families the
    aggregator derives from, plus the real task protocol for workers."""

    def __init__(self, index, role, harness, incarnation=0):
        self.index = index
        self.role = role
        self.is_worker = role.startswith("worker")
        self.harness = harness
        self.incarnation = incarnation
        self.pid = _PID_BASE + index * 1000 + incarnation
        self.alive = True
        self.straggler_factor = 1.0
        self.task_id = None
        self.leased = []  # batched-lease buffer (lease_batch > 1)
        self.unreported = []  # completed ids awaiting a batch report
        self.last_push = 0.0
        self._rng = random.Random(
            (harness.seed << 20) ^ (index << 4) ^ incarnation
        )
        self.registry = MetricsRegistry()
        if self.is_worker:
            self._h_phase = self.registry.histogram(
                "edl_phase_seconds",
                "Worker phase latency",
                labelnames=("phase",),
            )
            self._c_steps = self.registry.counter(
                "edl_steps_total", "Steps simulated"
            )
            # Data-plane families, same shapes as observability.datapath
            # (the metric-names lint enforces one shape per name): the
            # simulated feed path splits each step into read/decode with
            # a small starve tail, so the aggregator's datapath rollup
            # has fleet-scale input to derive from.
            self._c_dp_seconds = self.registry.counter(
                "edl_datapath_seconds_total",
                "Input pipeline time by stage (simulated)",
                labelnames=("stage",),
            )
            self._c_dp_records = self.registry.counter(
                "edl_datapath_records_total",
                "Records delivered to the training loop (simulated)",
            )
            self._g_dp_queue = self.registry.gauge(
                "edl_datapath_queue_depth",
                "Bounded feed queue occupancy (simulated)",
                labelnames=("queue",),
            )
        else:
            # Same labelnames as the real PS servicer: pods share no
            # registry with it, but the aggregator's per-shard derive
            # (and the metric-names lint) expects one shape per metric.
            self._c_push_b = self.registry.counter(
                "edl_ps_push_bytes_total",
                "Gradient push request bytes received, by shard",
                labelnames=("shard",),
            )
            self._c_pull_b = self.registry.counter(
                "edl_ps_pull_bytes_total",
                "Parameter/embedding pull response bytes sent",
                labelnames=("rpc", "shard"),
            )
        self.exporter = None
        self.pusher = None
        if harness.mode == "pull":
            self.exporter = MetricsExporter(
                self.registry, port=0, host="127.0.0.1"
            )
            self._advertise()
        else:
            self.pusher = TelemetryPusher(
                self.registry,
                self.role,
                full_every=harness.push_full_every,
            )
            # object identity is not enough once a pod relaunches: the
            # pusher's pid must track the incarnation.
            self.pusher.pid = self.pid

    # -- endpoint advertisement (pull mode), mirrors observability.setup --

    def _advert_path(self):
        return os.path.join(
            self.harness.endpoints_dir, f"{self.role}.json"
        )

    def _advertise(self):
        os.makedirs(self.harness.endpoints_dir, exist_ok=True)
        info = {
            "role": self.role,
            "job": self.harness.job,
            "pid": self.pid,
            "port": self.exporter.port,
            "host": "127.0.0.1",
        }
        tmp = f"{self._advert_path()}.tmp.{self.pid}"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, self._advert_path())

    # -- lifecycle (chaos) --

    def kill(self):
        """SIGKILL semantics: the endpoint dies, the advert survives —
        exactly the stale-endpoint case the aggregator must absorb."""
        self.alive = False
        self.task_id = None
        self.leased = []
        self.unreported = []
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None

    def relaunch(self):
        """Come back as a fresh incarnation (new pid, empty registry) —
        the advert rewrite is what flips the endpoints-dir mtime."""
        self.__init__(
            self.index,
            self.role,
            self.harness,
            incarnation=self.incarnation + 1,
        )

    def close(self):
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None
        # Clean leave withdraws the advert (observability.close parity).
        if self.harness.mode == "pull":
            try:
                os.unlink(self._advert_path())
            except OSError:
                pass

    # -- one scheduler tick --

    def tick(self, now):
        if not self.alive:
            return
        step = self.harness.base_step_s * self.straggler_factor
        if self.is_worker:
            # Simulated work: the histogram moves like a real worker's,
            # no wall-clock is actually burned.
            draw = max(
                1e-4, self._rng.gauss(step, 0.15 * step)
            )
            self._h_phase.labels(phase="batch_process").observe(draw)
            self._c_steps.inc()
            # Feed-path attribution moves with the step: a straggler's
            # slowdown surfaces as starve seconds (its feed can't keep
            # up), which is exactly what the starvation alert watches.
            self._c_dp_seconds.labels(stage="read").inc(0.25 * draw)
            self._c_dp_seconds.labels(stage="decode").inc(0.15 * draw)
            starve = max(0.0, (self.straggler_factor - 1.0) * step)
            if starve:
                self._c_dp_seconds.labels(stage="starve").inc(starve)
            self._c_dp_records.inc(64)
            self._g_dp_queue.labels(queue="prefetch").set(
                self._rng.randint(0, 64)
            )
            self._task_rpc()
        else:
            shard = str(self.index)
            self._c_push_b.labels(shard=shard).inc(
                int(self._rng.uniform(0.5, 1.5) * 65536)
            )
            self._c_pull_b.labels(
                rpc="pull_parameters", shard=shard
            ).inc(int(self._rng.uniform(0.5, 1.5) * 65536))
        if self.pusher is not None and (
            now - self.last_push
            >= self.harness.push_interval
            * self._rng.uniform(0.9, 1.1)
        ):
            self.last_push = now
            self.harness.submit_push(self, self.pusher.snapshot())

    def _task_rpc(self):
        if self.harness.lease_batch > 1:
            return self._task_rpc_batched()
        stub = self.harness.stub
        try:
            if self.task_id is None:
                res = stub.get_task(
                    pb.GetTaskRequest(worker_id=self.index)
                )
                if res.task_id >= 0 and res.type != pb.WAIT:
                    self.task_id = res.task_id
                    self.harness.count("dispatched")
            else:
                stub.report_task_result(
                    pb.ReportTaskResultRequest(task_id=self.task_id)
                )
                self.task_id = None
                self.harness.count("reported")
        except Exception:
            self.harness.count("rpc_errors")

    def _task_rpc_batched(self):
        """Batched lease protocol, still at most ONE task RPC per tick:
        an empty buffer refills with get_task_batch; otherwise one task
        'completes' per tick and a full unreported buffer flushes as one
        report_task_results — so each RPC moves lease_batch tasks."""
        stub = self.harness.stub
        batch = self.harness.lease_batch
        try:
            if self.unreported and (
                len(self.unreported) >= batch or not self.leased
            ):
                req = pb.ReportTaskResultsRequest()
                for tid in self.unreported:
                    req.results.add(task_id=tid)
                stub.report_task_results(req)
                self.harness.count("reported", len(self.unreported))
                self.unreported = []
            elif not self.leased:
                res = stub.get_task_batch(
                    pb.GetTaskRequest(
                        worker_id=self.index, max_tasks=batch
                    )
                )
                if res.tasks:
                    self.leased = [t.task_id for t in res.tasks]
                    self.harness.count("dispatched", len(res.tasks))
            else:
                self.unreported.append(self.leased.pop(0))
        except Exception:
            self.harness.count("rpc_errors")


class FleetMaster:
    """The real master control plane under test: dispatcher + servicer
    behind gRPC, aggregator, /api/summary exporter."""

    def __init__(self, obs_dir, job="fleet", n_records=1 << 20,
                 records_per_task=64, interval=0.5, policy=False,
                 policy_kwargs=None, journal_dir=None,
                 snapshot_every=None):
        self.job = job
        self.task_d = TaskDispatcher(
            {"fleet": (0, n_records)},
            records_per_task=records_per_task,
            # The harness measures steady-state dispatch, not job
            # completion: enough epochs that the queue never drains.
            num_epochs=1_000_000,
            shuffle=False,
        )
        # Optional journal plane, wired exactly like the real Master:
        # restore-then-attach, providers registered before the
        # snapshot-on-start, incarnation bumped on recovery. This is what
        # the fleet-scale master-restart drill exercises.
        self.master_incarnation = 1
        self.journal = None
        if journal_dir:
            from elasticdl_tpu.master.journal import MasterJournal

            self.journal = MasterJournal(
                journal_dir, snapshot_every=snapshot_every
            )
            state = self.journal.load()
            if state["incarnation"] > 0:
                self.master_incarnation = state["incarnation"] + 1
                self.task_d.restore_state(state)
            self.task_d.attach_journal(self.journal)
            self.journal.add_state_provider(self.task_d.export_state)
            self.journal.add_state_provider(
                lambda: {"incarnation": self.master_incarnation}
            )
            self.journal.record(
                {"op": "incarnation", "value": self.master_incarnation}
            )
            self.journal.compact()
        self.servicer = MasterServicer(self.task_d)
        self._server, self.port = rpc.serve(
            self.servicer, rpc.MASTER_SERVICE, port=0
        )
        self.aggregator = TelemetryAggregator(
            obs_dir,
            registry=default_registry(),
            job=job,
            interval=interval,
        )
        self.policy = None
        self.world_hints = None
        if policy:
            # The REAL policy engine against the simulated fleet: same
            # summary input, same dispatcher actuators. No instance
            # manager (pods aren't processes), so the straggler rule's
            # blacklist+recover applies while restart/scale no-op. The
            # harness master loop ticks it synchronously — deterministic
            # decision timing instead of a second clock.
            from elasticdl_tpu.master.policy import (
                PolicyEngine,
                WorldHintBoard,
            )

            self.world_hints = WorldHintBoard()
            self.policy = PolicyEngine(
                self.aggregator.summary,
                self.task_d,
                world_hints=self.world_hints,
                **(policy_kwargs or {}),
            )
        self.servicer.bind_job_context(
            aggregator=self.aggregator,
            policy=self.policy,
            world_hints=self.world_hints,
            master_incarnation=self.master_incarnation,
        )
        self.exporter = MetricsExporter(
            default_registry(), port=0, host="127.0.0.1"
        )
        self.exporter.summary_provider = self._summary

    def _summary(self):
        summary = self.aggregator.summary()
        if self.policy is not None:
            summary["policy"] = self.policy.summary()
        return summary

    def close(self, crash=False):
        """Tear down; crash=True models SIGKILL — the gRPC server dies but
        the journal is NOT cleanly closed (no final snapshot), so whatever
        the WAL tail holds is exactly what a relaunch replays."""
        self.exporter.close()
        self.aggregator.close()
        stopped = self._server.stop(0 if crash else 1)
        if self.journal is not None and not crash:
            self.journal.close()
        return stopped


class FleetHarness:
    """N simulated pods + one real master, swept by carrier threads."""

    def __init__(self, n_workers=50, n_ps=0, obs_dir=None, mode="push",
                 tick_interval=0.25, push_interval=0.5,
                 push_full_every=16, relay_fanout=16, schedule=None,
                 seed=0, carriers=8, base_step_s=0.05,
                 aggregator_interval=0.5, job="fleet", lease_batch=1,
                 policy=False, policy_kwargs=None, journal_dir=None,
                 master_snapshot_every=None):
        assert mode in ("push", "pull"), mode
        if obs_dir is None:
            import tempfile

            obs_dir = tempfile.mkdtemp(prefix="edl-fleet-")
        self.obs_dir = obs_dir
        self.endpoints_dir = os.path.join(obs_dir, "endpoints")
        self.mode = mode
        self.job = job
        self.tick_interval = tick_interval
        self.push_interval = push_interval
        self.push_full_every = push_full_every
        self.base_step_s = base_step_s
        self.schedule = schedule
        self.seed = seed
        self.n_workers = n_workers
        self.n_ps = n_ps
        self.lease_batch = max(1, lease_batch)
        self._policy = policy
        self._policy_kwargs = policy_kwargs
        self._journal_dir = journal_dir
        self._master_snapshot_every = master_snapshot_every
        self.policy_decisions = []
        self._n_carriers = max(1, min(carriers, n_workers + n_ps))
        self._relay_fanout = relay_fanout
        self._agg_interval = aggregator_interval
        self._counts = {
            "dispatched": 0,
            "reported": 0,
            "rpc_errors": 0,
            "kills": 0,
            "relaunches": 0,
            "straggler_ticks": 0,
            "pushes": 0,
            "push_batches": 0,
            "need_full": 0,
        }
        self._count_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = []
        self.master = None
        self.stub = None
        self.pods = []
        self._leaf_relays = []
        self._all_relays = []
        self.master_tick_seconds = []
        self.ticks = 0

    # -- shared accounting --

    def count(self, key, n=1):
        with self._count_lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def submit_push(self, pod, snapshot):
        self.count("pushes")
        if self._leaf_relays:
            relay = self._leaf_relays[
                pod.index % len(self._leaf_relays)
            ]
            relay.submit([snapshot])
        else:
            self._report_batch([snapshot])

    def _report_batch(self, snapshots):
        self.count("push_batches")
        try:
            req = pb.ReportTelemetryRequest(origin="fleet-relay")
            for snap in snapshots:
                req.snapshots.add(**snap)
            resp = self.stub.report_telemetry(req)
        except Exception:
            self.count("rpc_errors")
            return
        for role in resp.need_full:
            self.count("need_full")
            pod = self._pods_by_role.get(role)
            if pod is not None and pod.pusher is not None:
                pod.pusher.reset()

    # -- lifecycle --

    def start(self):
        if self.mode == "pull":
            self._raise_nofile(self.n_workers + self.n_ps)
        self.master = FleetMaster(
            self.obs_dir,
            job=self.job,
            interval=self._agg_interval,
            policy=self._policy,
            policy_kwargs=self._policy_kwargs,
            journal_dir=self._journal_dir,
            snapshot_every=self._master_snapshot_every,
        )
        self._channel = rpc.build_channel(f"127.0.0.1:{self.master.port}")
        self.stub = rpc.Stub(self._channel, rpc.MASTER_SERVICE)
        self.pods = [
            SimPod(i, f"worker-{i}", self)
            for i in range(self.n_workers)
        ] + [
            SimPod(self.n_workers + j, f"ps-{j}", self)
            for j in range(self.n_ps)
        ]
        self._pods_by_role = {p.role: p for p in self.pods}
        if self.mode == "push":
            self._leaf_relays, self._all_relays = build_relay_chain(
                self._report_batch,
                len(self.pods),
                fanout=self._relay_fanout,
            )
        for c in range(self._n_carriers):
            t = threading.Thread(
                target=self._carrier,
                args=(self.pods[c::self._n_carriers],),
                name=f"fleet-carrier-{c}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        t = threading.Thread(
            target=self._master_loop, name="fleet-master-tick",
            daemon=True,
        )
        t.start()
        self._threads.append(t)
        self._started_at = time.monotonic()
        return self

    @staticmethod
    def _raise_nofile(n_pods):
        # ~3 fds per pull exporter (listen socket + transient accepts):
        # bump the soft limit toward the hard one when 500 pods need it.
        try:
            import resource

            soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
            want = n_pods * 4 + 512
            if soft < want:
                resource.setrlimit(
                    resource.RLIMIT_NOFILE, (min(want, hard), hard)
                )
        except (ImportError, ValueError, OSError):
            pass

    def _carrier(self, pods):
        while not self._stop.is_set():
            sweep_start = time.monotonic()
            now = time.time()
            for pod in pods:
                self._apply_chaos(pod)
                pod.tick(now)
                if self._stop.is_set():
                    return
            # The carrier owning pod 0 flushes the relay tree once per
            # sweep (bottom-up: build_relay_chain orders leaves first)
            # so buffered snapshots never outlive a tick.
            if pods and pods[0].index == 0:
                for relay in self._all_relays:
                    relay.flush()
            elapsed = time.monotonic() - sweep_start
            self._stop.wait(max(0.005, self.tick_interval - elapsed))

    def _apply_chaos(self, pod):
        if self.schedule is None:
            faults = ()
        else:
            faults = self.schedule.decide(
                pod_method_name(pod.index), "client"
            )
        dead = any(r.kind == "unavailable" for r in faults)
        slow = any(r.kind == "latency" for r in faults)
        if dead and pod.alive:
            pod.kill()
            self.count("kills")
        elif not dead and not pod.alive:
            pod.relaunch()
            self.count("relaunches")
        if slow and pod.alive:
            pod.straggler_factor = 4.0
            self.count("straggler_ticks")
        elif pod.alive:
            pod.straggler_factor = 1.0

    def _master_loop(self):
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                self.master.aggregator.poll_once()
                if self.master.policy is not None:
                    # Policy rides the same tick as the aggregator:
                    # decisions follow directly from the rollup the tick
                    # just produced (deterministic causality for tests).
                    self.policy_decisions.extend(
                        self.master.policy.tick()
                    )
                if self.master.journal is not None:
                    # Journal maintenance outside every dispatcher/
                    # provider lock — same placement rule as the real
                    # master's watchdog tick (MasterJournal.maybe_compact).
                    self.master.journal.maybe_compact()
            except Exception:
                logger.warning("fleet master tick failed", exc_info=True)
            self.master_tick_seconds.append(time.perf_counter() - t0)
            self.ticks += 1
            self._stop.wait(self._agg_interval)

    def run(self, seconds):
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline and not self._stop.is_set():
            time.sleep(0.05)
        return self

    def restart_master(self):
        """Kill the master mid-run (SIGKILL semantics: no journal close,
        no final snapshot) and bring up a replacement over the same
        journal dir. Pods keep ticking throughout — their RPCs against
        the dead endpoint land in rpc_errors, exactly like a real
        restart — and the harness re-points its shared stub at the new
        port once replay finishes. Requires journal_dir (a journal-less
        master would come back with an empty queue and re-dispatch
        everything)."""
        assert self._journal_dir, "restart_master needs journal_dir"
        old = self.master
        stopped = old.close(crash=True)
        # Let in-flight handlers drain so the old journal handle cannot
        # interleave a final append with the successor's WAL writes.
        stopped.wait(timeout=10.0)
        old.journal.close()
        self.count("master_restarts")
        self.master = FleetMaster(
            self.obs_dir,
            job=self.job,
            interval=self._agg_interval,
            policy=self._policy,
            policy_kwargs=self._policy_kwargs,
            journal_dir=self._journal_dir,
            snapshot_every=self._master_snapshot_every,
        )
        old_channel = self._channel
        self._channel = rpc.build_channel(
            f"127.0.0.1:{self.master.port}"
        )
        self.stub = rpc.Stub(self._channel, rpc.MASTER_SERVICE)
        if old_channel is not None:
            old_channel.close()
        return self.master

    def stats(self):
        with self._count_lock:
            counts = dict(self._counts)
        summary = (
            self.master.aggregator.summary() if self.master else {}
        )
        ticks = sorted(self.master_tick_seconds)
        elapsed = time.monotonic() - getattr(
            self, "_started_at", time.monotonic()
        )
        out = {
            "mode": self.mode,
            "pods": len(self.pods),
            "counts": counts,
            "lease_batch": self.lease_batch,
            "dispatch_tasks_per_s": (
                counts.get("reported", 0) / elapsed if elapsed > 0 else 0.0
            ),
            "master_ticks": len(ticks),
            "master_tick_p50_s": ticks[len(ticks) // 2] if ticks else None,
            "master_tick_max_s": ticks[-1] if ticks else None,
            "fleet": summary.get("fleet") or {},
            "datapath": summary.get("datapath") or {},
            "roles_scraped": len(summary.get("roles_scraped") or ()),
            "summary_ts": summary.get("ts"),
        }
        if self.master is not None and self.master.policy is not None:
            out["policy"] = self.master.policy.summary()
            out["policy_decisions"] = list(self.policy_decisions)
        return out

    def fetch_summary_http(self):
        """GET the master's /api/summary over real HTTP (render cost
        included) — the bench's summary-render probe."""
        import urllib.request

        url = (
            f"http://127.0.0.1:{self.master.exporter.port}/api/summary"
        )
        with urllib.request.urlopen(url, timeout=5.0) as res:
            return json.loads(res.read().decode())

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []
        # Exporter shutdown blocks up to the HTTP server's poll
        # interval; serially that makes a 500-pod pull fleet take
        # minutes to tear down. Close in parallel.
        if self.pods:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=64) as pool:
                list(pool.map(lambda p: p.close(), self.pods))
        if self.master is not None:
            self.master.close()
            self.master = None
        if getattr(self, "_channel", None) is not None:
            self._channel.close()
            self._channel = None
