"""Simulated-fleet harness: hundreds of lightweight pods from one process.

Real 500-pod jobs don't fit CI, but the master's control plane must be
measured at that scale — task-dispatch latency, scrape fan-out cost,
telemetry freshness, endpoint bookkeeping. This package fakes the POD
(no jax, no training, a few hundred bytes of state each) while keeping
every PROTOCOL real: simulated workers pull tasks and report results
over actual gRPC against a real TaskDispatcher + MasterServicer,
publish real MetricsRegistry families, and either expose genuine
/metrics HTTP endpoints with endpoint-advert files (pull mode) or push
delta-encoded snapshots through a relay tree into the ReportTelemetry
RPC (push mode). Churn — kill/leave/rejoin, stragglers — is scripted
through the existing chaos FaultSchedule so runs replay exactly.

    from elasticdl_tpu.fleet import FleetHarness
    h = FleetHarness(n_workers=200, n_ps=20, mode="push")
    h.start(); h.run(10.0); stats = h.stats(); h.stop()

`python -m elasticdl_tpu.fleet --pods 200 --seconds 10` runs one from
the command line and prints the stats dict.
"""

from elasticdl_tpu.fleet.harness import (  # noqa: F401
    FleetHarness,
    FleetMaster,
    Relay,
    SimPod,
    build_relay_chain,
    churn_schedule,
    preemption_wave_schedule,
)
