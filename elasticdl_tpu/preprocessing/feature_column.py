"""Feature columns: declarative spec -> dense input tensor.

Reference counterparts: the EDL embedding feature column
(/root/reference/elasticdl/python/elasticdl/feature_column/
feature_column.py:25-221) and the preprocessing package's embedding_column
(elasticdl_preprocessing/feature_column/feature_column.py).

TPU-first redesign: columns are plain dataclass specs lowered by ONE flax
module (`DenseFeatures`) into gathers/one-hots/concats that XLA fuses.
`embedding_column` lowers to a stock `nn.Embed`, which means the
ModelHandler (common/model_handler.py) transparently swaps any table over
the 2 MB threshold to the parameter server under the PS strategy — the
same "feature columns leverage the PS iff the table is big" behavior the
reference implements with a custom TF EmbeddingColumn, with zero custom
lookup code here.

Categorical transforms (hashing, vocab lookup) reuse the preprocessing
layers; statistics-driven defaults come from analyzer_utils (env vars).
"""

import dataclasses
import functools
import math

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.preprocessing.layers import IndexLookup, _stable_hash64


@dataclasses.dataclass(frozen=True)
class NumericColumn:
    key: str
    shape: tuple = (1,)
    normalizer_fn: object = None  # callable array -> array


@dataclasses.dataclass(frozen=True)
class IdentityCategoricalColumn:
    key: str
    num_buckets: int


@dataclasses.dataclass(frozen=True)
class HashedCategoricalColumn:
    key: str
    hash_bucket_size: int


@dataclasses.dataclass(frozen=True)
class VocabularyCategoricalColumn:
    key: str
    vocabulary: tuple
    num_oov_indices: int = 1

    @property
    def num_buckets(self):
        return len(self.vocabulary) + self.num_oov_indices


@dataclasses.dataclass(frozen=True)
class BucketizedColumn:
    key: str
    boundaries: tuple

    @property
    def num_buckets(self):
        return len(self.boundaries) + 1


@dataclasses.dataclass(frozen=True)
class EmbeddingColumn:
    categorical: object
    dimension: int
    combiner: str = "mean"
    initializer_stddev: float = None  # default 1/sqrt(dim)


@dataclasses.dataclass(frozen=True)
class IndicatorColumn:
    categorical: object


def numeric_column(key, shape=(1,), normalizer_fn=None):
    return NumericColumn(key, tuple(shape), normalizer_fn)


def categorical_column_with_identity(key, num_buckets):
    return IdentityCategoricalColumn(key, num_buckets)


def categorical_column_with_hash_bucket(key, hash_bucket_size):
    return HashedCategoricalColumn(key, hash_bucket_size)


def categorical_column_with_vocabulary_list(
    key, vocabulary, num_oov_indices=1
):
    return VocabularyCategoricalColumn(
        key, tuple(vocabulary), num_oov_indices
    )


def bucketized_column(key, boundaries):
    """Numeric -> bucket id by boundaries. Pure in-graph (searchsorted),
    so it needs no host-side preprocess step."""
    return BucketizedColumn(key, tuple(sorted(boundaries)))


def embedding_column(
    categorical, dimension, combiner="mean", initializer_stddev=None
):
    """PS-aware embedding column: the table lives in params for small
    vocabs and is auto-swapped to the PS when it exceeds the ModelHandler
    threshold (reference feature_column.py:25-221 semantics)."""
    if dimension is None or dimension < 1:
        raise ValueError(f"invalid embedding dimension {dimension}")
    return EmbeddingColumn(categorical, dimension, combiner,
                           initializer_stddev)


def indicator_column(categorical):
    return IndicatorColumn(categorical)


def _bucket_count(categorical):
    if isinstance(categorical, HashedCategoricalColumn):
        return categorical.hash_bucket_size
    return categorical.num_buckets


def _is_int_array(raw):
    dtype = getattr(raw, "dtype", None)
    return dtype is not None and np.issubdtype(
        np.dtype(str(dtype)), np.integer
    )


def _jnp_int_hash(ids):
    """In-graph 32-bit finalizer (lowbias32): decorrelates raw integer ids
    before the bucket modulo, like the host-side Hashing layer does for
    strings. Pure jnp, so hashed columns with integer inputs work inside
    jit."""
    x = jnp.asarray(ids).astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _categorical_ids(categorical, features):
    """Column spec + feature batch -> int id array.

    Hashing/vocab transforms run on STRINGS and therefore on the host —
    under jit, call `DenseFeatures.preprocess` in the data feed first
    (it replaces those keys with int ids, which pass through here as
    identity). Bucketize/identity lower to pure in-graph ops."""
    raw = features[categorical.key]
    if isinstance(categorical, BucketizedColumn):
        return jnp.searchsorted(
            jnp.asarray(categorical.boundaries, jnp.float32),
            jnp.asarray(raw, jnp.float32),
            side="right",
        ).astype(jnp.int32)
    if isinstance(categorical, HashedCategoricalColumn):
        if not (_is_int_array(raw) or isinstance(raw, jnp.ndarray)):
            # Strings reduce to raw 63-bit hashes host-side (same step
            # preprocess() performs), so every input path runs EXACTLY one
            # in-graph mix+modulo below.
            arr = np.asarray(raw)
            raw = np.asarray(
                [
                    _stable_hash64(s) & 0x7FFFFFFFFFFFFFFF
                    for s in arr.reshape(-1)
                ],
                np.int64,
            ).reshape(arr.shape)
        # Integer ids are NOT assumed pre-bucketed (a raw Criteo id can be
        # millions): mix + modulo in-graph.
        return (
            _jnp_int_hash(raw) % jnp.uint32(categorical.hash_bucket_size)
        ).astype(jnp.int32)
    if isinstance(categorical, IdentityCategoricalColumn) or _is_int_array(
        raw
    ):
        return jnp.asarray(raw, jnp.int32)
    if isinstance(categorical, VocabularyCategoricalColumn):
        return jnp.asarray(
            _lookup_for(categorical)(np.asarray(raw)), jnp.int32
        )
    raise TypeError(f"not a categorical column: {categorical!r}")


@functools.lru_cache(maxsize=256)
def _lookup_for(categorical):
    """One IndexLookup per frozen column spec — preprocess runs per batch
    in the feed hot path and must not rebuild the vocab dict each call."""
    return IndexLookup(
        list(categorical.vocabulary),
        num_oov_indices=categorical.num_oov_indices,
    )


def _walk_categoricals(columns):
    for col in columns:
        if isinstance(col, (EmbeddingColumn, IndicatorColumn)):
            yield col.categorical


def _combine(embedded, combiner):
    if embedded.ndim == 2:  # single id per example: nothing to combine
        return embedded
    if combiner == "sum":
        return jnp.sum(embedded, axis=-2)
    if combiner == "mean":
        return jnp.mean(embedded, axis=-2)
    if combiner == "sqrtn":
        n = embedded.shape[-2]
        return jnp.sum(embedded, axis=-2) / math.sqrt(n)
    raise ValueError(f"unknown combiner {combiner!r}")


class DenseFeatures(nn.Module):
    """Lowers a list of column specs against a feature dict into one dense
    [batch, total_width] tensor (the tf.keras DenseFeatures analog).

    String-keyed transforms (hash buckets, vocabulary lookups) cannot run
    inside a compiled step: call `preprocess(features)` in the data feed
    (host side) — it replaces those keys with int id arrays — and the
    module's in-graph `__call__` handles the rest."""

    columns: tuple

    def preprocess(self, features):
        """Host-side transform pass: hash/vocab string columns -> int id
        arrays under the same keys. Safe to call on already-transformed
        batches (int inputs pass through)."""
        out = dict(features)
        for cat in _walk_categoricals(self.columns):
            raw = out.get(cat.key)
            if raw is None or _is_int_array(raw):
                continue
            if isinstance(cat, HashedCategoricalColumn):
                # Strings become RAW 63-bit hashes, NOT bucket ids: the
                # in-graph mix+modulo does the single bucketing step, so
                # values never get hashed twice (double-hashing collapses
                # buckets).
                arr = np.asarray(raw)
                out[cat.key] = np.asarray(
                    [
                        _stable_hash64(s) & 0x7FFFFFFFFFFFFFFF
                        for s in arr.reshape(-1)
                    ],
                    np.int64,
                ).reshape(arr.shape)
            elif isinstance(cat, VocabularyCategoricalColumn):
                out[cat.key] = np.asarray(
                    _lookup_for(cat)(np.asarray(raw))
                )
        return out

    @nn.compact
    def __call__(self, features):
        pieces = []
        for col in self.columns:
            if isinstance(col, NumericColumn):
                value = jnp.asarray(features[col.key], jnp.float32)
                if col.normalizer_fn is not None:
                    value = col.normalizer_fn(value)
                pieces.append(value.reshape(value.shape[0], -1))
            elif isinstance(col, EmbeddingColumn):
                ids = _categorical_ids(col.categorical, features)
                # Gather semantics for out-of-range ids: clamp explicitly
                # (XLA would clamp anyway; the TF column raises, which a
                # compiled step cannot). Indicator columns below instead
                # keep one_hot's drop-to-zero-row behavior.
                ids = jnp.clip(
                    ids, 0, _bucket_count(col.categorical) - 1
                )
                stddev = col.initializer_stddev or (
                    1.0 / math.sqrt(col.dimension)
                )
                table = nn.Embed(
                    num_embeddings=_bucket_count(col.categorical),
                    features=col.dimension,
                    embedding_init=nn.initializers.truncated_normal(
                        stddev
                    ),
                    name=f"emb_{col.categorical.key}",
                )
                embedded = _combine(table(ids), col.combiner)
                pieces.append(embedded.reshape(embedded.shape[0], -1))
            elif isinstance(col, IndicatorColumn):
                ids = _categorical_ids(col.categorical, features)
                # Multi-hot over the bucket count (multivalent ids sum).
                one_hot = jax.nn.one_hot(
                    ids.reshape(ids.shape[0], -1),
                    _bucket_count(col.categorical),
                    dtype=jnp.float32,
                )
                pieces.append(jnp.sum(one_hot, axis=1))
            else:
                raise TypeError(f"unsupported column {col!r}")
        return jnp.concatenate(pieces, axis=-1)
