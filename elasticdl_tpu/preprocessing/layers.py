"""The 11 preprocessing transforms, as dense/padded-dense pure functions.

Reference counterparts under /root/reference/elasticdl_preprocessing/layers/
(per-class citations below). Sparse/Ragged input branches of the reference
become the (values, mask) padded-dense form: XLA needs static shapes, so
"missing" positions are padding ids masked out of combiners instead of
absent coordinates.

Every class is stateless and callable on numpy or jnp arrays; use them in
`feed` (host-side, numpy) or inside flax modules (traced).
"""

import dataclasses
import hashlib

import jax.numpy as jnp
import numpy as np

import flax.linen as nn


def _stable_hash64(s) -> int:
    """Process-independent 64-bit hash of a string/bytes token. Used for
    every host-side string bucketing decision (Hashing, IndexLookup OOV) so
    the same token lands in the same bucket on every worker and across
    restarts — Python's builtin hash() is randomized per process."""
    if isinstance(s, bytes):
        s = s.decode("utf-8", "ignore")
    elif not isinstance(s, str):
        s = str(s)
    return int.from_bytes(
        hashlib.sha256(s.encode("utf-8")).digest()[:8], "little"
    )


@dataclasses.dataclass
class PaddedFeature:
    """Padded-dense stand-in for the reference's Sparse/RaggedTensor inputs:
    `values` [batch, max_len] and boolean `mask` [batch, max_len] (True =
    real element)."""

    values: object
    mask: object


def to_padded(list_of_lists, max_len=None, pad_value=0, dtype=np.int64):
    """The ToRagged/ToSparse analog (/root/reference/elasticdl_preprocessing/
    layers/to_ragged.py, to_sparse.py): variable-length python/numpy rows ->
    PaddedFeature with static [batch, max_len] shape."""
    if max_len is None:
        max_len = max((len(r) for r in list_of_lists), default=0) or 1
    n = len(list_of_lists)
    values = np.full((n, max_len), pad_value, dtype=dtype)
    mask = np.zeros((n, max_len), dtype=bool)
    for i, row in enumerate(list_of_lists):
        row = list(row)[:max_len]
        values[i, : len(row)] = row
        mask[i, : len(row)] = True
    return PaddedFeature(values=values, mask=mask)


def _xp(x):
    return jnp if isinstance(x, jnp.ndarray) else np


def _map_values(fn, inputs):
    if isinstance(inputs, PaddedFeature):
        return PaddedFeature(values=fn(inputs.values), mask=inputs.mask)
    return fn(inputs)


class ToNumber:
    """Strings/bytes -> numbers (reference to_number.py). Host-side only
    (strings never reach the device)."""

    def __init__(self, out_type=np.float32, default_value=0):
        self.out_type = out_type
        self.default_value = default_value

    def __call__(self, inputs):
        def convert(arr):
            flat = []
            for x in np.asarray(arr).reshape(-1):
                if isinstance(x, bytes):
                    x = x.decode("utf-8", "ignore")
                try:
                    flat.append(self.out_type(x))
                except (TypeError, ValueError):
                    flat.append(self.out_type(self.default_value))
            return np.asarray(flat, self.out_type).reshape(
                np.asarray(arr).shape
            )

        return _map_values(convert, inputs)


class RoundIdentity:
    """round() + clip to [0, num_buckets) (reference round_identity.py:18-61).
    """

    def __init__(self, num_buckets, default_value=0):
        self.num_buckets = num_buckets
        self.default_value = default_value

    def __call__(self, inputs):
        def fn(x):
            xp = _xp(x)
            out = xp.clip(xp.round(x), 0, self.num_buckets - 1)
            return out.astype(xp.int64 if xp is np else jnp.int64)

        return _map_values(fn, inputs)


class LogRound:
    """round(log_base(x)) clipped to [0, num_bins) (reference
    log_round.py:29-75)."""

    def __init__(self, num_bins, default_value=0, base=None):
        self.num_bins = num_bins
        self.base = base
        self.default_value = default_value

    def __call__(self, inputs):
        def fn(x):
            xp = _xp(x)
            safe = xp.maximum(x, 1e-12)
            logged = xp.log(safe)
            if self.base is not None:
                logged = logged / np.log(self.base)
            out = xp.clip(xp.round(logged), 0, self.num_bins - 1)
            return out.astype(xp.int64 if xp is np else jnp.int64)

        return _map_values(fn, inputs)


class Hashing:
    """Deterministic hash of values into [0, num_bins) (reference
    hashing.py: strings via to_hash_bucket_fast; here a splitmix64-style
    integer mix, identical across host/device)."""

    def __init__(self, num_bins):
        if num_bins < 1:
            raise ValueError("num_bins must be >= 1")
        self.num_bins = num_bins

    def __call__(self, inputs):
        def fn(x):
            if isinstance(x, np.ndarray) and x.dtype.kind in ("U", "S", "O"):
                flat = np.asarray(
                    [
                        _stable_hash64(s) % self.num_bins
                        for s in x.reshape(-1)
                    ],
                    np.int64,
                )
                return flat.reshape(x.shape)
            xp = _xp(x)
            # murmur3 fmix32 in uint32: identical on host numpy and on
            # device (jax defaults to 32-bit ints; uint64 would silently
            # truncate there). 64-bit host ids fold hi^lo into 32 bits
            # first — same result for any id the device could represent.
            if xp is np:
                wide = x.astype(np.uint64)
                z = ((wide & np.uint64(0xFFFFFFFF)) ^ (wide >> 32)).astype(
                    np.uint32
                )
            else:
                z = x.astype(jnp.uint32)
            c1, c2 = np.uint32(0x85EBCA6B), np.uint32(0xC2B2AE35)
            z = z ^ (z >> 16)
            z = z * c1
            z = z ^ (z >> 13)
            z = z * c2
            z = z ^ (z >> 16)
            return (z % np.uint32(self.num_bins)).astype(
                jnp.int32 if xp is jnp else np.int64
            )

        return _map_values(fn, inputs)


class Discretization:
    """Bucketize by boundaries: output in [0, len(bins)] (reference
    discretization.py)."""

    def __init__(self, bins):
        self.bins = np.asarray(sorted(bins), np.float64)

    def __call__(self, inputs):
        def fn(x):
            xp = _xp(x)
            bins = self.bins if xp is np else jnp.asarray(self.bins)
            out = (
                np.digitize(x, bins)
                if xp is np
                else jnp.digitize(x, bins)
            )
            return out.astype(np.int64 if xp is np else jnp.int64)

        return _map_values(fn, inputs)


class IndexLookup:
    """Vocabulary -> index; OOV maps to len(vocab) (reference
    index_lookup.py: lookup table with num_oov_indices=1). Host-side (string
    keys)."""

    def __init__(self, vocabulary, num_oov_indices=1):
        if isinstance(vocabulary, str):
            with open(vocabulary) as f:
                vocabulary = [line.rstrip("\n") for line in f if line.strip()]
        self.vocab = {v: i for i, v in enumerate(vocabulary)}
        self.num_oov_indices = max(1, num_oov_indices)

    def vocab_size(self):
        return len(self.vocab) + self.num_oov_indices

    def __call__(self, inputs):
        def fn(x):
            arr = np.asarray(x)
            oov_base = len(self.vocab)

            def lookup(s):
                if isinstance(s, bytes):
                    s = s.decode("utf-8", "ignore")
                idx = self.vocab.get(s)
                if idx is None:
                    idx = oov_base + (
                        _stable_hash64(s) % self.num_oov_indices
                    )
                return idx

            return np.asarray(
                [lookup(s) for s in arr.reshape(-1)], np.int64
            ).reshape(arr.shape)

        return _map_values(fn, inputs)


class Normalizer:
    """(x - subtractor) / divisor (reference normalizer.py; the analyzer
    feeds mean/std or min/max from dataset statistics)."""

    def __init__(self, subtractor, divisor):
        self.subtractor = float(subtractor)
        self.divisor = float(divisor) or 1.0

    def __call__(self, inputs):
        return _map_values(
            lambda x: (x - self.subtractor) / self.divisor, inputs
        )


class ConcatenateWithOffset:
    """Concatenate id features, offsetting each input so id spaces don't
    collide (reference concatenate_with_offset.py). PaddedFeature inputs
    concatenate values AND masks."""

    def __init__(self, offsets, axis=-1):
        self.offsets = list(offsets)
        self.axis = axis

    def __call__(self, inputs):
        if len(self.offsets) != len(inputs):
            raise ValueError(
                f"{len(self.offsets)} offsets != {len(inputs)} inputs"
            )
        if isinstance(inputs[0], PaddedFeature):
            xp = _xp(inputs[0].values)
            values = xp.concatenate(
                [
                    f.values + off
                    for f, off in zip(inputs, self.offsets)
                ],
                axis=self.axis,
            )
            mask = xp.concatenate(
                [f.mask for f in inputs], axis=self.axis
            )
            return PaddedFeature(values=values, mask=mask)
        xp = _xp(inputs[0])
        return xp.concatenate(
            [x + off for x, off in zip(inputs, self.offsets)],
            axis=self.axis,
        )


class SparseEmbedding(nn.Module):
    """Embedding over padded multivalent ids with masked combiner —
    the reference's SparseEmbedding layer (sparse_embedding.py:20) on
    padded-dense input. Trainable table in params (for the PS-resident
    variant use layers.embedding.DistributedEmbedding)."""

    vocab_size: int
    dim: int
    combiner: str = "sum"

    @nn.compact
    def __call__(self, feature: PaddedFeature):
        table = self.param(
            "table",
            nn.initializers.uniform(scale=0.05),
            (self.vocab_size, self.dim),
        )
        ids = jnp.asarray(feature.values).astype(jnp.int32)
        mask = jnp.asarray(feature.mask)
        emb = jnp.take(table, ids, axis=0)  # [B, L, D]
        emb = emb * mask[..., None]
        total = jnp.sum(emb, axis=-2)
        count = jnp.maximum(jnp.sum(mask, axis=-1, keepdims=True), 1)
        if self.combiner == "sum":
            return total
        if self.combiner == "mean":
            return total / count
        if self.combiner == "sqrtn":
            return total / jnp.sqrt(count.astype(total.dtype))
        raise ValueError(f"unknown combiner {self.combiner!r}")
