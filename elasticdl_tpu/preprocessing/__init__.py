"""Feature preprocessing for TPU pipelines.

Counterpart of the reference's elasticdl_preprocessing package (11 Keras
layers, /root/reference/elasticdl_preprocessing/layers/__init__.py).
TPU-first redesign: XLA has no ragged/sparse tensors, so variable-length
features travel as PADDED DENSE arrays + masks (see PaddedFeature); every
transform is a pure function of dense arrays, traceable under jit and
equally usable in numpy inside `feed`.
"""

from elasticdl_tpu.preprocessing.layers import (  # noqa: F401
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    LogRound,
    Normalizer,
    PaddedFeature,
    RoundIdentity,
    SparseEmbedding,
    ToNumber,
    to_padded,
)
