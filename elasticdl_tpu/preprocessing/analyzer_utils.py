"""Dataset-statistics lookup from the environment.

Reference counterpart: /root/reference/elasticdl_preprocessing/utils/
analyzer_utils.py:15-30 + constants.py — an external analysis job (SQLFlow
in the reference) publishes per-feature statistics as environment variables
(`_{feature}_min`, `_{feature}_stddev`, ...) and preprocessing layers pick
them up at model-build time, falling back to defaults for unit tests. The
env naming is kept verbatim so jobs written against the reference's
analyzer contract parameterize these layers unchanged.
"""

import os

_MIN = "_{}_min"
_MAX = "_{}_max"
_AVG = "_{}_avg"
_STDDEV = "_{}_stddev"
_BUCKET_BOUNDARIES = "_{}_boundaries"
_DISTINCT_COUNT = "_{}_distinct_count"
_VOCABULARY = "_{}_vocab"


def _float_env(template, feature_name, default_value):
    value = os.environ.get(template.format(feature_name))
    return float(value) if value is not None else default_value


def get_min(feature_name, default_value):
    return _float_env(_MIN, feature_name, default_value)


# edl-lint: disable=dead-code
def get_max(feature_name, default_value):
    # Reference-parity accessor family (min/max/avg/stddev); max has no
    # in-tree caller today but the set stays symmetric for model code.
    return _float_env(_MAX, feature_name, default_value)


def get_avg(feature_name, default_value):
    return _float_env(_AVG, feature_name, default_value)


def get_stddev(feature_name, default_value):
    return _float_env(_STDDEV, feature_name, default_value)


def get_bucket_boundaries(feature_name, default_value):
    """Comma-separated floats -> sorted list."""
    value = os.environ.get(_BUCKET_BOUNDARIES.format(feature_name))
    if value is None:
        return default_value
    return sorted(float(v) for v in value.split(",") if v.strip())


def get_distinct_count(feature_name, default_value):
    value = os.environ.get(_DISTINCT_COUNT.format(feature_name))
    return int(value) if value is not None else default_value


def get_vocabulary(feature_name, default_value):
    """Comma-separated tokens -> list."""
    value = os.environ.get(_VOCABULARY.format(feature_name))
    if value is None:
        return default_value
    return [v for v in value.split(",") if v]
