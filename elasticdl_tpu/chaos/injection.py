"""Seeded, counter-based gRPC fault injection.

A FaultSchedule owns a list of FaultRules plus one seeded RNG. Each rule
matches RPCs by method-name substring and side ("server" or "client") and
fires on a deterministic call-index window: the rule's counter increments on
every matching call, and calls with start <= index < start + count get the
fault. Latency jitter draws from the schedule's seeded RNG, so two runs
with the same schedule and the same call order inject byte-identical fault
sequences — which is what lets the unit suite assert retry/backoff/breaker
behavior without real processes or wall-clock races.

Fault kinds:
  unavailable  server: context.abort(UNAVAILABLE); client: synthetic
               UNAVAILABLE raised before the wire — both retryable.
  latency      sleep latency_s (+/- seeded jitter) before serving.
  deadline     server: sleep past the caller's remaining deadline; client:
               shrink the call's timeout to ~1ms. Deterministic
               DEADLINE_EXCEEDED either way.
  truncate     server only: the response payload is cut in half at the
               serializer, simulating a torn payload; the client sees a
               deserialization failure (INTERNAL — fail-fast, the worker's
               minibatch retry ladder owns recovery).
  kill         local injection points only: SIGKILL the OWN process at the
               matching call index — the deterministic process-crash fault
               behind the master-kill drills ("master.dispatch" fires at
               the Nth task dispatch, "master.scale" between the world
               hint and the scale actuation). Ignored on wire
               interceptors: killing a process from inside an RPC handler
               would model nothing a network can do.
"""

import dataclasses
import json
import os
import random
import signal
import threading
import time

import grpc

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("chaos.injection")

CHAOS_ENV = "ELASTICDL_CHAOS"

KINDS = ("unavailable", "latency", "deadline", "truncate", "kill")

_INJECTED = default_registry().counter(
    "edl_chaos_injected_total",
    "Faults injected by the chaos interceptors",
    labelnames=("kind", "side"),
)


@dataclasses.dataclass
class FaultRule:
    method: str  # substring of the full method name ("" matches all)
    kind: str  # one of KINDS
    start: int = 0  # first matching call index (0-based) affected
    count: int = -1  # number of calls affected; -1 = unbounded
    latency_s: float = 0.25
    side: str = "server"  # "server" | "client"
    # Target one process by its ELASTICDL_ROLE stamp: "" matches every
    # process, "worker-0" exactly that instance, a trailing "*" matches
    # the prefix ("worker-*" = all workers). Exact by default — a
    # substring match would make "worker-1" also hit worker-10..19 and
    # silently widen a single-straggler drill into a cohort.
    role: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.side not in ("server", "client"):
            raise ValueError(f"unknown fault side {self.side!r}")

    def matches_role(self):
        if not self.role:
            return True
        stamp = knobs.get_str("ELASTICDL_ROLE")
        if self.role.endswith("*"):
            return stamp.startswith(self.role[:-1])
        return stamp == self.role


class FaultSchedule:
    """Thread-safe, deterministic fault decisions for a rule list."""

    def __init__(self, rules, seed=0):
        self.rules = [
            r if isinstance(r, FaultRule) else FaultRule(**r)
            for r in rules
        ]
        self.seed = seed
        self._rng = random.Random(seed)
        self._counts = [0] * len(self.rules)
        self._lock = threading.Lock()

    def decide(self, method, side):
        """Faults to apply to this call (consumes one count per matching
        rule). Deterministic given the per-method call order."""
        active = []
        with self._lock:
            for i, rule in enumerate(self.rules):
                if rule.side != side or rule.method not in method:
                    continue
                if not rule.matches_role():
                    continue
                index = self._counts[i]
                self._counts[i] += 1
                if index >= rule.start and (
                    rule.count < 0 or index < rule.start + rule.count
                ):
                    active.append(rule)
        return active

    def jitter(self, rule):
        """Jittered latency for a latency-kind fault; the draw comes from
        the schedule's seeded RNG so sequences replay."""
        with self._lock:
            return rule.latency_s * (0.5 + self._rng.random())

    # -- (de)serialization: drills ship schedules to subprocesses via env --

    def to_json(self):
        return json.dumps(
            {
                "seed": self.seed,
                "rules": [dataclasses.asdict(r) for r in self.rules],
            }
        )

    @classmethod
    def from_json(cls, raw):
        spec = json.loads(raw)
        return cls(spec.get("rules", ()), seed=spec.get("seed", 0))


_env_schedule = None
_env_lock = threading.Lock()


def schedule_from_env():
    """The process-wide schedule from ELASTICDL_CHAOS, or None. Cached: all
    servers/channels of one process share one schedule (and therefore one
    set of rule counters), mirroring how one process experiences one
    network."""
    global _env_schedule
    raw = knobs.raw(CHAOS_ENV)
    if not raw:
        return None
    with _env_lock:
        if _env_schedule is None:
            try:
                _env_schedule = FaultSchedule.from_json(raw)
                logger.warning(
                    "CHAOS ACTIVE: %d fault rules (seed %d) from $%s",
                    len(_env_schedule.rules),
                    _env_schedule.seed,
                    CHAOS_ENV,
                )
            except (ValueError, TypeError) as e:
                logger.error("Bad %s (%s); chaos disabled", CHAOS_ENV, e)
                os.environ.pop(CHAOS_ENV, None)
                return None
        return _env_schedule


def inject_local(point):
    """Apply env-scheduled latency faults at a non-RPC injection point.

    The interceptors above only reach calls that cross a channel, but
    some drills need to perturb purely in-process code paths — e.g. the
    input-starve scenario slows one worker's record reader by matching
    rules against the synthetic method name "datapath.read", and the
    master-kill drills SIGKILL the master at "master.dispatch" /
    "master.scale". Same rule grammar (method substring, start/count
    window, role targeting, seeded jitter); only latency and kill faults
    make sense here — the other kinds model wire behavior — so anything
    else on a local point is ignored."""
    schedule = schedule_from_env()
    if schedule is None:
        return
    for rule in schedule.decide(point, "client"):
        if rule.kind == "latency":
            _INJECTED.labels(kind="latency", side="client").inc()
            time.sleep(schedule.jitter(rule))
        elif rule.kind == "kill":
            # The deterministic crash fault: no cleanup, no atexit, no
            # flushing — exactly what a preemption looks like. The metric
            # bump below is best-effort (the exporter may never scrape it).
            _INJECTED.labels(kind="kill", side="client").inc()
            logger.warning("CHAOS: SIGKILL self at local point %r", point)
            os.kill(os.getpid(), signal.SIGKILL)


class ChaosServerInterceptor(grpc.ServerInterceptor):
    """Injects scheduled faults into a server's handlers."""

    def __init__(self, schedule: FaultSchedule):
        self._schedule = schedule
        # Serialization runs on the same server thread as the handler, so a
        # threadlocal carries the truncate decision from handler to
        # serializer.
        self._local = threading.local()

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler
        inner = handler.unary_unary
        serializer = handler.response_serializer
        method = handler_call_details.method
        schedule = self._schedule
        local = self._local

        def chaotic(request, context):
            local.truncate = False
            for rule in schedule.decide(method, "server"):
                _INJECTED.labels(kind=rule.kind, side="server").inc()
                if rule.kind == "latency":
                    time.sleep(schedule.jitter(rule))
                elif rule.kind == "deadline":
                    remaining = context.time_remaining()
                    if remaining is not None:
                        # Sleep just past the caller's deadline — the
                        # sleep is self-bounding (the client's own
                        # deadline caps it), so no separate cap that
                        # could undershoot large deadlines and turn the
                        # fault into a silent latency blip.
                        time.sleep(remaining + 0.5)
                    else:
                        # No client deadline to overrun: degenerate to a
                        # plain latency fault rather than parking a
                        # server thread forever.
                        time.sleep(rule.latency_s)
                elif rule.kind == "truncate":
                    local.truncate = True
                elif rule.kind == "unavailable":
                    context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        f"chaos: injected UNAVAILABLE on {method}",
                    )
            return inner(request, context)

        def chaotic_serializer(message):
            data = serializer(message)
            if getattr(local, "truncate", False):
                local.truncate = False
                return data[: len(data) // 2]
            return data

        return grpc.unary_unary_rpc_method_handler(
            chaotic,
            request_deserializer=handler.request_deserializer,
            response_serializer=chaotic_serializer,
        )


class ChaosClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    """Injects scheduled faults on the client side, before the wire."""

    def __init__(self, schedule: FaultSchedule):
        self._schedule = schedule

    def intercept_unary_unary(self, continuation, details, request):
        timeout = details.timeout
        for rule in self._schedule.decide(details.method, "client"):
            _INJECTED.labels(kind=rule.kind, side="client").inc()
            if rule.kind == "latency":
                time.sleep(self._schedule.jitter(rule))
            elif rule.kind == "deadline":
                # Shrink the deadline so the real call overruns it.
                timeout = 0.001
            elif rule.kind == "unavailable":
                from elasticdl_tpu.common.rpc import SyntheticRpcError

                raise SyntheticRpcError(
                    grpc.StatusCode.UNAVAILABLE,
                    f"chaos: injected UNAVAILABLE on {details.method}",
                )
            # "truncate" is server-side only: the client cannot corrupt the
            # response before its own deserializer sees it.
        if timeout != details.timeout:
            from elasticdl_tpu.common.rpc import _CallDetails

            details = _CallDetails(details, timeout)
        return continuation(details, request)
