"""Process-level fault primitives for chaos drills.

Roles are addressed by command-line pattern, the same way
tools/elastic_drill.py finds its victim: every instance of a local job
carries the master address on its argv, so (module, master_port, extra
needles) uniquely identifies one process without tracking pids across
relaunches."""

import os
import random
import signal
import subprocess
import time

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("chaos.process")

ROLE_MODULES = {
    "worker": "elasticdl_tpu.worker.main",
    "ps": "elasticdl_tpu.ps.main",
}


def find_role_pid(role, instance_id, master_port, timeout=60):
    """Pid of the live worker/PS subprocess with this id in the job rooted
    at master_port. Raises RuntimeError when none shows up in time."""
    module = ROLE_MODULES[role]
    id_flag = "--worker_id" if role == "worker" else "--ps_id"
    needles = (
        f"--master_addr 127.0.0.1:{master_port}",
        f"{id_flag} {instance_id}",
    )
    deadline = time.time() + timeout
    while time.time() < deadline:
        out = subprocess.run(
            ["pgrep", "-af", module], capture_output=True, text=True
        ).stdout
        for line in out.splitlines():
            if all(n in line for n in needles):
                return int(line.split()[0])
        time.sleep(0.2)
    raise RuntimeError(
        f"{role} {instance_id} process not found for master port "
        f"{master_port}"
    )


def find_job_pids(master_port):
    """All live worker/PS pids of the job rooted at master_port (the
    leftover-process check drills run at teardown)."""
    pids = []
    needle = f"--master_addr 127.0.0.1:{master_port}"
    for module in ROLE_MODULES.values():
        out = subprocess.run(
            ["pgrep", "-af", module], capture_output=True, text=True
        ).stdout
        for line in out.splitlines():
            if needle in line:
                pids.append((int(line.split()[0]), line.strip()))
    return pids


def deliver(pid, sig):
    """Send a signal, tolerating an already-gone target. Returns True when
    the signal was delivered."""
    try:
        os.kill(pid, sig)
        return True
    except ProcessLookupError:
        return False


def kill_role(role, instance_id, master_port, timeout=60):
    """SIGKILL one role instance; returns its pid."""
    pid = find_role_pid(role, instance_id, master_port, timeout)
    logger.info("chaos: SIGKILL %s %d (pid %d)", role, instance_id, pid)
    deliver(pid, signal.SIGKILL)
    return pid


def preemption_wave(n_workers, master_port, fraction=0.3, seed=0,
                    timeout=60):
    """SIGKILL a seeded fraction of the job's workers in one sweep — the
    spot/maintenance preemption wave, process edition. Victims are drawn
    deterministically from (n_workers, fraction, seed); workers that are
    already gone are skipped. Returns [(worker_id, pid), ...] actually
    killed."""
    rng = random.Random(seed)
    n_victims = max(1, int(round(n_workers * fraction)))
    victims = sorted(
        rng.sample(range(n_workers), min(n_workers, n_victims))
    )
    logger.info(
        "chaos: preemption wave over workers %s (%.0f%% of %d)",
        victims, 100 * fraction, n_workers,
    )
    killed = []
    for wid in victims:
        try:
            pid = find_role_pid("worker", wid, master_port, timeout)
        except RuntimeError:
            continue
        if deliver(pid, signal.SIGKILL):
            killed.append((wid, pid))
    return killed


def stall(pid, seconds):
    """SIGSTOP a process for `seconds`, then SIGCONT it. Returns True when
    both signals were delivered (the target survived the stall)."""
    if not deliver(pid, signal.SIGSTOP):
        return False
    logger.info("chaos: SIGSTOP pid %d for %.1fs", pid, seconds)
    try:
        time.sleep(seconds)
    finally:
        resumed = deliver(pid, signal.SIGCONT)
    return resumed
