"""Deterministic fault injection for the RPC plane and for whole processes.

Two layers (docs/ROBUSTNESS.md keeps the scenario catalog):

- injection: a seeded FaultSchedule drives a gRPC server/client interceptor
  pair that injects UNAVAILABLE aborts, latency, deadline overruns, and
  payload truncation by method-name pattern. Schedules are counter-based
  (the Nth matching call misbehaves), so a test or drill replays the exact
  same fault sequence every run.
- process: SIGKILL/SIGSTOP/SIGCONT helpers addressed by role (worker/PS/
  master command-line patterns), used by tools/elastic_drill.py scenarios.

Real processes pick schedules up from the ELASTICDL_CHAOS environment
variable (JSON, see injection.schedule_from_env); in-process tests pass a
FaultSchedule directly to rpc.serve / rpc.build_channel.
"""

from elasticdl_tpu.chaos.injection import (  # noqa: F401
    ChaosClientInterceptor,
    ChaosServerInterceptor,
    FaultRule,
    FaultSchedule,
    CHAOS_ENV,
    schedule_from_env,
)
