"""Model-zoo spec loading.

The zoo contract mirrors the reference's module-level-name lookup
(/root/reference/elasticdl/python/common/model_utils.py:135-191): a model
definition module exports
  custom_model() -> flax.linen.Module     (called `model factory` here)
  loss(labels, predictions) -> scalar     (jax-traceable)
  optimizer() -> ops.optimizers.OptimizerSpec
  feed(records, mode, metadata) -> (features, labels)  numpy batch
  eval_metrics_fn() -> {name: metric}     (see common/evaluation_utils)
optional:
  callbacks() -> list                     (train-end hooks etc.)
  prediction_outputs_processor            (BasePredictionOutputsProcessor)
  dataset_fn / create_data_reader hooks
"""

import importlib
import importlib.util
import os


class Modes:
    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"


def load_module(module_ref):
    """Import a model-def module from a dotted path or a .py file path."""
    if os.path.isfile(module_ref) and module_ref.endswith(".py"):
        spec = importlib.util.spec_from_file_location(
            os.path.splitext(os.path.basename(module_ref))[0], module_ref
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module
    return importlib.import_module(module_ref)


_REQUIRED = ["custom_model", "loss", "optimizer", "feed"]
_OPTIONAL = [
    "eval_metrics_fn",
    "callbacks",
    "prediction_outputs_processor",
    "create_data_reader",
]


class ModelSpec:
    def __init__(self, module):
        self.module = module
        missing = [n for n in _REQUIRED if not hasattr(module, n)]
        if missing:
            raise ValueError(
                f"model def {module.__name__!r} is missing {missing}; "
                f"required: {_REQUIRED}"
            )
        for name in _REQUIRED + _OPTIONAL:
            setattr(self, name, getattr(module, name, None))

    def build_model(self):
        return self.custom_model()

    def build_optimizer_spec(self):
        return self.optimizer()

    def build_metrics(self):
        return self.eval_metrics_fn() if self.eval_metrics_fn else {}


def get_model_spec(model_def):
    """model_def: dotted module path ('elasticdl_tpu.models.mnist.mnist_model')
    or a path to a .py file."""
    return ModelSpec(load_module(model_def))
