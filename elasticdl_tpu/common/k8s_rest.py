"""Minimal Kubernetes REST transport (stdlib only).

The reference depends on the `kubernetes` python client for every
cluster call (/root/reference/elasticdl/python/common/k8s_client.py:40-300).
This image (and many TPU-VM images) does not ship it, so the pod
lifecycle this framework actually needs — create/read/delete pods,
create services, list+watch with a label selector — is implemented
directly against the Kubernetes HTTP API: JSON bodies over
http.client, the watch as the API's chunked line-delimited event
stream. `common/k8s_client.Client` uses the official client when it is
importable and falls back to this transport when not; either way the
wire behavior is exercised end to end by tests/fake_k8s_server.py.

Auth: in-cluster service-account token + CA when present
(/var/run/secrets/kubernetes.io/serviceaccount), or a plain endpoint
from EDL_K8S_API_SERVER (stub servers, kubectl proxy).
"""

import json
import os
import ssl
import threading
from http import client as http_client
from urllib.parse import quote, urlsplit

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.k8s_rest")

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sApiError(RuntimeError):
    def __init__(self, status, body):
        super().__init__(f"kubernetes API error {status}: {body[:300]}")
        self.status = status
        self.body = body


class ObjView:
    """Attribute-style view over a k8s JSON object, so watch callbacks
    written for the official client's models (pod.status.phase,
    cs.state.terminated.exit_code) read REST dicts unchanged. Missing
    fields resolve to None, snake_case maps to the API's camelCase."""

    def __init__(self, data):
        self._data = data

    @staticmethod
    def _wrap(value):
        if isinstance(value, dict):
            return ObjView(value)
        if isinstance(value, list):
            return [ObjView._wrap(v) for v in value]
        return value

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        data = object.__getattribute__(self, "_data")
        if name in data:
            return self._wrap(data[name])
        parts = name.split("_")
        camel = parts[0] + "".join(p.title() for p in parts[1:])
        return self._wrap(data.get(camel))

    def get(self, key, default=None):
        """Dict-style access: label/annotation maps are consumed with
        .get() (the official client models them as plain dicts)."""
        data = object.__getattribute__(self, "_data")
        return self._wrap(data.get(key, default))

    def to_dict(self):
        return self._data

    def __repr__(self):
        return f"ObjView({self._data!r})"


class RestApi:
    """The four pod/service operations + watch, over one API server."""

    def __init__(self, base_url, token=None, ca_file=None,
                 insecure_skip_verify=False):
        parts = urlsplit(base_url)
        self._scheme = parts.scheme or "http"
        self._host = parts.hostname
        self._port = parts.port or (443 if self._scheme == "https" else 80)
        self._token = token
        if self._scheme == "https":
            if ca_file:
                self._ssl = ssl.create_default_context(cafile=ca_file)
            else:
                self._ssl = ssl.create_default_context()
            if insecure_skip_verify:
                self._ssl.check_hostname = False
                self._ssl.verify_mode = ssl.CERT_NONE
        else:
            self._ssl = None

    # ---------- plumbing ----------

    def _connect(self, timeout=30):
        if self._scheme == "https":
            return http_client.HTTPSConnection(
                self._host, self._port, timeout=timeout, context=self._ssl
            )
        return http_client.HTTPConnection(
            self._host, self._port, timeout=timeout
        )

    def _headers(self, has_body=False):
        headers = {"Accept": "application/json"}
        if has_body:
            headers["Content-Type"] = "application/json"
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        return headers

    def _request(self, method, path, body=None):
        conn = self._connect()
        try:
            conn.request(
                method,
                path,
                body=json.dumps(body) if body is not None else None,
                headers=self._headers(body is not None),
            )
            res = conn.getresponse()
            payload = res.read().decode("utf-8", "replace")
            if res.status >= 300:
                raise K8sApiError(res.status, payload)
            return json.loads(payload) if payload else {}
        finally:
            conn.close()

    # ---------- operations ----------

    def create_pod(self, namespace, manifest):
        return self._request(
            "POST", f"/api/v1/namespaces/{namespace}/pods", manifest
        )

    def read_pod(self, namespace, name):
        return self._request(
            "GET", f"/api/v1/namespaces/{namespace}/pods/{name}"
        )

    def delete_pod(self, namespace, name):
        return self._request(
            "DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}"
        )

    def list_pods(self, namespace, label_selector):
        return self._request(
            "GET",
            f"/api/v1/namespaces/{namespace}/pods"
            f"?labelSelector={quote(label_selector)}",
        )

    def create_service(self, namespace, manifest):
        return self._request(
            "POST", f"/api/v1/namespaces/{namespace}/services", manifest
        )

    def read_service(self, namespace, name):
        return self._request(
            "GET", f"/api/v1/namespaces/{namespace}/services/{name}"
        )

    def watch_pods(self, namespace, label_selector, event_callback,
                   stop_event=None):
        """Blocking watch loop: stream ADDED/MODIFIED/DELETED pod events
        (each a JSON line of the chunked response) into `event_callback`
        as {"type": ..., "object": ObjView} until stop_event is set. The
        stream is re-established on any error, matching the official
        watch's reconnect behavior.

        Every REconnect is a LIST+WATCH (the official client's Reflector
        pattern): a bare watch starts from "now", so pod transitions that
        happened while the stream was down would be lost forever — a
        worker that died in that window would never be relaunched. The
        re-list (a) synthesizes a MODIFIED event per currently matching
        pod (consumers treat repeated same-phase MODIFIEDs as no-ops),
        (b) diffs against the pods seen so far to synthesize DELETED for
        any that vanished during the outage, and (c) anchors the new
        watch at the list's resourceVersion so transitions between the
        LIST response and the WATCH being accepted are replayed, not
        skipped. An expired anchor (410 Gone) just resets the stream:
        the next iteration re-lists and gets a fresh one."""
        stop_event = stop_event or threading.Event()
        base = (
            f"/api/v1/namespaces/{namespace}/pods"
            f"?watch=true&labelSelector={quote(label_selector)}"
        )
        known = {}  # pod name -> last seen raw object
        first_connect = True
        resource_version = None
        while not stop_event.is_set():
            conn = None
            try:
                if not first_connect:
                    # Re-list to cover the blind window. (On the first
                    # connect there is nothing to have missed yet — the
                    # watch starts before any pod is created.)
                    listing = self.list_pods(namespace, label_selector)
                    resource_version = (
                        listing.get("metadata", {}).get("resourceVersion")
                    )
                    current = {}
                    for item in listing.get("items", []):
                        name = (item.get("metadata") or {}).get("name")
                        if name:
                            current[name] = item
                    vanished = [
                        known[n] for n in known if n not in current
                    ]
                    known = current
                    for item in vanished:
                        if stop_event.is_set():
                            return
                        event_callback(
                            {"type": "DELETED", "object": ObjView(item)}
                        )
                    for item in current.values():
                        if stop_event.is_set():
                            return
                        event_callback(
                            {"type": "MODIFIED", "object": ObjView(item)}
                        )
                path = base
                if resource_version:
                    path += f"&resourceVersion={quote(resource_version)}"
                conn = self._connect(timeout=300)
                conn.request("GET", path, headers=self._headers())
                res = conn.getresponse()
                if res.status >= 300:
                    raise K8sApiError(
                        res.status, res.read().decode("utf-8", "replace")
                    )
                first_connect = False
                while not stop_event.is_set():
                    line = res.readline()
                    if not line:
                        break  # server closed the stream: reconnect
                    line = line.strip()
                    if not line:
                        continue
                    event = json.loads(line)
                    obj = event.get("object") or {}
                    name = (obj.get("metadata") or {}).get("name")
                    if name:
                        if event.get("type") == "DELETED":
                            known.pop(name, None)
                        else:
                            known[name] = obj
                    event_callback(
                        {"type": event.get("type"), "object": ObjView(obj)}
                    )
            except Exception:
                if stop_event.is_set():
                    return
                logger.warning("k8s watch stream reset", exc_info=True)
                # A 410-expired anchor must not wedge the loop on the same
                # stale version; the re-list above refreshes it anyway.
                resource_version = None
                stop_event.wait(1.0)
            finally:
                if conn is not None:
                    conn.close()


def in_cluster_available():
    return bool(os.environ.get("KUBERNETES_SERVICE_HOST")) and os.path.exists(
        os.path.join(_SA_DIR, "token")
    )


def default_rest_api():
    """RestApi from the environment: EDL_K8S_API_SERVER (stub servers,
    kubectl proxy) or the in-cluster service account. None if neither."""
    endpoint = os.environ.get("EDL_K8S_API_SERVER")
    if endpoint:
        return RestApi(endpoint)
    if in_cluster_available():
        with open(os.path.join(_SA_DIR, "token")) as f:
            token = f.read().strip()
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return RestApi(
            f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(_SA_DIR, "ca.crt"),
        )
    return None
