"""Master heartbeat files for the orphan reaper (tools/reap_orphans.py).

A SIGKILLed or wedged driver strands its whole `edl train` process tree:
workers block in rendezvous, the master keeps its ports, and every later
bench/chaos run on the machine inherits the noise. Each master therefore
writes a small JSON heartbeat — pid, process group, a /proc-verifiable
cmdline marker, and a timestamp — to a central directory on a short
period. The reaper kills the process group of any heartbeat that went
stale while its pid still runs the recorded command, and deletes
heartbeats of dead pids. The cmdline check makes pid reuse safe: a
recycled pid running something else is never signalled.

Heartbeats are best-effort by design: a full disk or read-only dir must
never take training down, so every write failure is swallowed after the
first warning.
"""

import json
import os
import threading
import time

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.heartbeat")

HEARTBEAT_DIR_ENV = "ELASTICDL_HEARTBEAT_DIR"
HEARTBEAT_SECONDS_ENV = "ELASTICDL_HEARTBEAT_SECONDS"


def read_cmdline(pid):
    """The process's argv joined with spaces, or None when it is gone
    (or /proc is unreadable — non-Linux; the reaper then refuses to
    kill, which fails safe)."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            raw = f.read()
    except OSError:
        return None
    return raw.replace(b"\0", b" ").decode(errors="replace").strip()


class HeartbeatWriter:
    """Periodic `<dir>/<job>-<pid>.json` toucher for one master."""

    def __init__(self, job="", directory=None, period=None):
        if directory is None:
            directory = knobs.get_str(HEARTBEAT_DIR_ENV)
        if period is None:
            period = knobs.get_float(HEARTBEAT_SECONDS_ENV)
        self._dir = directory
        self.period = float(period)
        self._job = job or "job"
        self.path = (
            os.path.join(
                directory, f"{self._job}-{os.getpid()}.json"
            )
            if directory
            else None
        )
        self._warned = False
        self._stop = threading.Event()
        self._thread = None

    @property
    def enabled(self):
        return bool(self.path) and self.period > 0

    def beat(self):
        """Write one heartbeat now (also the thread body's step)."""
        if not self.path:
            return False
        record = {
            "pid": os.getpid(),
            "pgid": os.getpgid(0),
            "job": self._job,
            "ts": time.time(),
            "period_s": self.period,
            # The reaper only kills while the pid still runs THIS
            # command — pid reuse by an unrelated process fails the
            # match and spares it.
            "cmdline": read_cmdline(os.getpid()) or "",
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self._dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self.path)
            return True
        except OSError as e:
            if not self._warned:
                self._warned = True
                logger.warning("heartbeat write failed: %s", e)
            return False

    def start(self):
        if not self.enabled or self._thread is not None:
            return self
        self.beat()
        self._thread = threading.Thread(
            target=self._run, name="edl-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.period):
            self.beat()

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self.path:
            try:
                os.unlink(self.path)
            except OSError:
                pass
