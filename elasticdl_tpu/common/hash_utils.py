"""Stable partitioning of parameters and embedding ids across PS shards.

Mirrors the reference's scheme (/root/reference/elasticdl/python/common/
hash_utils.py:17-62): dense params by sha256(name) mod N, embedding ids by
id mod N — stable across processes/languages so a restarted PS or a client in
another language partitions identically.
"""

import hashlib

import numpy as np


def string_to_id(name: str, num_buckets: int) -> int:
    h = hashlib.sha256(name.encode("utf-8")).hexdigest()
    return int(h, 16) % num_buckets


def scatter_embedding_ids(ids: np.ndarray, num_ps: int):
    """Partition embedding ids by modulo; returns {ps_id: (ids, positions)}.

    `positions` are the indices into the original `ids` array, so pulled rows
    can be scattered back into batch order.
    """
    ids = np.asarray(ids, dtype=np.int64)
    result = {}
    mods = ids % num_ps
    for ps_id in range(num_ps):
        mask = mods == ps_id
        if mask.any():
            result[ps_id] = (ids[mask], np.nonzero(mask)[0])
    return result
