"""Argument definitions for master / worker / PS processes and the CLI.

Reference counterparts: /root/reference/elasticdl_client/common/args.py
(~60 flags over zoo/common/train/evaluate/predict groups) and
elasticdl/python/common/args.py:154-164 (validation: async => grads_to_wait
is 1). Three-stage relay kept: CLI flags -> master argv -> worker/PS argv
(build_arguments_from_parsed_result)."""

import argparse
import os

from elasticdl_tpu.common.constants import (
    COORDINATOR_PORT_ROTATION,
    DistributionStrategy,
)


def add_common_arguments(parser):
    parser.add_argument("--job_name", default="edl-job")
    parser.add_argument(
        "--model_zoo",
        default="",
        help="directory prepended to sys.path before importing model_def",
    )
    parser.add_argument(
        "--model_def",
        required=True,
        help="dotted module path or .py file exporting the model spec "
        "(custom_model/loss/optimizer/feed[/eval_metrics_fn])",
    )
    parser.add_argument(
        "--distribution_strategy",
        default=DistributionStrategy.ALLREDUCE,
        choices=[
            DistributionStrategy.LOCAL,
            DistributionStrategy.ALLREDUCE,
            DistributionStrategy.PARAMETER_SERVER,
        ],
    )
    parser.add_argument("--minibatch_size", type=int, default=64)
    parser.add_argument(
        "--get_model_steps",
        type=int,
        default=1,
        help="PS strategy: pull fresh params every N minibatches, train "
        "with the locally-updated model in between (gradients still "
        "push every step)",
    )
    parser.add_argument("--log_loss_steps", type=int, default=100)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--ps_wire_dtype",
        default=None,
        choices=["float32", "bfloat16", "int8"],
        help="PS strategy wire codec: bfloat16 halves sparse pull/push "
        "bandwidth; int8 additionally block-quantizes dense gradients "
        "with error feedback (embedding legs stay bf16). Unset reads "
        "ELASTICDL_WIRE_DTYPE (default float32); dense params and "
        "optimizer state stay float32 on the PS either way.",
    )
    parser.add_argument(
        "--model_parallel_size",
        type=int,
        default=1,
        help="tensor-parallel width for the AllReduce strategy: the device "
        "mesh gains a 'model' axis of this size and params are laid out by "
        "the model spec's param_specs(variables) hook (pure DP when 1)",
    )
    parser.add_argument(
        "--pipeline_stages",
        type=int,
        default=1,
        help="pipeline-parallel depth for the AllReduce strategy: the "
        "device mesh gains a 'stage' axis of this size and the model "
        "spec's pipeline_spec(...) hook builds the staged step "
        "(parallel/pipeline.py). In multi-host worlds the stage axis "
        "stays inside each process, like the model axis (no pipelining "
        "when 1)",
    )
    parser.add_argument(
        "--pipeline_schedule",
        default="1f1b",
        choices=["gpipe", "1f1b", "interleaved"],
        help="microbatch schedule when --pipeline_stages > 1: gpipe "
        "(scan autodiff, O(microbatches) activation memory), 1f1b "
        "(O(stages) memory, vocab-parallel head), or interleaved 1F1B "
        "(virtual chunks, smaller bubble)",
    )
    parser.add_argument(
        "--pipeline_microbatches",
        type=int,
        default=0,
        help="microbatches per minibatch for the pipeline schedules "
        "(0: auto = 2 * pipeline_stages; more microbatches amortize the "
        "pipeline bubble at the cost of smaller per-stage matmuls)",
    )
    parser.add_argument(
        "--pipeline_virtual_stages",
        type=int,
        default=2,
        help="virtual chunks per device for "
        "--pipeline_schedule interleaved (ignored by other schedules)",
    )
    parser.add_argument(
        "--context_parallel_size",
        type=int,
        default=1,
        help="sequence/context-parallel width for the AllReduce strategy: "
        "the device mesh gains a 'seq' axis of this size and the model "
        "spec's context_parallel_model(...) hook rebinds attention to it "
        "(ring attention / Ulysses, parallel/ring_attention.py). "
        "Composes with --model_parallel_size into a 3-D DPxTPxSP mesh. "
        "Sequence length must divide by 2x this size (zigzag halves)",
    )
    parser.add_argument(
        "--context_parallel_impl",
        default="zigzag",
        choices=["zigzag", "ring", "ulysses"],
        help="sequence-parallel attention: zigzag (balanced causal ring, "
        "default), ring (plain causal ring), or ulysses (all-to-all "
        "head re-sharding; needs heads divisible by the seq axis and "
        "does not compose with --model_parallel_size)",
    )


def add_data_arguments(parser):
    parser.add_argument("--training_data", default="")
    parser.add_argument("--validation_data", default="")
    parser.add_argument("--prediction_data", default="")
    parser.add_argument("--records_per_task", type=int, default=1024)
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument(
        "--shuffle_shards", action="store_true", default=True
    )
    parser.add_argument(
        "--no_shuffle_shards", dest="shuffle_shards", action="store_false"
    )
    parser.add_argument(
        "--prefetch_records",
        type=int,
        default=1024,
        help="read records on a background thread, this many ahead of the "
        "training loop (0 disables prefetching)",
    )


def add_train_arguments(parser):
    parser.add_argument(
        "--evaluation_steps",
        type=int,
        default=0,
        help="evaluate every N model versions (0: once per epoch-ish "
        "report)",
    )
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--checkpoint_steps", type=int, default=0)
    parser.add_argument("--keep_checkpoint_max", type=int, default=3)
    parser.add_argument(
        "--checkpoint_dir_for_init",
        default="",
        help="restore PS state from this checkpoint dir at boot",
    )
    parser.add_argument("--output", default="", help="model export path")
    parser.add_argument(
        "--metrics_dir",
        default="",
        help="publish eval/throughput scalars here as metrics.jsonl + "
        "TensorBoard event files (point tensorboard --logdir at it)",
    )
    parser.add_argument(
        "--profile_dir",
        default="",
        help="capture one XLA device trace of steady-state training steps "
        "per worker under <profile_dir>/worker<id>/ (TensorBoard "
        "trace-viewer format)",
    )
    parser.add_argument(
        "--profile_start_step",
        type=int,
        default=10,
        help="first profiled step (skip compile + warmup)",
    )
    parser.add_argument(
        "--profile_steps", type=int, default=5,
        help="number of steps in the trace window",
    )


def add_cluster_arguments(parser):
    parser.add_argument("--num_workers", type=int, default=0)
    parser.add_argument("--num_ps", type=int, default=0)
    parser.add_argument(
        "--instance_backend",
        default="local_process",
        choices=["local_process", "k8s", "none"],
        help="none: workers/PS are launched externally and dial in",
    )
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--image_name", default="")
    parser.add_argument(
        "--worker_resources",
        default="",
        help="per-worker pod resources, e.g. cpu=4,memory=8Gi,tpu=4",
    )
    parser.add_argument("--ps_resources", default="")
    parser.add_argument(
        "--worker_pod_priority",
        default="",
        help="priority class for worker pods; 'high=0.5' gives the first "
        "half the 'high' class and the rest 'low'",
    )
    parser.add_argument(
        "--volume",
        default="",
        help="pod volumes: host_path=/d,mount_path=/d;"
        "claim_name=c,mount_path=/m[,sub_path=s]",
    )
    parser.add_argument("--max_relaunches", type=int, default=3)
    parser.add_argument("--master_port", type=int, default=50001)
    parser.add_argument(
        "--multi_host",
        action="store_true",
        default=False,
        help="AllReduce workers are separate processes/hosts forming one "
        "jax.distributed SPMD world; training is driven by "
        "step-synchronized task leases",
    )
    parser.add_argument(
        "--zero1",
        action="store_true",
        default=False,
        help="shard optimizer state over the data axis (cross-replica "
        "weight-update sharding): per-chip optimizer memory drops by "
        "the DP degree, update compiles as reduce-scatter -> "
        "shard-local math -> all-gather. In multi-host worlds the shard "
        "axis is the intra-process device slice (memory drops by the "
        "local chip count) so elastic regroups keep a full copy per "
        "process",
    )
    parser.add_argument(
        "--quantized_grads",
        action="store_true",
        default=False,
        help="AllReduce strategy: reduce DP gradients with int8 wire "
        "payloads (EQuARX-style reduce-scatter + all-gather, ~4x less "
        "collective bandwidth); on multi-host meshes only the "
        "cross-process leg quantizes, intra-host stays exact f32",
    )
    parser.add_argument(
        "--coordinator_port",
        type=int,
        default=51000,
        help="jax.distributed coordination-service port on rank 0. The "
        "port ROTATES across membership epochs: the job reserves the "
        "16-port block [port, port+15], which firewalls/NetworkPolicies "
        "must open and no other service (master_port, PS ports) may "
        "occupy",
    )
    parser.add_argument(
        "--task_timeout_check_seconds", type=float, default=30.0
    )
    parser.add_argument(
        "--worker_liveness_timeout_seconds", type=float, default=180.0
    )


def add_ps_arguments(parser):
    parser.add_argument("--ps_id", type=int, default=0)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--use_async", action="store_true", default=True)
    parser.add_argument(
        "--use_sync", dest="use_async", action="store_false"
    )
    parser.add_argument("--grads_to_wait", type=int, default=1)
    parser.add_argument("--sync_version_tolerance", type=int, default=0)
    parser.add_argument(
        "--sync_window_timeout",
        type=float,
        default=30.0,
        help="seconds before an unfilled sync quorum window applies what "
        "it has (liveness under elastic shrink); raise for jobs whose "
        "steps legitimately exceed it",
    )
    parser.add_argument(
        "--lr_staleness_modulation", action="store_true", default=False
    )


def validate_args(args):
    """Cross-flag validation (reference elasticdl/python/common/
    args.py:154-164)."""
    if getattr(args, "use_async", True) and getattr(
        args, "grads_to_wait", 1
    ) > 1:
        raise ValueError("async SGD requires grads_to_wait == 1")
    # Master-side only checks (worker/PS parsers have no num_ps /
    # instance_backend; workers enforce --ps_addrs instead).
    num_ps = getattr(args, "num_ps", None)
    if (
        getattr(args, "distribution_strategy", None)
        == DistributionStrategy.PARAMETER_SERVER
        and num_ps is not None
        and hasattr(args, "instance_backend")
        and num_ps < 1
        and args.instance_backend != "none"
    ):
        raise ValueError("ParameterServerStrategy requires --num_ps >= 1")
    # A master that manages instances but has no workers to spawn would
    # poll forever: require explicit --instance_backend none for externally
    # launched workers.
    if (
        getattr(args, "instance_backend", None)
        in ("local_process", "k8s")
        and getattr(args, "num_workers", None) is not None
        and args.num_workers < 1
    ):
        raise ValueError(
            "--num_workers >= 1 is required (or --instance_backend none "
            "when workers are launched externally)"
        )
    # Pipeline parallelism composes with DP (the stage axis pairs with the
    # data axis) but not yet with TP — both claim the intra-process device
    # slice, and no model spec lays params out over both at once. Fail
    # loudly instead of silently picking one.
    pipeline_stages = getattr(args, "pipeline_stages", 1) or 1
    if pipeline_stages > 1:
        if (
            getattr(args, "distribution_strategy", None)
            not in (None, DistributionStrategy.ALLREDUCE)
        ):
            raise ValueError(
                "--pipeline_stages > 1 requires the AllReduce strategy"
            )
        if getattr(args, "model_parallel_size", 1) > 1:
            raise ValueError(
                "--pipeline_stages and --model_parallel_size cannot be "
                "combined (both lay out the intra-process device slice); "
                "pick one"
            )
    if getattr(args, "pipeline_microbatches", 0) < 0:
        raise ValueError(
            "--pipeline_microbatches must be >= 0 (0 = auto)"
        )
    context_parallel = getattr(args, "context_parallel_size", 1) or 1
    if context_parallel > 1:
        if (
            getattr(args, "distribution_strategy", None)
            not in (None, DistributionStrategy.ALLREDUCE)
        ):
            raise ValueError(
                "--context_parallel_size > 1 requires the AllReduce "
                "strategy"
            )
        if pipeline_stages > 1:
            raise ValueError(
                "--context_parallel_size and --pipeline_stages cannot "
                "be combined (no model spec stages a sequence-parallel "
                "attention); pick one"
            )
        if (
            getattr(args, "context_parallel_impl", "zigzag") == "ulysses"
            and getattr(args, "model_parallel_size", 1) > 1
        ):
            raise ValueError(
                "--context_parallel_impl ulysses does not compose with "
                "--model_parallel_size (it re-shards heads itself); use "
                "zigzag"
            )
    # The coordination port rotates over a 16-port block across membership
    # epochs (master/membership.py): a master_port inside the block would
    # collide with a re-rendezvous after some elastic event.
    coordinator_port = getattr(args, "coordinator_port", None)
    master_port = getattr(args, "master_port", None)
    width = COORDINATOR_PORT_ROTATION
    if (
        coordinator_port is not None
        and master_port is not None
        and master_port != 0
        and coordinator_port <= master_port < coordinator_port + width
    ):
        raise ValueError(
            f"--master_port {master_port} falls inside the reserved "
            f"coordination-port rotation block [{coordinator_port}, "
            f"{coordinator_port + width - 1}]; move one of them"
        )


def build_arguments_from_parsed_result(args, filter_args=None):
    """argparse Namespace -> argv list, for relaying flags into spawned
    processes (reference args.py:521-543)."""
    items = vars(args)
    argv = []
    for key, value in items.items():
        if filter_args and key not in filter_args:
            continue
        if value is None or value == "":
            continue
        if isinstance(value, bool):
            if key == "use_async":
                argv.append("--use_async" if value else "--use_sync")
            elif value:
                argv.append(f"--{key}")
            continue
        argv.extend([f"--{key}", str(value)])
    return argv


def master_parser():
    p = argparse.ArgumentParser("elasticdl_tpu master")
    add_common_arguments(p)
    add_data_arguments(p)
    add_train_arguments(p)
    add_cluster_arguments(p)
    add_ps_arguments(p)
    return p


def worker_parser():
    p = argparse.ArgumentParser("elasticdl_tpu worker")
    add_common_arguments(p)
    add_data_arguments(p)
    add_train_arguments(p)
    p.add_argument("--worker_id", type=int, required=True)
    p.add_argument("--master_addr", required=True)
    p.add_argument("--ps_addrs", default="", help="comma-separated")
    p.add_argument(
        "--worker_host",
        default=os.environ.get("MY_POD_IP", "127.0.0.1"),
        help="address other workers can reach this worker on (defaults to "
        "$MY_POD_IP, injected into every k8s replica pod)",
    )
    p.add_argument(
        "--job_type",
        default="training_only",
        choices=[
            "training_only",
            "training_with_evaluation",
            "evaluation_only",
            "prediction_only",
        ],
    )
    p.add_argument("--multi_host", action="store_true", default=False)
    p.add_argument("--zero1", action="store_true", default=False)
    p.add_argument(
        "--quantized_grads", action="store_true", default=False
    )
    return p


def ps_parser():
    p = argparse.ArgumentParser("elasticdl_tpu pserver")
    add_common_arguments(p)
    add_train_arguments(p)
    add_ps_arguments(p)
    p.add_argument("--num_ps", type=int, default=1)
    p.add_argument("--master_addr", default="")
    return p
