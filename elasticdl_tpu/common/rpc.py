"""Spec-driven gRPC stubs and servicer registration + the resilient RPC plane.

The image has protoc but no grpc python plugin, so instead of codegen'd
`*_pb2_grpc.py` files each service is declared once as a ServiceSpec table and
both the client stub and the server handler are built from it generically.
Method set mirrors the reference's Master and Pserver services
(/root/reference/elasticdl/proto/elasticdl.proto:108-157).

Every channel built here is hardened (docs/ROBUSTNESS.md):

- per-method deadlines: a stub call with no explicit timeout gets the
  method's default from METHOD_POLICIES, so no call site can hang forever
  on a wedged peer.
- retries: jittered exponential backoff on retryable statuses (UNAVAILABLE
  always; DEADLINE_EXCEEDED only for idempotent methods — a timed-out
  gradient push may have applied server-side and must not double-apply).
  INVALID_ARGUMENT and friends fail fast.
- circuit breaker: per-peer, trips after consecutive connectivity failures,
  fails fast while open, half-opens on a timer with a single probe.
- channel-readiness wait: build_channel TCP-probes the peer before opening
  the channel. A channel whose first connect attempt predates the peer's
  bind can wedge in UNAVAILABLE on sandboxed/virtualized network stacks
  (first observed in tools/elastic_drill.py with grpc 1.68 under the CI
  sandbox); probing first sidesteps the wedge for every client.
- fault injection: when a chaos schedule is configured (argument or the
  ELASTICDL_CHAOS env var), serve()/build_channel() install the
  elasticdl_tpu.chaos interceptors so drills can inject deterministic
  faults into real processes.

Retry/trip counts export through the process metrics registry:
edl_rpc_retries_total, edl_rpc_client_failures_total,
edl_rpc_breaker_trips_total, edl_rpc_breaker_fast_fail_total.
"""

import concurrent.futures
import dataclasses
import json
import random
import socket
import threading
import time

import grpc

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.metrics import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

logger = get_logger("common.rpc")

# Matches the reference's 256 MB gRPC message cap
# (/root/reference/elasticdl/python/common/constants.py:15-19).
MAX_MESSAGE_LENGTH = 256 * 1024 * 1024

GRPC_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", MAX_MESSAGE_LENGTH),
    # Elasticity tuning: a relaunched peer (PS flap, worker preemption)
    # comes back in seconds, but grpc's default reconnect backoff climbs
    # to 20s+ — the channel would keep reporting UNAVAILABLE long after
    # the peer recovered, stretching every failover. Reconnect fast,
    # capped low; the retry plane's own jittered backoff paces the calls.
    ("grpc.initial_reconnect_backoff_ms", 250),
    ("grpc.min_reconnect_backoff_ms", 250),
    ("grpc.max_reconnect_backoff_ms", 5000),
]

_REG = default_registry()
_RETRIES = _REG.counter(
    "edl_rpc_retries_total",
    "RPC attempts retried after a retryable failure",
    labelnames=("method",),
)
_FAILURES = _REG.counter(
    "edl_rpc_client_failures_total",
    "Terminal client-side RPC failures (retries exhausted or fail-fast)",
    labelnames=("method", "code"),
)
_TRIPS = _REG.counter(
    "edl_rpc_breaker_trips_total",
    "Circuit-breaker trips (closed/half-open -> open)",
    labelnames=("peer",),
)
_FAST_FAILS = _REG.counter(
    "edl_rpc_breaker_fast_fail_total",
    "Calls rejected locally because the peer's circuit was open",
    labelnames=("peer",),
)


@dataclasses.dataclass(frozen=True)
class ServiceSpec:
    name: str
    # method name -> (request class, response class)
    methods: dict


MASTER_SERVICE = ServiceSpec(
    name="elasticdl_tpu.Master",
    methods={
        "get_task": (pb.GetTaskRequest, pb.Task),
        # Lease batching: up to max_tasks tasks per RPC, batched reports.
        "get_task_batch": (pb.GetTaskRequest, pb.TaskBatch),
        "report_task_result": (pb.ReportTaskResultRequest, pb.Empty),
        "report_task_results": (pb.ReportTaskResultsRequest, pb.Empty),
        "report_evaluation_metrics": (pb.ReportEvaluationMetricsRequest, pb.Empty),
        "report_version": (pb.ReportVersionRequest, pb.Empty),
        "get_comm_rank": (pb.GetCommRankRequest, pb.GetCommRankResponse),
        "lease_steps": (pb.LeaseStepsRequest, pb.LeaseStepsResponse),
        "report_lease": (pb.ReportLeaseRequest, pb.Empty),
        "report_worker_liveness": (pb.ReportWorkerLivenessRequest, pb.Empty),
        "get_job_status": (pb.GetJobStatusRequest, pb.JobStatusResponse),
        "start_profile": (pb.StartProfileRequest, pb.StartProfileResponse),
        "report_telemetry": (
            pb.ReportTelemetryRequest,
            pb.ReportTelemetryResponse,
        ),
        # Policy plane: workers poll the announced next world so the AOT
        # speculator compiles it instead of guessing N±delta.
        "get_world_hint": (pb.GetWorldHintRequest, pb.WorldHintResponse),
    },
)

# Rank-0 worker state broadcast for elastic AllReduce regroups (the Horovod
# broadcast_variables analog — see elasticdl_tpu/parallel/broadcast.py).
COLLECTIVE_SERVICE = ServiceSpec(
    name="elasticdl_tpu.Collective",
    methods={"pull_model": (pb.PullDenseParametersRequest, pb.Model)},
)

PSERVER_SERVICE = ServiceSpec(
    name="elasticdl_tpu.Pserver",
    methods={
        "push_model": (pb.Model, pb.Empty),
        "push_embedding_table_infos": (pb.Model, pb.Empty),
        "pull_dense_parameters": (
            pb.PullDenseParametersRequest,
            pb.PullDenseParametersResponse,
        ),
        "pull_embedding_vectors": (pb.PullEmbeddingVectorsRequest, pb.Tensor),
        "pull_embedding_table": (
            pb.PullEmbeddingTableRequest,
            pb.IndexedSlices,
        ),
        "push_gradients": (pb.PushGradientsRequest, pb.PushGradientsResponse),
        # Out-of-band transport: slim span header + one contiguous payload
        # blob (clients may send a duck-typed tensor_utils.PackedPushRequest
        # that appends the payload without copying it through a proto
        # object — the Stub serializer is duck-typed for exactly this).
        "push_gradients_packed": (
            pb.PushGradientsPackedRequest,
            pb.PushGradientsResponse,
        ),
    },
)


# ---------- retry policy ----------

_RETRYABLE = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)
# Connectivity-only: non-idempotent methods must not replay a call that may
# have applied server-side before its deadline fired.
_RETRYABLE_CONNECTIVITY = (grpc.StatusCode.UNAVAILABLE,)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Deadline + retry classification for one RPC method."""

    deadline: float = 30.0
    max_attempts: int = 5
    backoff_base: float = 0.2
    backoff_multiplier: float = 2.0
    backoff_max: float = 5.0
    jitter: float = 0.5  # fraction of each backoff randomized away
    retryable_codes: tuple = _RETRYABLE

    def retryable(self, code):
        return code in self.retryable_codes

    def backoff(self, attempt, rng):
        """Sleep before retry number `attempt` (0-based). Full backoff minus
        a jittered fraction, so a fleet of workers hitting one restarted
        peer doesn't re-dogpile it in lockstep."""
        base = min(
            self.backoff_max,
            self.backoff_base * self.backoff_multiplier**attempt,
        )
        return base * (1.0 - self.jitter * rng.random())


# Per-method deadline/retry matrix (docs/ROBUSTNESS.md keeps the prose
# version). EVERY spec method must appear here — tools/check_rpc_deadlines.py
# fails the lint lane otherwise.
METHOD_POLICIES = {
    # Master service: small control messages; get_task answers WAIT rather
    # than blocking, so short deadlines are safe.
    "get_task": RetryPolicy(deadline=30.0),
    # Batched leases share get_task's semantics: a replayed lease at worst
    # strands tasks in _doing for the watchdog to recover, same as today.
    "get_task_batch": RetryPolicy(deadline=30.0),
    "report_task_result": RetryPolicy(deadline=30.0),
    # Duplicate reports are absorbed server-side (unknown/duplicate ids are
    # acknowledged and discarded), so the batch report retries like the
    # single-task one.
    "report_task_results": RetryPolicy(deadline=30.0),
    "report_evaluation_metrics": RetryPolicy(deadline=60.0),
    "report_version": RetryPolicy(deadline=30.0),
    "get_comm_rank": RetryPolicy(deadline=30.0),
    "lease_steps": RetryPolicy(deadline=30.0),
    "report_lease": RetryPolicy(deadline=30.0),
    "report_worker_liveness": RetryPolicy(deadline=30.0),
    "get_job_status": RetryPolicy(deadline=15.0),
    # Hint polls are periodic and read-only; a missed poll self-heals on
    # the next interval, so don't burn retry budget.
    "get_world_hint": RetryPolicy(deadline=10.0, max_attempts=2),
    # Telemetry pushes are periodic and self-healing (a lost snapshot is
    # resent as a full resync on the next interval), so a failed push is
    # never worth burning retry budget on: one connectivity retry, and a
    # timed-out push — which may have applied and bumped the seq server
    # side — must NOT replay (the replayed seq would read as a gap and
    # force a spurious full resync).
    "report_telemetry": RetryPolicy(
        deadline=15.0,
        max_attempts=2,
        retryable_codes=_RETRYABLE_CONNECTIVITY,
    ),
    # Profile fan-out blocks for the capture duration on every role; not
    # idempotent (each attempt burns a capture slot on every endpoint),
    # so a timed-out request is never replayed and connectivity failures
    # retry once.
    "start_profile": RetryPolicy(
        deadline=120.0,
        max_attempts=2,
        retryable_codes=_RETRYABLE_CONNECTIVITY,
    ),
    # Pserver service: payload-bearing; pushes that time out may have
    # applied, so only UNAVAILABLE replays them.
    "push_model": RetryPolicy(deadline=120.0),
    "push_embedding_table_infos": RetryPolicy(deadline=60.0),
    "pull_dense_parameters": RetryPolicy(deadline=60.0),
    "pull_embedding_vectors": RetryPolicy(deadline=60.0),
    "pull_embedding_table": RetryPolicy(deadline=120.0),
    "push_gradients": RetryPolicy(
        deadline=60.0, retryable_codes=_RETRYABLE_CONNECTIVITY
    ),
    # Same non-idempotence as push_gradients (a timed-out chunk may have
    # landed and counted toward the reassembly), with the same deadline:
    # chunking means each sub-request is bounded by THIS deadline instead
    # of one giant push needing a one-off larger budget.
    "push_gradients_packed": RetryPolicy(
        deadline=60.0, retryable_codes=_RETRYABLE_CONNECTIVITY
    ),
    # Collective service: a full model state pull during elastic regroup.
    # Deadline NOT retried: rejoin latency is the product being measured
    # there — a wedged rank-0 must surface after one budget, not five
    # (broadcast.pull_state shares one budget between probe and RPC).
    "pull_model": RetryPolicy(
        deadline=120.0, retryable_codes=_RETRYABLE_CONNECTIVITY
    ),
}

# Environment overrides (read once; reload_config() re-reads — used by tests
# and by drills that shrink deadlines to force retries):
#   ELASTICDL_RPC_DEADLINES        JSON {method: seconds}
#   ELASTICDL_RPC_MAX_ATTEMPTS     int, all methods
#   ELASTICDL_RPC_BACKOFF_BASE     float, all methods
#   ELASTICDL_RPC_BACKOFF_MAX     float, all methods
#   ELASTICDL_RPC_BREAKER_THRESHOLD  int (<=0 disables the breaker)
#   ELASTICDL_RPC_BREAKER_COOLDOWN   float seconds
#   ELASTICDL_RPC_READY_TIMEOUT      float seconds (0 disables ready-wait)
_config_lock = threading.Lock()
_policy_cache = None


def _load_policies():
    policies = dict(METHOD_POLICIES)
    overrides = {}
    raw = knobs.raw("ELASTICDL_RPC_DEADLINES")
    if raw:
        try:
            overrides = {
                str(k): float(v) for k, v in json.loads(raw).items()
            }
        except (ValueError, AttributeError):
            logger.warning("Bad ELASTICDL_RPC_DEADLINES %r; ignored", raw)
    changes = {}
    for env, field, cast in (
        ("ELASTICDL_RPC_MAX_ATTEMPTS", "max_attempts", int),
        ("ELASTICDL_RPC_BACKOFF_BASE", "backoff_base", float),
        ("ELASTICDL_RPC_BACKOFF_MAX", "backoff_max", float),
    ):
        raw = knobs.raw(env)
        if raw:
            try:
                changes[field] = cast(raw)
            except ValueError:
                logger.warning("Bad %s %r; ignored", env, raw)
    for method, policy in list(policies.items()):
        per = dict(changes)
        if method in overrides:
            per["deadline"] = overrides[method]
        if per:
            policies[method] = dataclasses.replace(policy, **per)
    return policies


def policy_for(method):
    """RetryPolicy for a full ("/pkg.Service/name") or short method name."""
    global _policy_cache
    with _config_lock:
        if _policy_cache is None:
            _policy_cache = _load_policies()
        return _policy_cache.get(
            method.rsplit("/", 1)[-1], RetryPolicy()
        )


def reload_config():
    """Re-read env overrides (tests / in-process drills). Live channels
    hold references to their peer's breaker, so breakers are re-tuned and
    reset IN PLACE — clearing the registry would split per-peer state
    between old channels and new ones."""
    global _policy_cache
    with _config_lock:
        _policy_cache = None
    threshold = knobs.get_int("ELASTICDL_RPC_BREAKER_THRESHOLD")
    cooldown = knobs.get_float("ELASTICDL_RPC_BREAKER_COOLDOWN")
    with _breakers_lock:
        for breaker in _breakers.values():
            with breaker._lock:
                breaker.threshold = threshold
                breaker.cooldown = cooldown
                breaker._state = CircuitBreaker.CLOSED
                breaker._failures = 0
                breaker._probing = False


def ready_timeout():
    """The channel-readiness probe budget (seconds) this process uses —
    the single accessor for ELASTICDL_RPC_READY_TIMEOUT, shared by
    build_channel and clients that probe on their own (PSClient)."""
    return knobs.get_float("ELASTICDL_RPC_READY_TIMEOUT")


# build_channel's `ready_timeout` PARAMETER shadows the accessor above;
# this alias keeps the accessor the single reader of the knob there.
_default_ready_timeout = ready_timeout


# ---------- synthetic call objects ----------


class SyntheticRpcError(grpc.RpcError, grpc.Call, grpc.Future):
    """A locally-manufactured failed call: raised by the circuit breaker's
    fast-fail path and by client-side chaos injection. Implements the
    Call/Future surface so it can stand in anywhere a real failed call
    object can."""

    def __init__(self, code, details):
        super().__init__()
        self._code = code
        self._details = details

    # grpc.Call
    def initial_metadata(self):
        return ()

    def trailing_metadata(self):
        return ()

    def code(self):
        return self._code

    def details(self):
        return self._details

    def is_active(self):
        return False

    def time_remaining(self):
        return 0.0

    def add_callback(self, callback):
        return False

    # grpc.Future
    def cancel(self):
        return False

    def cancelled(self):
        return False

    def running(self):
        return False

    def done(self):
        return True

    def result(self, timeout=None):
        raise self

    def exception(self, timeout=None):
        return self

    def traceback(self, timeout=None):
        return None

    def add_done_callback(self, fn):
        fn(self)

    def __str__(self):
        return f"SyntheticRpcError({self._code}, {self._details!r})"


class CircuitOpenError(SyntheticRpcError):
    def __init__(self, peer, method):
        super().__init__(
            grpc.StatusCode.UNAVAILABLE,
            f"circuit breaker open for peer {peer} (method {method})",
        )
        self.peer = peer


# ---------- circuit breaker ----------


class CircuitBreaker:
    """Per-peer consecutive-failure breaker.

    closed --(threshold consecutive connectivity failures)--> open
    open   --(cooldown elapsed)--> half-open (one probe admitted)
    half-open --probe success--> closed; --probe failure--> open again
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, peer, threshold=None, cooldown=None):
        self.peer = peer
        self.threshold = (
            threshold
            if threshold is not None
            else knobs.get_int("ELASTICDL_RPC_BREAKER_THRESHOLD")
        )
        self.cooldown = (
            cooldown
            if cooldown is not None
            else knobs.get_float("ELASTICDL_RPC_BREAKER_COOLDOWN")
        )
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._probe_started = 0.0

    @property
    def state(self):
        with self._lock:
            return self._state

    def allow(self):
        """May a call proceed right now? Transitions open -> half-open when
        the cooldown has elapsed; half-open admits exactly one probe."""
        if self.threshold <= 0:  # breaker disabled
            return True
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if time.time() - self._opened_at < self.cooldown:
                    return False
                self._state = self.HALF_OPEN
                self._probing = True
                self._probe_started = time.time()
                logger.info(
                    "Circuit for %s half-open; probing", self.peer
                )
                return True
            # HALF_OPEN: one probe in flight at a time — but a probe whose
            # outcome never reached record_* (caller crashed, outcome was
            # swallowed) must not wedge the breaker; re-admit after a
            # cooldown's worth of silence.
            if self._probing and (
                time.time() - self._probe_started < self.cooldown
            ):
                return False
            self._probing = True
            self._probe_started = time.time()
            return True

    def record_success(self):
        with self._lock:
            if self._state != self.CLOSED:
                logger.info("Circuit for %s closed again", self.peer)
            self._state = self.CLOSED
            self._failures = 0
            self._probing = False

    def record_failure(self):
        """One failed connectivity ATTEMPT (each retry counts — a dead
        peer whose every call burns 5 attempts trips after ~2 calls, which
        is the point: stop burning budgets fast. `threshold` is therefore
        consecutive failed attempts, not failed calls)."""
        if self.threshold <= 0:
            return
        with self._lock:
            self._failures += 1
            tripped = (
                self._state == self.HALF_OPEN
                or (
                    self._state == self.CLOSED
                    and self._failures >= self.threshold
                )
            )
            if tripped:
                self._state = self.OPEN
                self._opened_at = time.time()
                self._probing = False
                _TRIPS.labels(peer=self.peer).inc()
                logger.warning(
                    "Circuit for %s OPEN after %d consecutive failures "
                    "(cooldown %.1fs)",
                    self.peer,
                    self._failures,
                    self.cooldown,
                )


_breakers = {}
_breakers_lock = threading.Lock()


def breaker_for(peer):
    """The process-wide breaker for a peer address (shared by every channel
    to that peer, and consultable by clients e.g. PSClient degradation)."""
    with _breakers_lock:
        breaker = _breakers.get(peer)
        if breaker is None:
            breaker = CircuitBreaker(peer)
            _breakers[peer] = breaker
        return breaker


# ---------- retrying client interceptor ----------


class _CallDetails(grpc.ClientCallDetails):
    def __init__(self, base, timeout):
        self.method = base.method
        self.timeout = timeout
        self.metadata = base.metadata
        self.credentials = base.credentials
        self.wait_for_ready = getattr(base, "wait_for_ready", None)
        self.compression = getattr(base, "compression", None)


def _short(method):
    return method.rsplit("/", 1)[-1]


class _RetryingFuture:
    """Future returned for `stub.method.future(...)` calls: retries happen
    lazily inside result()/exception(), on the caller's thread, so a fan-out
    of N futures still overlaps its healthy peers while one retries.

    Contract caveat: done()/running()/cancel()/add_done_callback reflect
    the CURRENT attempt only — a first attempt that failed fast reads as
    done even though result() may still retry. In-repo callers harvest
    exclusively via result()/exception(); poll-style consumers should
    treat done() as advisory."""

    def __init__(self, interceptor, continuation, details, request, call,
                 policy, attempt):
        self._i = interceptor
        self._continuation = continuation
        self._details = details
        self._request = request
        self._call = call
        self._policy = policy
        self._attempt = attempt

    def result(self, timeout=None):
        while True:
            try:
                value = self._call.result(timeout)
            except grpc.RpcError as err:
                code = err.code() if hasattr(err, "code") else None
                retry = self._i.on_failure(
                    self._details, self._policy, code, self._attempt
                )
                if not retry:
                    raise
                self._attempt += 1
                self._call = self._i.reissue(
                    self._continuation, self._details, self._request
                )
                continue
            self._i.on_success(self._details)
            return value

    def exception(self, timeout=None):
        try:
            self.result(timeout)
            return None
        except grpc.RpcError as err:
            return err

    def done(self):
        return self._call.done()

    def running(self):
        return self._call.running()

    def cancelled(self):
        return self._call.cancelled()

    def cancel(self):
        return self._call.cancel()

    def code(self):
        return self._call.code()

    def details(self):
        return self._call.details()

    def add_done_callback(self, fn):
        self._call.add_done_callback(lambda _c: fn(self))

    def traceback(self, timeout=None):
        return self._call.traceback(timeout)


class RetryingClientInterceptor(grpc.UnaryUnaryClientInterceptor):
    """Outermost interceptor on every built channel: injects the
    per-method default deadline, classifies failures against the method's
    RetryPolicy, retries with jittered exponential backoff, and consults
    the peer's circuit breaker (fail-fast when open)."""

    def __init__(self, peer, rng=None):
        self._peer = peer
        self._breaker = breaker_for(peer)
        self._rng = rng if rng is not None else random.Random()
        self._rng_lock = threading.Lock()

    # -- shared retry machinery (used by the blocking path and the future
    # wrapper) --

    def on_success(self, details):
        self._breaker.record_success()

    def on_failure(self, details, policy, code, attempt):
        """Bookkeep one failed attempt; True when the caller should retry
        (after this method has slept the backoff)."""
        method = _short(details.method)
        connectivity = code in _RETRYABLE
        if connectivity:
            self._breaker.record_failure()
        elif code is not None:
            # A non-connectivity status (INVALID_ARGUMENT, INTERNAL, ...)
            # means the peer ANSWERED: connectivity-wise that's a success,
            # and it must release a half-open probe instead of wedging it.
            self._breaker.record_success()
        if (
            code is None
            or not policy.retryable(code)
            or attempt >= policy.max_attempts - 1
        ):
            _FAILURES.labels(
                method=method, code=getattr(code, "name", str(code))
            ).inc()
            return False
        if not self._breaker.allow():
            # Peer declared down mid-retry: stop burning the budget.
            _FAILURES.labels(method=method, code="BREAKER_OPEN").inc()
            return False
        _RETRIES.labels(method=method).inc()
        with self._rng_lock:
            delay = policy.backoff(attempt, self._rng)
        logger.debug(
            "Retrying %s to %s in %.2fs (attempt %d, %s)",
            method,
            self._peer,
            delay,
            attempt + 2,
            code,
        )
        time.sleep(delay)
        return True

    def reissue(self, continuation, details, request):
        try:
            return continuation(details, request)
        except grpc.RpcError as err:
            return err if _is_call(err) else _as_call(err)

    # -- interceptor entry point --

    def intercept_unary_unary(self, continuation, details, request):
        policy = policy_for(details.method)
        if details.timeout is None and policy.deadline > 0:
            details = _CallDetails(details, policy.deadline)
        if not self._breaker.allow():
            # RETURN the failed call rather than raising: grpc invokes
            # this interceptor synchronously even for `.future()` calls,
            # and a raise there would explode out of a fan-out's
            # future-creation loop (e.g. PSClient's per-shard
            # comprehensions) instead of reaching its per-future
            # mark-degraded handling. Blocking callers still see the
            # exception — the machinery calls result(), which raises it.
            _FAST_FAILS.labels(peer=self._peer).inc()
            return CircuitOpenError(self._peer, _short(details.method))
        call = self.reissue(continuation, details, request)
        if call.done():
            code = call.code()
            if code is None or code == grpc.StatusCode.OK:
                self.on_success(details)
                return call
        # Failed-or-in-flight first attempt: ALL retrying happens lazily
        # inside the wrapper's result(). Blocking callers reach it
        # immediately (the interceptor machinery calls result()); a
        # fan-out's future() calls return instantly even when the first
        # attempt already failed synchronously (client-side chaos, fast
        # connection refusal) — retrying inline here would serialize the
        # fan-out with this thread's backoff sleeps.
        return _RetryingFuture(
            self, continuation, details, request, call, policy, 0
        )


def _is_call(err):
    return hasattr(err, "done") and hasattr(err, "result")


def _as_call(err):
    code = err.code() if hasattr(err, "code") else grpc.StatusCode.UNKNOWN
    details = err.details() if hasattr(err, "details") else str(err)
    return SyntheticRpcError(code, details)


# ---------- stubs / servers / channels ----------


class Stub:
    """Client stub: one callable attribute per spec method."""

    def __init__(self, channel: grpc.Channel, spec: ServiceSpec):
        for method, (req_cls, resp_cls) in spec.methods.items():
            setattr(
                self,
                method,
                channel.unary_unary(
                    f"/{spec.name}/{method}",
                    # Duck-typed on purpose (not req_cls.SerializeToString):
                    # out-of-band requests (tensor_utils.PackedPushRequest)
                    # serialize themselves by joining the header with raw
                    # payload views instead of round-tripping the bytes
                    # through a proto message.
                    request_serializer=lambda m: m.SerializeToString(),
                    response_deserializer=resp_cls.FromString,
                ),
            )


def add_servicer_to_server(servicer, spec: ServiceSpec, server: grpc.Server):
    """Register servicer methods (matched by name) for the spec's service."""
    handlers = {}
    for method, (req_cls, resp_cls) in spec.methods.items():
        handlers[method] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, method),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(spec.name, handlers),)
    )


def _chaos_server_interceptors(chaos):
    if chaos is None:
        from elasticdl_tpu.chaos import injection

        chaos = injection.schedule_from_env()
    if chaos is None:
        return ()
    from elasticdl_tpu.chaos import injection

    return (injection.ChaosServerInterceptor(chaos),)


def build_server(max_workers: int = 64, chaos=None) -> grpc.Server:
    # The tracing interceptor propagates edl-trace-* metadata into each
    # handler's context and records server spans once a recorder is
    # configured (observability.setup); unconfigured it costs one dict
    # lookup per RPC. The chaos interceptor (configured runs only) sits
    # inside tracing so injected faults still show up in traces.
    return grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=max_workers),
        options=GRPC_CHANNEL_OPTIONS,
        interceptors=(
            tracing.TracingServerInterceptor(),
            *_chaos_server_interceptors(chaos),
        ),
    )


def serve(servicer, spec: ServiceSpec, port: int = 0, max_workers: int = 64,
          chaos=None):
    """Start a server for one servicer; returns (server, bound_port)."""
    server = build_server(max_workers, chaos=chaos)
    add_servicer_to_server(servicer, spec, server)
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise RuntimeError(f"failed to bind gRPC server to port {port}")
    server.start()
    return server, bound


def wait_channel_ready(addr, timeout, abort_check=None):
    """TCP-probe `addr` until it accepts a connection or `timeout` elapses.
    Returns True when the peer accepted. abort_check() returning True ends
    the wait early (e.g. "the subprocess that should bind this port died")."""
    host, _, port = addr.rpartition(":")
    host = host or "127.0.0.1"
    try:
        port = int(port)
    except ValueError:
        return False
    deadline = time.time() + timeout
    while time.time() < deadline:
        if abort_check is not None and abort_check():
            return False
        try:
            probe = socket.create_connection((host, port), timeout=1)
            probe.close()
            return True
        except OSError:
            time.sleep(0.2)
    return False


def build_channel(addr: str, ready_timeout=None, chaos=None) -> grpc.Channel:
    """A hardened channel to `addr`: readiness-waited, then interceptor
    stack [retry/deadline/breaker -> tracing -> chaos? -> wire].

    ready_timeout: seconds to TCP-probe before opening (None reads
    ELASTICDL_RPC_READY_TIMEOUT via rpc.ready_timeout(), default 30; 0
    skips the probe). On probe timeout the channel is still built — the
    retry plane owns the failure from there."""
    if ready_timeout is None:
        # (the module-level ready_timeout() accessor; the parameter
        # shadows its name here)
        ready_timeout = _default_ready_timeout()
    if ready_timeout > 0:
        if not wait_channel_ready(addr, ready_timeout):
            logger.warning(
                "Peer %s not accepting connections after %.1fs; opening "
                "the channel anyway (retries/breaker take over)",
                addr,
                ready_timeout,
            )
    channel = grpc.insecure_channel(addr, options=GRPC_CHANNEL_OPTIONS)
    # grpc.intercept_channel invokes the FIRST listed interceptor first
    # (outermost). Order: retry (outermost, so every attempt re-runs the
    # inner stack) -> tracing (each attempt records its own client span,
    # and trace-context injection rides every retry so one task's RPC
    # chain shares a trace id across processes) -> chaos (innermost,
    # closest to the wire — injected faults look like the network).
    interceptors = [RetryingClientInterceptor(addr)]
    interceptors.append(tracing.TracingClientInterceptor())
    if chaos is None:
        from elasticdl_tpu.chaos import injection

        chaos = injection.schedule_from_env()
    if chaos is not None:
        from elasticdl_tpu.chaos import injection

        interceptors.append(injection.ChaosClientInterceptor(chaos))
    return grpc.intercept_channel(channel, *interceptors)
