"""Spec-driven gRPC stubs and servicer registration.

The image has protoc but no grpc python plugin, so instead of codegen'd
`*_pb2_grpc.py` files each service is declared once as a ServiceSpec table and
both the client stub and the server handler are built from it generically.
Method set mirrors the reference's Master and Pserver services
(/root/reference/elasticdl/proto/elasticdl.proto:108-157).
"""

import concurrent.futures
import dataclasses

import grpc

from elasticdl_tpu.observability import tracing
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

# Matches the reference's 256 MB gRPC message cap
# (/root/reference/elasticdl/python/common/constants.py:15-19).
MAX_MESSAGE_LENGTH = 256 * 1024 * 1024

GRPC_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", MAX_MESSAGE_LENGTH),
]


@dataclasses.dataclass(frozen=True)
class ServiceSpec:
    name: str
    # method name -> (request class, response class)
    methods: dict


MASTER_SERVICE = ServiceSpec(
    name="elasticdl_tpu.Master",
    methods={
        "get_task": (pb.GetTaskRequest, pb.Task),
        "report_task_result": (pb.ReportTaskResultRequest, pb.Empty),
        "report_evaluation_metrics": (pb.ReportEvaluationMetricsRequest, pb.Empty),
        "report_version": (pb.ReportVersionRequest, pb.Empty),
        "get_comm_rank": (pb.GetCommRankRequest, pb.GetCommRankResponse),
        "lease_steps": (pb.LeaseStepsRequest, pb.LeaseStepsResponse),
        "report_lease": (pb.ReportLeaseRequest, pb.Empty),
        "report_worker_liveness": (pb.ReportWorkerLivenessRequest, pb.Empty),
        "get_job_status": (pb.GetJobStatusRequest, pb.JobStatusResponse),
    },
)

# Rank-0 worker state broadcast for elastic AllReduce regroups (the Horovod
# broadcast_variables analog — see elasticdl_tpu/parallel/broadcast.py).
COLLECTIVE_SERVICE = ServiceSpec(
    name="elasticdl_tpu.Collective",
    methods={"pull_model": (pb.PullDenseParametersRequest, pb.Model)},
)

PSERVER_SERVICE = ServiceSpec(
    name="elasticdl_tpu.Pserver",
    methods={
        "push_model": (pb.Model, pb.Empty),
        "push_embedding_table_infos": (pb.Model, pb.Empty),
        "pull_dense_parameters": (
            pb.PullDenseParametersRequest,
            pb.PullDenseParametersResponse,
        ),
        "pull_embedding_vectors": (pb.PullEmbeddingVectorsRequest, pb.Tensor),
        "pull_embedding_table": (
            pb.PullEmbeddingTableRequest,
            pb.IndexedSlices,
        ),
        "push_gradients": (pb.PushGradientsRequest, pb.PushGradientsResponse),
    },
)


class Stub:
    """Client stub: one callable attribute per spec method."""

    def __init__(self, channel: grpc.Channel, spec: ServiceSpec):
        for method, (req_cls, resp_cls) in spec.methods.items():
            setattr(
                self,
                method,
                channel.unary_unary(
                    f"/{spec.name}/{method}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )


def add_servicer_to_server(servicer, spec: ServiceSpec, server: grpc.Server):
    """Register servicer methods (matched by name) for the spec's service."""
    handlers = {}
    for method, (req_cls, resp_cls) in spec.methods.items():
        handlers[method] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, method),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(spec.name, handlers),)
    )


def build_server(max_workers: int = 64) -> grpc.Server:
    # The tracing interceptor propagates edl-trace-* metadata into each
    # handler's context and records server spans once a recorder is
    # configured (observability.setup); unconfigured it costs one dict
    # lookup per RPC.
    return grpc.server(
        concurrent.futures.ThreadPoolExecutor(max_workers=max_workers),
        options=GRPC_CHANNEL_OPTIONS,
        interceptors=(tracing.TracingServerInterceptor(),),
    )


def serve(servicer, spec: ServiceSpec, port: int = 0, max_workers: int = 64):
    """Start a server for one servicer; returns (server, bound_port)."""
    server = build_server(max_workers)
    add_servicer_to_server(servicer, spec, server)
    bound = server.add_insecure_port(f"[::]:{port}")
    if bound == 0:
        raise RuntimeError(f"failed to bind gRPC server to port {port}")
    server.start()
    return server, bound


def build_channel(addr: str) -> grpc.Channel:
    channel = grpc.insecure_channel(addr, options=GRPC_CHANNEL_OPTIONS)
    # Trace-context injection rides every channel so one task's RPC chain
    # (dispatch -> pull -> train -> push -> report) shares a trace id
    # across processes.
    return grpc.intercept_channel(
        channel, tracing.TracingClientInterceptor()
    )
