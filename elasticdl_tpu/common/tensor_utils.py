"""numpy <-> proto tensor codec.

Self-owned replacement for the reference's TF-TensorProto-based codec
(/root/reference/elasticdl/python/common/tensor_utils.py:63-122): tensors go
on the wire as (dtype enum, dims, raw little-endian bytes). bfloat16 is a
first-class dtype (via ml_dtypes) because it is the native TPU matmul type.
"""

import numpy as np
from ml_dtypes import bfloat16

from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

_NP_TO_PB = {
    np.dtype(np.float32): pb.DT_FLOAT32,
    np.dtype(np.float64): pb.DT_FLOAT64,
    np.dtype(np.float16): pb.DT_FLOAT16,
    np.dtype(bfloat16): pb.DT_BFLOAT16,
    np.dtype(np.int8): pb.DT_INT8,
    np.dtype(np.int16): pb.DT_INT16,
    np.dtype(np.int32): pb.DT_INT32,
    np.dtype(np.int64): pb.DT_INT64,
    np.dtype(np.uint8): pb.DT_UINT8,
    np.dtype(np.uint32): pb.DT_UINT32,
    np.dtype(np.uint64): pb.DT_UINT64,
    np.dtype(np.bool_): pb.DT_BOOL,
}
_PB_TO_NP = {v: k for k, v in _NP_TO_PB.items()}


def np_dtype_to_pb(dtype) -> int:
    try:
        return _NP_TO_PB[np.dtype(dtype)]
    except KeyError:
        raise ValueError(f"unsupported dtype for wire transfer: {dtype}")


def pb_dtype_to_np(dtype_enum: int) -> np.dtype:
    try:
        return _PB_TO_NP[dtype_enum]
    except KeyError:
        raise ValueError(f"unknown wire dtype enum: {dtype_enum}")


def _is_string_array(arr):
    if arr.dtype.kind in ("U", "S", "T"):
        return True
    if arr.dtype.kind == "O":
        # Object arrays are accepted ONLY when they hold text/bytes — a
        # numeric/ragged object array must keep the loud unsupported-dtype
        # error instead of serializing reprs.
        if arr.size == 0:
            return True
        if all(
            isinstance(s, (str, bytes)) for s in arr.reshape(-1)
        ):
            return True
        raise ValueError(
            "object-dtype array holds non-string elements; convert to a "
            "numeric dtype before wire transfer"
        )
    return False


def ndarray_to_tensor_pb(arr: np.ndarray, name: str = "") -> pb.Tensor:
    arr = np.asarray(arr)  # not ascontiguousarray: that promotes 0-d to 1-d
    if _is_string_array(arr):
        # Variable-length text/bytes: concatenated payload + per-element
        # lengths (the reference carries these as TF bytes features). ONE
        # wire type per tensor: any bytes element makes the whole tensor
        # DT_BYTES (every element decodes as bytes), otherwise DT_STRING
        # (every element decodes as str) — never content-dependent mixes.
        flat = list(arr.reshape(-1))
        any_bytes = any(isinstance(s, bytes) for s in flat) or (
            arr.dtype.kind == "S"
        )
        encoded = [
            s if isinstance(s, bytes) else str(s).encode("utf-8")
            for s in flat
        ]
        return pb.Tensor(
            name=name,
            dims=list(arr.shape),
            dtype=pb.DT_BYTES if any_bytes else pb.DT_STRING,
            content=b"".join(encoded),
            string_lengths=[len(e) for e in encoded],
        )
    return pb.Tensor(
        name=name,
        dims=list(arr.shape),
        dtype=np_dtype_to_pb(arr.dtype),
        content=arr.tobytes(),
    )


def tensor_pb_to_ndarray(tensor_pb: pb.Tensor) -> np.ndarray:
    if tensor_pb.dtype in (pb.DT_STRING, pb.DT_BYTES):
        as_bytes = tensor_pb.dtype == pb.DT_BYTES
        parts, offset = [], 0
        for length in tensor_pb.string_lengths:
            raw = tensor_pb.content[offset:offset + length]
            if as_bytes:
                parts.append(raw)
            else:
                try:
                    parts.append(raw.decode("utf-8"))
                except UnicodeDecodeError:
                    # Record files written before DT_BYTES existed stored
                    # binary features as DT_STRING; keep reading them.
                    parts.append(raw)
            offset += length
        return np.asarray(parts, dtype=object).reshape(
            tuple(tensor_pb.dims)
        )
    dtype = pb_dtype_to_np(tensor_pb.dtype)
    arr = np.frombuffer(tensor_pb.content, dtype=dtype)
    return arr.reshape(tuple(tensor_pb.dims)).copy()


def ndarray_to_indexed_slices_pb(
    values: np.ndarray, ids: np.ndarray, name: str = ""
) -> pb.IndexedSlices:
    if values.ndim != 2 or len(ids) != values.shape[0]:
        raise ValueError(
            f"IndexedSlices needs values [len(ids), dim]; "
            f"got values {values.shape}, {len(ids)} ids"
        )
    return pb.IndexedSlices(
        concat_tensors=ndarray_to_tensor_pb(values, name),
        ids_bytes=np.ascontiguousarray(ids, dtype=np.int64).tobytes(),
    )


def indexed_slices_pb_to_ndarrays(slices_pb: pb.IndexedSlices):
    values = tensor_pb_to_ndarray(slices_pb.concat_tensors)
    if slices_pb.ids_bytes:
        ids = np.frombuffer(slices_pb.ids_bytes, dtype=np.int64)
    else:  # older writers used the repeated form
        ids = np.asarray(slices_pb.ids, dtype=np.int64)
    return values, ids


def ids_to_bytes(ids: np.ndarray) -> bytes:
    """Embedding ids -> raw little-endian int64 bytes (the preferred wire
    form of every ids field; see IndexedSlices.ids_bytes). The single
    place id byte layout is decided — the wire-codec lint rule rejects
    ad-hoc tobytes()/frombuffer on proto fields elsewhere."""
    return np.ascontiguousarray(ids, dtype=np.int64).tobytes()


def ids_from_bytes(buf) -> np.ndarray:
    """Raw little-endian int64 id bytes -> ndarray VIEW (no copy)."""
    return np.frombuffer(buf, dtype=np.int64)


# ---------------------------------------------------------------------------
# int8 block-scaled codec (EQuARX-style, arxiv 2506.17615)
# ---------------------------------------------------------------------------

DEFAULT_INT8_BLOCK = 256


def quantize_int8_blocks(arr, block_size=DEFAULT_INT8_BLOCK):
    """float array -> (int8 flat [n], float32 scales [ceil(n/block)]).

    Per-block absmax scaling: scale = max(|x|)/127 over each block of
    ``block_size`` consecutive elements (row-major), q = round(x/scale).
    An all-zero block gets scale 0 and decodes to exact zeros. Max
    per-element round-trip error is scale/2 (pinned by tests); callers
    that push gradients keep the error out of the training trajectory
    with error feedback (worker/ps_client.py)."""
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    flat = np.ascontiguousarray(arr, dtype=np.float32).reshape(-1)
    n = flat.size
    if n == 0:
        return np.empty(0, np.int8), np.empty(0, np.float32)
    nblocks = -(-n // block_size)
    nfull = nblocks * block_size
    padded = flat
    if nfull != n:
        padded = np.zeros(nfull, np.float32)
        padded[:n] = flat
    blocks = padded.reshape(nblocks, block_size)
    scales = np.abs(blocks).max(axis=1) / 127.0
    inv = np.zeros_like(scales)
    np.divide(1.0, scales, out=inv, where=scales > 0)
    q = np.rint(blocks * inv[:, None]).astype(np.int8)
    return q.reshape(-1)[:n], scales.astype(np.float32)


def dequantize_int8_blocks(q, scales, block_size=DEFAULT_INT8_BLOCK):
    """Inverse of quantize_int8_blocks -> float32 flat [n]."""
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    q = np.asarray(q, dtype=np.int8)
    scales = np.asarray(scales, dtype=np.float32)
    n = q.size
    if n == 0:
        return np.empty(0, np.float32)
    nblocks = -(-n // block_size)
    if nblocks != scales.size:
        raise ValueError(
            f"{n} quantized elements at block {block_size} need "
            f"{nblocks} scales, got {scales.size}"
        )
    nfull = nblocks * block_size
    padded = q
    if nfull != n:
        padded = np.zeros(nfull, np.int8)
        padded[:n] = q
    out = padded.reshape(nblocks, block_size).astype(np.float32)
    out *= scales[:, None]
    return out.reshape(-1)[:n]


# ---------------------------------------------------------------------------
# out-of-band (packed) tensor transport
# ---------------------------------------------------------------------------
#
# The packed push replaces per-tensor `content=arr.tobytes()` proto
# assembly with a slim span header plus ONE contiguous payload. The
# client never materializes the payload as an intermediate buffer:
# PackedPayload keeps zero-copy byte views over the source arrays and
# PackedPushRequest.SerializeToString joins header + parts directly into
# the wire buffer — a single host copy between device_get and gRPC,
# where the proto path paid tobytes + message CopyFrom + serialize.
# The receiver decodes spans as np.frombuffer views into the received
# bytes: nothing is copied until the optimizer apply consumes the data.

# field 12, wire type 2 (length-delimited): (12 << 3) | 2.
_PACKED_PAYLOAD_TAG = bytes([(12 << 3) | 2])


def _encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _byte_view(arr: np.ndarray) -> memoryview:
    """Zero-copy uint8 view of a C-contiguous array's bytes."""
    return memoryview(
        np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
    )


class PackedPayload:
    """Ordered zero-copy byte parts forming one contiguous payload."""

    def __init__(self):
        self._parts = []
        self.nbytes = 0

    def add_array(self, arr) -> tuple:
        """Append an array's bytes; returns (offset, nbytes). Keeps a
        VIEW over the array — the caller must not mutate it before the
        request serializes."""
        view = _byte_view(arr)
        offset = self.nbytes
        self._parts.append(view)
        self.nbytes += len(view)
        return offset, len(view)

    @property
    def parts(self):
        return list(self._parts)

    def slice_parts(self, start: int, end: int):
        """Zero-copy views covering payload bytes [start, end) — the
        chunked-push splitter."""
        out, pos = [], 0
        for part in self._parts:
            plen = len(part)
            lo, hi = max(start, pos), min(end, pos + plen)
            if lo < hi:
                out.append(part[lo - pos:hi - pos])
            pos += plen
            if pos >= end:
                break
        return out


def pack_tensor_span(name, arr, payload: PackedPayload,
                     wire_dtype=None, block_size=0) -> pb.TensorSpan:
    """Append one tensor to the payload; returns its TensorSpan header.

    wire_dtype "int8" block-quantizes (use pack_quantized_span when the
    caller quantized itself, e.g. for error feedback); any other value
    ships the array's own dtype byte-exact."""
    arr = np.asarray(arr)
    if wire_dtype == "int8":
        q, scales = quantize_int8_blocks(
            arr, block_size or DEFAULT_INT8_BLOCK
        )
        return pack_quantized_span(
            name, arr.shape, q, scales,
            block_size or DEFAULT_INT8_BLOCK, payload,
        )
    span = pb.TensorSpan(
        name=name, dims=list(arr.shape), dtype=np_dtype_to_pb(arr.dtype)
    )
    span.offset, span.nbytes = payload.add_array(arr)
    return span


def pack_quantized_span(name, shape, q, scales, block_size,
                        payload: PackedPayload) -> pb.TensorSpan:
    span = pb.TensorSpan(
        name=name, dims=list(shape), dtype=pb.DT_INT8,
        block_size=int(block_size),
    )
    span.offset, span.nbytes = payload.add_array(q)
    span.scales_offset, span.scales_nbytes = payload.add_array(scales)
    return span


def pack_slices_span(name, values, ids,
                     payload: PackedPayload) -> pb.SlicesSpan:
    """Sparse rows (values [k, dim] + int64 ids [k]) into the payload."""
    span = pb.SlicesSpan()
    span.values.CopyFrom(pack_tensor_span(name, values, payload))
    span.ids_offset, span.ids_nbytes = payload.add_array(
        np.ascontiguousarray(ids, dtype=np.int64)
    )
    return span


def _payload_view(buf, offset, nbytes, dtype, what):
    if offset < 0 or nbytes < 0 or offset + nbytes > len(buf):
        raise ValueError(
            f"packed {what} range [{offset}, {offset + nbytes}) outside "
            f"the {len(buf)}-byte payload (truncated or corrupt push)"
        )
    dtype = np.dtype(dtype)
    if nbytes % dtype.itemsize:
        raise ValueError(
            f"packed {what}: {nbytes} bytes is not a multiple of "
            f"{dtype} itemsize"
        )
    return np.frombuffer(buf, dtype=dtype, count=nbytes // dtype.itemsize,
                         offset=offset)


def unpack_tensor_span(span: pb.TensorSpan, payload_buf) -> np.ndarray:
    """TensorSpan -> ndarray. f32/bf16/... spans come back as read-only
    VIEWS into payload_buf (zero copy); int8 block-quantized spans
    dequantize here — the receive path's only materialization. Raises
    ValueError on any out-of-bounds range (truncated payload)."""
    buf = memoryview(payload_buf)
    if span.scales_nbytes:
        q = _payload_view(
            buf, span.offset, span.nbytes, np.int8, f"span {span.name!r}"
        )
        scales = _payload_view(
            buf, span.scales_offset, span.scales_nbytes, np.float32,
            f"span {span.name!r} scales",
        )
        flat = dequantize_int8_blocks(
            q, scales, span.block_size or DEFAULT_INT8_BLOCK
        )
    else:
        flat = _payload_view(
            buf, span.offset, span.nbytes, pb_dtype_to_np(span.dtype),
            f"span {span.name!r}",
        )
    shape = tuple(span.dims)
    expected = 1
    for d in shape:
        expected *= int(d)
    if flat.size != expected:
        raise ValueError(
            f"span {span.name!r}: {flat.size} elements cannot fill "
            f"shape {shape}"
        )
    return flat.reshape(shape)


def unpack_slices_span(span: pb.SlicesSpan, payload_buf):
    """SlicesSpan -> (values [k, dim], ids [k] int64), both views where
    the dtype allows (see unpack_tensor_span)."""
    values = unpack_tensor_span(span.values, payload_buf)
    ids = _payload_view(
        memoryview(payload_buf), span.ids_offset, span.ids_nbytes,
        np.int64, f"slices {span.values.name!r} ids",
    )
    if values.ndim != 2 or ids.size != values.shape[0]:
        raise ValueError(
            f"slices {span.values.name!r}: {ids.size} ids for values "
            f"{values.shape}"
        )
    return values, ids


class PackedPushRequest:
    """Duck-typed gRPC request: slim header proto + out-of-band payload.

    rpc.Stub serializes requests via ``.SerializeToString()``, so this
    object can stand in for a pb.PushGradientsPackedRequest: it emits
    the serialized header followed by the payload field's wire bytes
    (tag, varint length, raw parts) — valid proto3 wire format, decoded
    by the ordinary FromString on the server. ``header`` must leave
    ``payload`` unset."""

    def __init__(self, header, parts, nbytes):
        self._header = header
        self._parts = parts
        self._nbytes = int(nbytes)

    def SerializeToString(self) -> bytes:  # noqa: N802 (grpc contract)
        head = self._header.SerializeToString()
        if not self._nbytes:
            return head
        return b"".join(
            [head, _PACKED_PAYLOAD_TAG, _encode_varint(self._nbytes)]
            + list(self._parts)
        )


def merge_indexed_slices(values_list, ids_list):
    """Concatenate sparse updates, then sum duplicate ids.

    Equivalent of the reference's merge_indexed_slices + deduplicate
    (/root/reference/elasticdl/python/common/tensor_utils.py:24-60), done
    vectorized with np.unique instead of a python dict loop.
    """
    values = np.concatenate(values_list, axis=0)
    ids = np.concatenate(ids_list, axis=0)
    return deduplicate_indexed_slices(values, ids)


def deduplicate_indexed_slices(values: np.ndarray, ids: np.ndarray):
    from elasticdl_tpu import native

    lib = native.lib()
    if (
        lib is not None
        and values.ndim == 2
        and values.dtype == np.float32
        and len(ids)
    ):
        values = np.ascontiguousarray(values)
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        out_ids = np.empty(len(ids), dtype=np.int64)
        out_values = np.empty_like(values)
        n = lib.edl_dedup_sum(
            native._i64p(ids), native._f32p(values), len(ids),
            values.shape[1], native._i64p(out_ids),
            native._f32p(out_values),
        )
        return out_values[:n], out_ids[:n]
    unique_ids, inverse = np.unique(ids, return_inverse=True)
    summed = np.zeros((len(unique_ids),) + values.shape[1:], dtype=values.dtype)
    np.add.at(summed, inverse, values)
    return summed, unique_ids
