"""numpy <-> proto tensor codec.

Self-owned replacement for the reference's TF-TensorProto-based codec
(/root/reference/elasticdl/python/common/tensor_utils.py:63-122): tensors go
on the wire as (dtype enum, dims, raw little-endian bytes). bfloat16 is a
first-class dtype (via ml_dtypes) because it is the native TPU matmul type.
"""

import numpy as np
from ml_dtypes import bfloat16

from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

_NP_TO_PB = {
    np.dtype(np.float32): pb.DT_FLOAT32,
    np.dtype(np.float64): pb.DT_FLOAT64,
    np.dtype(np.float16): pb.DT_FLOAT16,
    np.dtype(bfloat16): pb.DT_BFLOAT16,
    np.dtype(np.int8): pb.DT_INT8,
    np.dtype(np.int16): pb.DT_INT16,
    np.dtype(np.int32): pb.DT_INT32,
    np.dtype(np.int64): pb.DT_INT64,
    np.dtype(np.uint8): pb.DT_UINT8,
    np.dtype(np.uint32): pb.DT_UINT32,
    np.dtype(np.uint64): pb.DT_UINT64,
    np.dtype(np.bool_): pb.DT_BOOL,
}
_PB_TO_NP = {v: k for k, v in _NP_TO_PB.items()}


def np_dtype_to_pb(dtype) -> int:
    try:
        return _NP_TO_PB[np.dtype(dtype)]
    except KeyError:
        raise ValueError(f"unsupported dtype for wire transfer: {dtype}")


def pb_dtype_to_np(dtype_enum: int) -> np.dtype:
    try:
        return _PB_TO_NP[dtype_enum]
    except KeyError:
        raise ValueError(f"unknown wire dtype enum: {dtype_enum}")


def _is_string_array(arr):
    if arr.dtype.kind in ("U", "S", "T"):
        return True
    if arr.dtype.kind == "O":
        # Object arrays are accepted ONLY when they hold text/bytes — a
        # numeric/ragged object array must keep the loud unsupported-dtype
        # error instead of serializing reprs.
        if arr.size == 0:
            return True
        if all(
            isinstance(s, (str, bytes)) for s in arr.reshape(-1)
        ):
            return True
        raise ValueError(
            "object-dtype array holds non-string elements; convert to a "
            "numeric dtype before wire transfer"
        )
    return False


def ndarray_to_tensor_pb(arr: np.ndarray, name: str = "") -> pb.Tensor:
    arr = np.asarray(arr)  # not ascontiguousarray: that promotes 0-d to 1-d
    if _is_string_array(arr):
        # Variable-length text/bytes: concatenated payload + per-element
        # lengths (the reference carries these as TF bytes features). ONE
        # wire type per tensor: any bytes element makes the whole tensor
        # DT_BYTES (every element decodes as bytes), otherwise DT_STRING
        # (every element decodes as str) — never content-dependent mixes.
        flat = list(arr.reshape(-1))
        any_bytes = any(isinstance(s, bytes) for s in flat) or (
            arr.dtype.kind == "S"
        )
        encoded = [
            s if isinstance(s, bytes) else str(s).encode("utf-8")
            for s in flat
        ]
        return pb.Tensor(
            name=name,
            dims=list(arr.shape),
            dtype=pb.DT_BYTES if any_bytes else pb.DT_STRING,
            content=b"".join(encoded),
            string_lengths=[len(e) for e in encoded],
        )
    return pb.Tensor(
        name=name,
        dims=list(arr.shape),
        dtype=np_dtype_to_pb(arr.dtype),
        content=arr.tobytes(),
    )


def tensor_pb_to_ndarray(tensor_pb: pb.Tensor) -> np.ndarray:
    if tensor_pb.dtype in (pb.DT_STRING, pb.DT_BYTES):
        as_bytes = tensor_pb.dtype == pb.DT_BYTES
        parts, offset = [], 0
        for length in tensor_pb.string_lengths:
            raw = tensor_pb.content[offset:offset + length]
            if as_bytes:
                parts.append(raw)
            else:
                try:
                    parts.append(raw.decode("utf-8"))
                except UnicodeDecodeError:
                    # Record files written before DT_BYTES existed stored
                    # binary features as DT_STRING; keep reading them.
                    parts.append(raw)
            offset += length
        return np.asarray(parts, dtype=object).reshape(
            tuple(tensor_pb.dims)
        )
    dtype = pb_dtype_to_np(tensor_pb.dtype)
    arr = np.frombuffer(tensor_pb.content, dtype=dtype)
    return arr.reshape(tuple(tensor_pb.dims)).copy()


def ndarray_to_indexed_slices_pb(
    values: np.ndarray, ids: np.ndarray, name: str = ""
) -> pb.IndexedSlices:
    if values.ndim != 2 or len(ids) != values.shape[0]:
        raise ValueError(
            f"IndexedSlices needs values [len(ids), dim]; "
            f"got values {values.shape}, {len(ids)} ids"
        )
    return pb.IndexedSlices(
        concat_tensors=ndarray_to_tensor_pb(values, name),
        ids_bytes=np.ascontiguousarray(ids, dtype=np.int64).tobytes(),
    )


def indexed_slices_pb_to_ndarrays(slices_pb: pb.IndexedSlices):
    values = tensor_pb_to_ndarray(slices_pb.concat_tensors)
    if slices_pb.ids_bytes:
        ids = np.frombuffer(slices_pb.ids_bytes, dtype=np.int64)
    else:  # older writers used the repeated form
        ids = np.asarray(slices_pb.ids, dtype=np.int64)
    return values, ids


def merge_indexed_slices(values_list, ids_list):
    """Concatenate sparse updates, then sum duplicate ids.

    Equivalent of the reference's merge_indexed_slices + deduplicate
    (/root/reference/elasticdl/python/common/tensor_utils.py:24-60), done
    vectorized with np.unique instead of a python dict loop.
    """
    values = np.concatenate(values_list, axis=0)
    ids = np.concatenate(ids_list, axis=0)
    return deduplicate_indexed_slices(values, ids)


def deduplicate_indexed_slices(values: np.ndarray, ids: np.ndarray):
    from elasticdl_tpu import native

    lib = native.lib()
    if (
        lib is not None
        and values.ndim == 2
        and values.dtype == np.float32
        and len(ids)
    ):
        values = np.ascontiguousarray(values)
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        out_ids = np.empty(len(ids), dtype=np.int64)
        out_values = np.empty_like(values)
        n = lib.edl_dedup_sum(
            native._i64p(ids), native._f32p(values), len(ids),
            values.shape[1], native._i64p(out_ids),
            native._f32p(out_values),
        )
        return out_values[:n], out_ids[:n]
    unique_ids, inverse = np.unique(ids, return_inverse=True)
    summed = np.zeros((len(unique_ids),) + values.shape[1:], dtype=values.dtype)
    np.add.at(summed, inverse, values)
    return summed, unique_ids
