"""Version shims over the moving parts of the JAX API.

The repo targets current JAX (`jax.shard_map`, `check_vma=`), but the
image may carry an older release where shard_map still lives in
jax.experimental and the replication-check kwarg is `check_rep`. Import
shard_map from here instead of from jax directly; call sites keep the
modern spelling (`check_vma=`) and this shim down-translates when needed.
"""

try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        # New API selects the MANUAL axes (axis_names); the experimental
        # signature selects the complement (auto = axes left automatic).
        axis_names = kwargs.pop("axis_names", None)
        if axis_names is not None:
            kwargs["auto"] = frozenset(
                kwargs["mesh"].axis_names
            ) - frozenset(axis_names)
        return _shard_map(f, **kwargs)
