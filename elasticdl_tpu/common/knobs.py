"""Central registry of every ELASTICDL_* environment knob.

Every environment variable the framework reads is declared HERE, once,
with its type, default, and documentation. Call sites then fetch values
through the typed accessors (`get_str` / `get_int` / `get_float`) or the
raw string (`raw`, `is_set`) — never through `os.environ` directly. The
`env-knobs` rule of `python -m tools.edl_lint` enforces both halves
statically: an `os.environ` read of an `ELASTICDL_*` key outside this
module is an error, and so is an accessor call naming an undeclared knob.

Reads are LIVE (`os.environ` is consulted on every call, no caching):
tests and in-process drills mutate the environment and expect
`rpc.reload_config()`-style re-reads to see the change. Modules that
want read-once semantics cache at their own layer, exactly as before.

docs/KNOBS.md is generated from this registry
(`python -m tools.edl_lint --write-knob-docs`); the env-knobs rule fails
when the checked-in table drifts from the declarations below.

Stdlib-only, imports nothing from the package (log_utils reads its own
level/format knobs through here, so this module must sit below it).
"""

import logging
import os

_logger = logging.getLogger("elasticdl_tpu.common.knobs")

_TYPES = ("str", "int", "float")


class Knob:
    """One declared environment knob: name, type, default, doc."""

    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name, type, default, doc):
        self.name = name
        self.type = type
        self.default = default
        self.doc = doc


_REGISTRY = {}


def declare(name, type, default, doc):
    """Register a knob. Re-declaring with a conflicting type or default
    is an error (two modules silently disagreeing on a default is exactly
    the bug the registry exists to prevent)."""
    if type not in _TYPES:
        raise ValueError(f"knob {name}: unknown type {type!r}")
    if not name.startswith("ELASTICDL_"):
        raise ValueError(f"knob {name}: names must start with ELASTICDL_")
    prior = _REGISTRY.get(name)
    if prior is not None:
        if (prior.type, prior.default) != (type, default):
            raise ValueError(
                f"knob {name} re-declared as ({type}, {default!r}); "
                f"conflicts with ({prior.type}, {prior.default!r})"
            )
        return prior
    knob = Knob(name, type, default, doc)
    _REGISTRY[name] = knob
    return knob


def _knob(name):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"environment knob {name!r} is not declared in "
            f"elasticdl_tpu/common/knobs.py"
        ) from None


def raw(name):
    """The raw environment string for a DECLARED knob ("" when unset).
    For callers that need presence/emptiness semantics (JSON blobs,
    forward-to-child-env logic) rather than a parsed value."""
    _knob(name)
    return os.environ.get(name, "")


def is_set(name):
    """True when the declared knob is present and non-empty."""
    return bool(raw(name))


def get_str(name):
    knob = _knob(name)
    value = os.environ.get(name, "")
    return value if value else knob.default


def get_int(name):
    knob = _knob(name)
    value = os.environ.get(name, "")
    if value:
        try:
            return int(value)
        except ValueError:
            # Float-formatted values ("12.0") truncate, matching the
            # int(float(...)) parsing the pre-registry helpers used.
            try:
                return int(float(value))
            except ValueError:
                _logger.warning("Bad %s=%r; using default %r", name,
                                value, knob.default)
    return knob.default


def get_float(name):
    knob = _knob(name)
    value = os.environ.get(name, "")
    if value:
        try:
            return float(value)
        except ValueError:
            _logger.warning("Bad %s=%r; using default %r", name, value,
                            knob.default)
    return knob.default


def all_knobs():
    """Every declared knob, name-sorted (docs generation, lint)."""
    return [_REGISTRY[name] for name in sorted(_REGISTRY)]


def docs_table():
    """The markdown table docs/KNOBS.md carries (generated, lint-pinned)."""
    lines = [
        "| Knob | Type | Default | Purpose |",
        "| --- | --- | --- | --- |",
    ]
    for knob in all_knobs():
        default = "" if knob.default in ("", None) else repr(knob.default)
        doc = " ".join(knob.doc.split())
        lines.append(
            f"| `{knob.name}` | {knob.type} | `{default}` | {doc} |"
            if default
            else f"| `{knob.name}` | {knob.type} | *(unset)* | {doc} |"
        )
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The registry. One declaration per knob, grouped by subsystem. Defaults
# mirror the behavior each subsystem shipped with; the accessor returns
# the default when the variable is unset, empty, or unparseable.
# ---------------------------------------------------------------------------

# -- identity / logging (common/log_utils.py, chaos/injection.py) --
declare("ELASTICDL_JOB_NAME", "str", "",
        "Job name stamped into JSON log records and event logs; set by "
        "the master for every spawned instance.")
declare("ELASTICDL_ROLE", "str", "",
        "This process's role stamp (master / worker-N / ps-N); set by the "
        "instance managers, read by logging and role-targeted chaos.")
declare("ELASTICDL_LOG_LEVEL", "str", "",
        "Package log level: DEBUG/INFO/WARNING/ERROR or a number; "
        "default INFO.")
declare("ELASTICDL_LOG_FORMAT", "str", "",
        "\"json\" switches to one JSON object per log line with job/pod "
        "identity; anything else keeps the human format.")

# -- observability plane (observability/) --
declare("ELASTICDL_OBS_DIR", "str", "",
        "Directory for traces, the event log, and endpoint "
        "advertisements; the master seeds it into every child process.")
declare("ELASTICDL_METRICS_PORT", "int", 0,
        "Port for the /metrics exporter; 0 binds an ephemeral port, "
        "negative disables the endpoint.")
declare("ELASTICDL_METRICS_HOST", "str", "",
        "Bind address for the /metrics exporter (default 0.0.0.0); also "
        "the advertised scrape host when it names a real interface.")
declare("ELASTICDL_AGGREGATOR_INTERVAL", "float", 2.0,
        "Master telemetry aggregator scrape period in seconds.")
declare("ELASTICDL_OBS_MAX_LOG_MB", "float", 64.0,
        "Size cap in MB for each observability log (traces.jsonl / "
        "events.jsonl); crossing it rotates the file to <name>.1 with a "
        "rotated marker event. 0 disables rotation.")
declare("ELASTICDL_ENDPOINT_STALE_SCRAPES", "int", 5,
        "Consecutive scrape failures after which the master's "
        "aggregator stops scraping an advertised endpoint (counted in "
        "edl_job_endpoints_stale; a rewritten advertisement resets it).")
declare("ELASTICDL_COMPILE_TRACKER", "str", "auto",
        "Compile tracker behind tracked_jit: 0/false/off degrades to a "
        "plain jax.jit (no lowering accounting).")
declare("ELASTICDL_PROFILE_MAX_SECONDS", "float", 30.0,
        "Upper bound for one on-demand /debug/profile capture; longer "
        "requests are clamped. 0 removes the clamp.")
declare("ELASTICDL_MEM_SAMPLE_SECONDS", "float", 10.0,
        "Memory accountant sampling period; 0 disables the background "
        "sampler thread (direct samples still work).")
declare("ELASTICDL_MEM_WATERMARK_RATIO", "float", 1.2,
        "Factor by which a sample's live device bytes must exceed the "
        "previous peak to emit a mem_high_watermark event.")
declare("ELASTICDL_MFU", "str", "auto",
        "MFU instrumentation: 1/true forces on, 0/false forces off, "
        "\"auto\" activates only where observability.setup() ran.")
declare("ELASTICDL_PEAK_FLOPS", "float", 0.0,
        "Per-device peak FLOP/s override for MFU; 0 falls back to the "
        "device-kind table.")

# -- data-plane instrumentation (observability/datapath.py) --
declare("ELASTICDL_DATAPATH", "int", 1,
        "Stage-level input-pipeline instrumentation (task/read/decode/"
        "collate/h2d/starve stages as Timing phases, spans, and "
        "edl_datapath_* series); 0 turns every stage into a no-op.")
declare("ELASTICDL_DATAPATH_QUEUE_CAPACITY", "int", 1024,
        "Default capacity QueueTelemetry assumes for a hand-off queue "
        "whose constructor does not pass one (the prefetch queue passes "
        "its real bound); sizes the backpressure watermark.")
declare("ELASTICDL_DATAPATH_QUEUE_WATERMARK", "float", 0.8,
        "Fraction of a hand-off queue's capacity at which occupancy "
        "fires the edge-triggered datapath_backpressure event; <=0 "
        "disables watermark events (the depth gauge stays live).")

# -- push-based telemetry (observability/push.py, aggregator) --
declare("ELASTICDL_TELEMETRY_PUSH_INTERVAL", "float", 0.0,
        "Seconds between push-telemetry reports from workers/PS to the "
        "master's ReportTelemetry RPC; 0 (default) disables pushing and "
        "leaves the master's pull-scrape loop as the only path. A "
        "pushing role is skipped by the pull loop while its pushes stay "
        "fresh (pull remains the fallback).")
declare("ELASTICDL_TELEMETRY_PUSH_JITTER", "float", 0.2,
        "Fractional jitter applied to each push interval so a fleet of "
        "reporters does not dogpile the master in lockstep.")
declare("ELASTICDL_TELEMETRY_FULL_EVERY", "int", 16,
        "Every Nth telemetry push is a full snapshot instead of a delta "
        "(bounded resync horizon after a lost/reordered push); 0 sends "
        "a full snapshot only when the master asks (need_full).")

# -- event-log coalescing (observability/events.py) --
declare("ELASTICDL_EVENT_COALESCE_SECONDS", "float", 0.0,
        "Coalescing window for high-frequency event kinds: after one "
        "event of a coalesced kind is written, further events of that "
        "kind within the window are folded into the next write (which "
        "carries a coalesced=N field) instead of each taking a line. "
        "0 (default) writes every event.")
declare("ELASTICDL_EVENT_COALESCE_KINDS", "str", "membership_epoch",
        "Comma-separated event kinds subject to the coalescing window "
        "(per-epoch membership churn is the canonical spammer).")

# -- master heartbeat / orphan reaper (master/, tools/reap_orphans.py) --
declare("ELASTICDL_HEARTBEAT_DIR", "str", "/tmp/elasticdl_heartbeats",
        "Directory where each master writes its <job>-<pid>.json "
        "heartbeat (pid, pgid, ts); tools/reap_orphans.py kills process "
        "groups whose heartbeat went stale (SIGKILLed drivers strand "
        "whole `edl train` trees). Empty disables the heartbeat.")
declare("ELASTICDL_HEARTBEAT_SECONDS", "float", 10.0,
        "Master heartbeat touch period in seconds; 0 disables.")

# -- alert rules (observability/alerts.py) --
declare("ELASTICDL_ALERT_STRAGGLER_SKEW", "float", 2.0,
        "Straggler alert threshold: worker EWMA step latency over fleet "
        "median.")
declare("ELASTICDL_ALERT_PS_SKEW", "float", 3.0,
        "PS load alert threshold: hottest shard byte rate over the mean "
        "byte rate.")
declare("ELASTICDL_ALERT_STALL_SECONDS", "float", 60.0,
        "Stall alert: records_done frozen this long with tasks in "
        "flight.")
declare("ELASTICDL_ALERT_ABANDONED", "float", 1.0,
        "Abandoned-task count threshold for the abandonment alert.")
declare("ELASTICDL_ALERT_STARVE_SHARE", "float", 0.25,
        "Input-starvation alert threshold: fraction of a worker's wall "
        "time spent with the step blocked on an empty feed queue "
        "(datapath `starve` stage rate).")

# -- rpc plane (common/rpc.py) --
declare("ELASTICDL_RPC_DEADLINES", "str", "",
        "JSON {method: seconds} per-method deadline overrides.")
declare("ELASTICDL_RPC_MAX_ATTEMPTS", "int", 0,
        "Override max retry attempts for all methods; 0/unset keeps the "
        "per-method matrix.")
declare("ELASTICDL_RPC_BACKOFF_BASE", "float", 0.0,
        "Override retry backoff base seconds for all methods; 0/unset "
        "keeps the matrix.")
declare("ELASTICDL_RPC_BACKOFF_MAX", "float", 0.0,
        "Override retry backoff cap seconds for all methods; 0/unset "
        "keeps the matrix.")
declare("ELASTICDL_RPC_BREAKER_THRESHOLD", "int", 8,
        "Consecutive connectivity failures that trip a peer's circuit "
        "breaker; <=0 disables the breaker.")
declare("ELASTICDL_RPC_BREAKER_COOLDOWN", "float", 5.0,
        "Seconds an open breaker waits before a half-open probe.")
declare("ELASTICDL_RPC_READY_TIMEOUT", "float", 30.0,
        "Channel-readiness TCP probe budget in seconds; 0 disables the "
        "ready-wait.")

# -- PS wire codec + prefetch overlap (worker/, ps/) --
declare("ELASTICDL_WIRE_DTYPE", "str", "float32",
        "Default PS wire codec when the PSClient isn't given one "
        "explicitly: float32, bfloat16 (bf16 embedding legs), or int8 "
        "(block-quantized dense grads with error feedback + bf16 "
        "embedding legs).")
declare("ELASTICDL_WIRE_BLOCK_SIZE", "int", 256,
        "Block size for the int8 block-scaled gradient codec: one "
        "float32 absmax/127 scale per this many consecutive elements.")
declare("ELASTICDL_PS_MAX_PUSH_BYTES", "int", 64 * 1024 * 1024,
        "Packed gradient pushes larger than this split into chunked "
        "sub-requests (each its own RPC under the per-method deadline), "
        "so one giant embedding slice can't stall the channel. "
        "<=0 disables chunking.")
declare("ELASTICDL_PREFETCH_DEPTH", "int", 1,
        "PS-trainer embedding prefetch lookahead: 1 issues the next "
        "batch's pull RPCs while the current step computes (async "
        "pipelined mode only); 0 restores the inline blocking prefetch.")
declare("ELASTICDL_PREFETCH_CACHE_ROWS", "int", 1 << 22,
        "Max cached embedding rows per table in the worker's versioned "
        "row cache (the table flushes whole when exceeded and re-fills "
        "on the following misses). 0 disables the cache.")
declare("ELASTICDL_PREFETCH_CACHE_DENSE_IDS", "int", 1 << 24,
        "Upper bound on embedding ids the worker row cache will index "
        "(its id->slot index is a dense int32 array of this size at "
        "most, ~64 MB at the cap). A table with larger ids stops "
        "caching and pulls every prefetch from the PS.")
declare("ELASTICDL_PREFETCH_CACHE_STALENESS", "int", 8,
        "Staleness budget of the worker row cache, in PS model "
        "versions: a cached row only hits while it was filled within "
        "this many versions of the newest version the worker has seen "
        "— the bounded-staleness contract async SGD already absorbs. "
        "Negative disables the version check (never invalidate).")

# -- recompile-free elasticity (common/compile_cache.py, worker/) --
declare("ELASTICDL_COMPILE_CACHE_DIR", "str", "",
        "Directory for jax's persistent compilation cache: step "
        "executables are rehydrated from disk across process relaunches "
        "(the common preemption case), so a relaunched worker's first "
        "step pays trace+lower instead of a full XLA compile. Stamped "
        "into child env by both instance managers; empty disables.")
declare("ELASTICDL_AOT_SPECULATE", "str", "auto",
        "Speculative ahead-of-time world compilation: a background "
        "thread compiles the step of candidate nearby worlds (keyed by "
        "the unified world spec) while training continues, so an "
        "elastic regroup consumes a prebuilt executable instead of "
        "cold-compiling. 0/false/off disables.")
declare("ELASTICDL_AOT_WORLDS", "int", 1,
        "How many neighboring world sizes the speculator guesses in "
        "each direction (N±delta). Only worlds whose mesh is buildable "
        "on the live backend compile directly; the rest are skipped "
        "(their relaunch path is covered by the persistent cache).")

# -- worker resilience (worker/) --
declare("ELASTICDL_PS_DEGRADED_BLOCK_SECONDS", "float", 20.0,
        "Budget for _sync_model's re-seed/backoff loop on a degraded PS "
        "shard before failing the minibatch up the retry ladder.")
declare("ELASTICDL_MASTER_PATIENCE_SECONDS", "float", 120.0,
        "How long the worker task loop rides out an unreachable master "
        "before letting the failure propagate.")
declare("ELASTICDL_JOIN_GATE_SECONDS", "float", 0.0,
        "Join-gate wait budget at an elastic regroup; 0 (default) "
        "auto-derives max(90 s, 20 x the longest step compile the "
        "compile tracker has observed), capped at 600 s, so loaded "
        "boxes whose ~6.5 s compiles outlast a fixed gate scale the "
        "wait instead of churning membership.")

# -- bench subsystem (elasticdl_tpu/bench/) --
declare("ELASTICDL_BENCH_WATCHDOG_S", "float", 600.0,
        "Hard per-benchmark wall-clock bound in the full bench run; a "
        "wedged benchmark loses its own slot, not the run. 0 disables.")
declare("ELASTICDL_BENCH_BUDGET_S", "float", 780.0,
        "Soft shared budget for a FULL bench run: workloads stop "
        "opening timed windows when it runs out (degrading sample "
        "counts instead of dying) and the runner skips benchmarks that "
        "no longer fit (recorded, never silent). Default sits under "
        "the bench driver's historical ~870 s wall so the JSON line "
        "always lands before an outer timeout. 0 disables.")
declare("ELASTICDL_BENCH_WINDOWS", "int", 5,
        "Timed windows per benchmark in the full run; each window "
        "yields one examples/s sample for the bootstrap CI.")
declare("ELASTICDL_BENCH_MIN_EFFECT", "float", 0.02,
        "Relative effect below which a statistically significant bench "
        "difference is still reported as noise (the regression gate's "
        "practical-significance threshold).")
declare("ELASTICDL_BENCH_BASELINE", "str", "",
        "Explicit baseline BENCH json path for the verdict/gate; empty "
        "searches the repo root for the newest parseable BENCH_r*.json.")

# -- flight recorder (observability/flightrec.py) --
declare("ELASTICDL_FLIGHTREC", "str", "auto",
        "Crash-dump flight recorder: 0/false/off disables; anything "
        "else arms it wherever observability.setup() runs (and in "
        "bench runs).")
declare("ELASTICDL_FLIGHTREC_CAPACITY", "int", 256,
        "Ring capacity: how many recent spans the flight recorder "
        "keeps in memory per process.")
declare("ELASTICDL_FLIGHTREC_DIR", "str", "",
        "Directory for flightrec-<role>.json dumps; empty falls back "
        "to ELASTICDL_OBS_DIR, then the working directory.")

# -- policy engine (master/policy.py) --
declare("ELASTICDL_POLICY", "str", "",
        "1/true enables the master's self-healing policy engine (the "
        "control loop that blacklists stragglers, launches speculative "
        "backup tasks, and scales on drain ETA). Unset/0 leaves the "
        "loop off — detection-only, exactly the pre-policy behavior.")
declare("ELASTICDL_POLICY_INTERVAL", "float", 2.0,
        "Policy evaluation period in seconds (each tick reads the "
        "aggregator summary and evaluates every rule once).")
declare("ELASTICDL_POLICY_DRY_RUN", "str", "",
        "1/true makes the policy engine evaluate rules and emit "
        "policy_decision events with outcome=dry_run without actuating "
        "anything — the rehearsal mode for tuning thresholds.")
declare("ELASTICDL_POLICY_HYSTERESIS", "int", 3,
        "Consecutive policy ticks a rule's condition must hold before "
        "it fires (one clean tick resets the counter); the flap guard.")
declare("ELASTICDL_POLICY_COOLDOWN_SECONDS", "float", 30.0,
        "Per-(action, subject) cooldown: after an action applies, the "
        "same action on the same subject is suppressed this long.")
declare("ELASTICDL_POLICY_RATE_LIMIT", "int", 6,
        "Global cap on applied policy actions per 60 s sliding window; "
        "further decisions in the window land as outcome=rate_limited.")
declare("ELASTICDL_POLICY_STRAGGLER_SCORE", "float", 3.0,
        "Straggler-mitigation trigger: a worker whose aggregator "
        "straggler_score (EWMA step latency over fleet median) stays "
        "at or above this for the hysteresis window is blacklisted "
        "and relaunched.")
declare("ELASTICDL_POLICY_BLACKLIST_SECONDS", "float", 60.0,
        "TTL of a dispatcher blacklist entry created by the straggler "
        "rule; expiry re-admits the worker even if its relaunch never "
        "completed (self-healing default).")
declare("ELASTICDL_POLICY_MAX_BACKUPS", "int", 2,
        "Upper bound on speculative backup task copies in flight at "
        "once; 0 disables the backup-task rule.")
declare("ELASTICDL_POLICY_BACKUP_FACTOR", "float", 3.0,
        "Backup-task trigger: an in-flight training task whose elapsed "
        "time exceeds this multiple of the recent mean task duration "
        "gets a speculative second copy on a healthy worker.")
declare("ELASTICDL_POLICY_SCALE_STEP", "int", 1,
        "How many workers one drain-ETA scaling decision adds or "
        "retires (the k in ±k).")
declare("ELASTICDL_POLICY_MAX_WORKERS", "int", 0,
        "Ceiling for policy-driven scale-up; 0 defaults to twice the "
        "job's initial worker count.")
declare("ELASTICDL_POLICY_HINT_POLL_SECONDS", "float", 2.0,
        "How often a worker polls the master's world-hint RPC so the "
        "AOT speculator compiles the announced next world instead of "
        "guessing N±delta; 0 disables polling.")
declare("ELASTICDL_JOB_DEADLINE_SECONDS", "float", 0.0,
        "Soft job deadline for the drain-ETA scaling rule: when the "
        "aggregator's task-drain ETA overshoots the time remaining, "
        "the policy engine asks the instance manager for more workers "
        "(and retires them when far ahead). 0 disables the rule.")

# -- task lease batching (master/task_dispatcher.py, worker/) --
declare("ELASTICDL_TASK_LEASE_BATCH", "int", 1,
        "Tasks a worker leases per GetTask RPC (results are reported "
        "in matching batches); 1 keeps the classic one-task-per-RPC "
        "protocol. Raising it divides dispatch RPC load at fleet "
        "scale.")

# -- master journal (master/journal.py) --
declare("ELASTICDL_MASTER_JOURNAL_DIR", "str", "",
        "Directory for the master write-ahead journal + snapshots. Empty "
        "disables journaling (state is process-local, as before the "
        "survivable control plane).")
declare("ELASTICDL_JOURNAL_SNAPSHOT_EVERY", "int", 512,
        "Compact the master journal into a fresh snapshot after this many "
        "appended ops (bounds replay time and WAL growth).")

# -- chaos (chaos/injection.py) --
declare("ELASTICDL_CHAOS", "str", "",
        "JSON fault schedule injected into the rpc plane; set by drills, "
        "absent in production.")
