"""Automatic embedding placement for the PS strategy (the ModelHandler).

Reference counterpart: /root/reference/elasticdl/python/common/
model_handler.py:98-102,148-461 — the reference clones a Keras model,
replacing every `tf.keras.layers.Embedding` whose table exceeds 2 MB with
the PS-backed EDL Embedding, and reverses the swap (stuffing trained
checkpoint weights back) for SavedModel export.

TPU-first redesign: flax modules are immutable dataclass trees, so instead
of graph surgery the swap happens at TRACE time via
`flax.linen.intercept_methods`:

- `wrap_model_for_ps(model)` returns a wrapper module whose interceptor
  (a) skips `setup` for every `nn.Embed` above the size threshold, so the
      giant table param is never created, and
  (b) replaces its `__call__` with a read of per-position rows from the
      `edl_embedding` collection (keyed by the embed's module path) — the
      exact contract ParameterServerTrainer already speaks for
      DistributedEmbedding, so the trainer needs no new code path.
  Models with no over-threshold embeds come back unchanged (the caller
  checks `discover_swapped_tables`).

- `derive_embedding_inputs(...)` removes the hand-written
  `embedding_inputs` feed: a one-off EAGER capture pass records the
  concrete ids each swapped table consumed, then matches them against the
  feature pytree (exact leaf, column slice, or flatten) to synthesize the
  feed function. Models whose ids are computed (hashed/crossed) inside the
  forward pass fall back to a per-batch eager capture feed.

- `stuff_export_params(...)` is the reverse swap: trained PS table rows are
  materialized back into the ORIGINAL (unwrapped) model's param tree as
  plain `embedding` tables, so the exported checkpoint loads into the
  user's stock model exactly as the reference's export rewrite does.
"""

import contextvars

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.pytree_utils import get_at as _get_at, walk_dict
from elasticdl_tpu.layers.embedding import (
    EMBEDDING_COLLECTION,
    DistributedEmbedding,
)

logger = get_logger("common.model_handler")

# The reference partitions a table to the PS iff it exceeds 2 MB
# (model_handler.py:98-102).
DEFAULT_THRESHOLD_BYTES = 2 * 1024 * 1024

# When set (to a dict), swapped-embed interceptors record
# {table_name: np ids} instead of contributing to training.
_CAPTURE = contextvars.ContextVar("edl_capture", default=None)
# When set (to a dict), swapped-embed calls record
# {table_name: (dim, vocab)} — the declared table geometry, used to size
# the export reverse-swap exactly as the stock model declares it.
_DISCOVER = contextvars.ContextVar("edl_discover", default=None)


class discover_tables:
    """Context manager collecting {table: (dim, vocab)} during a wrapped
    model's init/apply."""

    def __enter__(self):
        self.tables = {}
        self._token = _DISCOVER.set(self.tables)
        return self.tables

    def __exit__(self, *exc):
        _DISCOVER.reset(self._token)
        return False


def _table_name(module):
    """Module path -> PS table key, with the wrapper's own 'inner' segment
    stripped so the name matches the ORIGINAL model's tree (what the export
    reverse-swap stuffs into)."""
    path = [p for p in module.path if p]
    if path and path[0] == "inner":
        path = path[1:]
    return "/".join(path)


def _oversized(module, threshold_bytes):
    if not isinstance(module, nn.Embed):
        return False
    # Size by the STORAGE dtype (param_dtype): under mixed precision the
    # table lives in float32 while `dtype` is only the compute dtype.
    storage = getattr(module, "param_dtype", None) or jnp.float32
    bytes_ = (
        module.num_embeddings
        * module.features
        * np.dtype(storage).itemsize
    )
    return bytes_ > threshold_bytes


def _combined_zeros(module, ids):
    """Zero output of a DistributedEmbedding call, shaped per its combiner
    (capture mode short-circuits the real lookup)."""
    ids = jnp.asarray(ids)
    if module.combiner is None:
        shape = ids.shape + (module.dim,)
    else:
        shape = ids.shape[:-1] + (module.dim,)
    return jnp.zeros(shape, jnp.float32)


class PSWrappedModel(nn.Module):
    """Wraps a user model, rerouting oversized `nn.Embed`s to the PS.

    Placement tiers (device_capacity_bytes is the round-3 upper tier):
      <= threshold_bytes                      replicate on device (stock)
      (threshold, device_capacity]            stay on device — on a
          multi-device mesh the trainer row-shards these over the mesh
          (parallel/sharded_embedding.py) instead of re-hosting them
      > device_capacity (or > threshold when no capacity is given)
                                              PS-resident (host RPC)
    """

    inner: nn.Module
    threshold_bytes: int = DEFAULT_THRESHOLD_BYTES
    device_capacity_bytes: int = 0  # 0 = no device tier (legacy 2-tier)

    @nn.compact
    def __call__(self, *args, **kwargs):
        outer = self
        calls_seen = set()  # tables applied so far in THIS forward

        ps_cutoff = max(
            outer.threshold_bytes, outer.device_capacity_bytes
        )

        def interceptor(next_fun, fargs, fkwargs, context):
            mod = context.module
            if _oversized(mod, ps_cutoff):
                if context.method_name == "setup":
                    # The swap: never declare the giant table param.
                    return None
                if context.method_name == "__call__":
                    ids = jnp.asarray(fargs[0])
                    table = _table_name(mod)
                    if table in calls_seen:
                        # One shared table applied at two call sites would
                        # collide on the collection key and silently train
                        # against the wrong ids — refuse instead.
                        raise ValueError(
                            f"embedding table {table!r} is applied more "
                            "than once per forward pass; automatic PS "
                            "placement does not support shared tables — "
                            "use DistributedEmbedding with an explicit "
                            "embedding_inputs feed"
                        )
                    calls_seen.add(table)
                    discover = _DISCOVER.get()
                    if discover is not None:
                        discover[table] = (
                            mod.features,
                            mod.num_embeddings,
                        )
                    capture = _CAPTURE.get()
                    if capture is not None:
                        # Capture mode: record ids, touch no variables (the
                        # caller has no collection to provide).
                        capture[table] = np.asarray(ids)
                        return jnp.zeros(
                            ids.shape + (mod.features,), jnp.float32
                        )
                    rows = outer.variable(
                        EMBEDDING_COLLECTION,
                        table,
                        lambda: jnp.zeros(
                            (ids.size, mod.features), jnp.float32
                        ),
                    )
                    return rows.value.reshape(
                        ids.shape + (mod.features,)
                    )
            elif (
                isinstance(mod, DistributedEmbedding)
                and context.method_name == "__call__"
            ):
                capture = _CAPTURE.get()
                if capture is not None:
                    capture[mod.table_name] = np.asarray(fargs[0])
                    return _combined_zeros(mod, fargs[0])
            return next_fun(*fargs, **fkwargs)

        with nn.intercept_methods(interceptor):
            return self.inner(*args, **kwargs)


def wrap_model_for_ps(model, threshold_bytes=DEFAULT_THRESHOLD_BYTES,
                      device_capacity_bytes=0):
    return PSWrappedModel(
        inner=model,
        threshold_bytes=threshold_bytes,
        device_capacity_bytes=device_capacity_bytes,
    )


class _CaptureDistributed(nn.Module):
    """Capture-only wrapper for models built directly on
    DistributedEmbedding (no swap needed, but the feed can still be
    derived automatically)."""

    inner: nn.Module

    @nn.compact
    def __call__(self, *args, **kwargs):
        def interceptor(next_fun, fargs, fkwargs, context):
            mod = context.module
            if (
                isinstance(mod, DistributedEmbedding)
                and context.method_name == "__call__"
            ):
                capture = _CAPTURE.get()
                if capture is not None:
                    capture[mod.table_name] = np.asarray(fargs[0])
                    return _combined_zeros(mod, fargs[0])
            return next_fun(*fargs, **fkwargs)

        with nn.intercept_methods(interceptor):
            return self.inner(*args, **kwargs)


def capture_embedding_ids(model, variables, features):
    """Eager forward solely to observe which ids each table consumed.
    Works for PSWrappedModel (swapped nn.Embeds) and, via a transient
    capture wrapper, for DistributedEmbedding models."""
    capture = {}
    token = _CAPTURE.set(capture)
    try:
        runner = (
            model
            if isinstance(model, PSWrappedModel)
            else _CaptureDistributed(inner=model)
        )
        if not isinstance(model, PSWrappedModel):
            variables = {"params": {"inner": variables["params"]}, **{
                k: {"inner": v}
                for k, v in variables.items()
                if k != "params"
            }}
        runner.apply(variables, features, training=False)
    finally:
        _CAPTURE.reset(token)
    return capture


def _match_leaf(ids, leaf):
    """Return an extractor leaf_array -> ids_array, or None. Covers the
    ways zoo models feed id features to embedding layers: the whole leaf,
    a single column of a [B, F] leaf, or a reshape of the leaf."""
    if ids.shape == leaf.shape and np.array_equal(ids, leaf):
        return lambda a: a
    if (
        leaf.ndim == 2
        and ids.ndim == 1
        and ids.shape[0] == leaf.shape[0]
    ):
        for j in range(leaf.shape[1]):
            if np.array_equal(ids, leaf[:, j]):
                return lambda a, j=j: a[:, j]
    if ids.size == leaf.size and np.array_equal(
        ids.reshape(-1), leaf.reshape(-1)
    ):
        if ids.ndim >= 1 and ids.shape[0] == leaf.shape[0]:
            # Batch-preserving reshape ([B, F] -> [B, ...]).
            shape_tail = ids.shape[1:]
            return lambda a, t=shape_tail: a.reshape((a.shape[0],) + t)
        if ids.ndim == 1:
            # Full flatten ([B, F] -> [B*F]).
            return lambda a: a.reshape(-1)
    return None


def derive_embedding_inputs(model, variables, sample_features):
    """Synthesize the `embedding_inputs` feed: features -> {table: ids}.

    Matches each table's captured ids against the feature pytree; any
    table whose ids are computed inside the model falls back to a
    per-batch eager capture (general, slower — logged once)."""
    captured = capture_embedding_ids(model, variables, sample_features)
    if not captured:
        return None
    extractors = {}
    unmatched = []
    leaves = [
        (path, np.asarray(leaf))
        for path, leaf in walk_dict(sample_features)
    ]
    for table, ids in captured.items():
        found = None
        for path, leaf in leaves:
            ex = _match_leaf(ids, leaf)
            if ex is not None:
                found = (path, ex)
                break
        if found is None:
            unmatched.append(table)
        else:
            extractors[table] = found
    if unmatched:
        logger.info(
            "Tables %s compute ids inside the forward pass; using a "
            "per-batch capture feed for them",
            unmatched,
        )

        def feed(features):
            out = capture_embedding_ids(model, variables, features)
            for table, (path, ex) in extractors.items():
                out[table] = np.asarray(
                    ex(np.asarray(_get_at(features, path)))
                )
            return out

        return feed

    def feed(features):
        return {
            table: np.asarray(ex(np.asarray(_get_at(features, path))))
            for table, (path, ex) in extractors.items()
        }

    return feed


def stuff_export_params(params, ps_tables, default_vocab=None):
    """Reverse swap for export: inject trained PS table rows back into the
    ORIGINAL model's param tree (reference model_handler.py:242-268).

    params: the INNER model's params (wrapper nesting already stripped).
    ps_tables: {table_name ('a/b' path form): (ids, values)} from the PS.
    default_vocab: {table_name: vocab_size} for sizing; defaults to
    max(id)+1.
    Unseen rows stay zero — ids never looked up were never trained.
    """
    params = _deep(params)
    for table, (ids, values) in ps_tables.items():
        ids = np.asarray(ids)
        values = np.asarray(values)
        vocab = (default_vocab or {}).get(
            table, int(ids.max()) + 1 if ids.size else 0
        )
        full = np.zeros((vocab, values.shape[1]), values.dtype)
        in_range = ids < vocab
        if not in_range.all():
            # Dirty data can materialize PS rows beyond the declared
            # vocab (training's clamped gather tolerates it); the export
            # must keep the stock model's declared shape, so drop them.
            logger.warning(
                "Table %s: dropping %d rows with ids >= declared vocab "
                "%d at export",
                table,
                int((~in_range).sum()),
                vocab,
            )
            ids, values = ids[in_range], values[in_range]
        full[ids] = values
        node = params
        parts = table.split("/")
        for k in parts[:-1]:
            node = node.setdefault(k, {})
        node.setdefault(parts[-1], {})["embedding"] = full
    return params


def _deep(tree):
    return {
        k: _deep(v) if hasattr(v, "items") else v for k, v in tree.items()
    }
