"""Phase timing accumulators for the worker/PS hot paths.

Reference counterpart: /root/reference/elasticdl/python/common/
timing_utils.py:17-48 (named start/end wall-clock accumulators reported at
task granularity under DEBUG) — redesigned as a context-manager API so a
phase can't be left open, plus per-phase call counts and means, which is
what a step-time breakdown (pull / step / push, the reference's published
benchmark decomposition, docs/benchmark/ftlib_benchmark.md:119-124) needs.
"""

import contextlib
import threading
import time


class Timing:
    """Accumulates wall-clock per named phase. Thread-safe; one instance is
    typically owned by a trainer and reported per task or per N steps."""

    def __init__(self, enabled=True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._total = {}
        self._count = {}

    @contextlib.contextmanager
    def record(self, phase):
        if not self.enabled:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            with self._lock:
                self._total[phase] = self._total.get(phase, 0.0) + elapsed
                self._count[phase] = self._count.get(phase, 0) + 1

    def add(self, phase, seconds):
        """Fold in an externally-measured duration (e.g. from a jitted
        step whose completion is observed asynchronously)."""
        if not self.enabled:
            return
        with self._lock:
            self._total[phase] = self._total.get(phase, 0.0) + seconds
            self._count[phase] = self._count.get(phase, 0) + 1

    def summary(self):
        """{phase: {"total_s", "count", "mean_s"}}"""
        with self._lock:
            return {
                phase: {
                    "total_s": total,
                    "count": self._count[phase],
                    "mean_s": total / max(self._count[phase], 1),
                }
                for phase, total in self._total.items()
            }

    def reset(self):
        with self._lock:
            self._total.clear()
            self._count.clear()

    def report(self, logger, reset=False):
        """DEBUG-log the per-phase breakdown (the reference's
        report_timing shape)."""
        for phase, s in sorted(self.summary().items()):
            logger.debug(
                "%s: %.6gs total / %d calls / %.6gs mean",
                phase,
                s["total_s"],
                s["count"],
                s["mean_s"],
            )
        if reset:
            self.reset()
