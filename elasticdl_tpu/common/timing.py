"""Phase timing accumulators for the worker/PS hot paths.

Reference counterpart: /root/reference/elasticdl/python/common/
timing_utils.py:17-48 (named start/end wall-clock accumulators reported at
task granularity under DEBUG) — redesigned as a context-manager API so a
phase can't be left open, plus per-phase call counts, means, min/max and
bounded-reservoir percentiles (p50/p99), which is what a step-time
breakdown (pull / step / push, the reference's published benchmark
decomposition, docs/benchmark/ftlib_benchmark.md:119-124) needs.

A Timing can mirror every sample into a labeled observability Histogram
(`bind_histogram`), which is how the per-phase totals reach the Prometheus
/metrics endpoint without a second instrumentation pass.
"""

import contextlib
import threading
import time

from elasticdl_tpu.observability.metrics import Reservoir

# Bounded per-phase sample reservoir for percentile estimation.
RESERVOIR_SIZE = 256


class _Phase:
    __slots__ = ("total", "count", "min", "max", "reservoir")

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.min = float("inf")
        self.max = 0.0
        self.reservoir = Reservoir(RESERVOIR_SIZE)


class Timing:
    """Accumulates wall-clock per named phase. Thread-safe; one instance is
    typically owned by a trainer and reported per task or per N steps."""

    def __init__(self, enabled=True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._phases = {}
        self._histogram = None

    def bind_histogram(self, histogram):
        """Mirror every sample into a metrics.Histogram labeled by phase
        (e.g. default_registry().histogram("edl_phase_seconds",
        labelnames=("phase",)))."""
        self._histogram = histogram
        return self

    @contextlib.contextmanager
    def record(self, phase):
        if not self.enabled:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            self.add(phase, time.perf_counter() - start)

    def add(self, phase, seconds):
        """Fold in an externally-measured duration (e.g. from a jitted
        step whose completion is observed asynchronously)."""
        if not self.enabled:
            return
        with self._lock:
            p = self._phases.get(phase)
            if p is None:
                p = self._phases[phase] = _Phase()
            p.total += seconds
            p.count += 1
            p.min = min(p.min, seconds)
            p.max = max(p.max, seconds)
            p.reservoir.add(seconds)
        if self._histogram is not None:
            self._histogram.labels(phase=phase).observe(seconds)

    def summary(self):
        """{phase: {"total_s", "count", "mean_s", "min_s", "max_s",
        "p50_s", "p99_s"}}; percentiles are reservoir estimates over up to
        RESERVOIR_SIZE samples."""
        with self._lock:
            out = {}
            for phase, p in self._phases.items():
                ordered = sorted(p.reservoir.snapshot())
                out[phase] = {
                    "total_s": p.total,
                    "count": p.count,
                    "mean_s": p.total / max(p.count, 1),
                    "min_s": p.min,
                    "max_s": p.max,
                    "p50_s": Reservoir.quantile_of(ordered, 0.50),
                    "p99_s": Reservoir.quantile_of(ordered, 0.99),
                }
            return out

    def reset(self):
        with self._lock:
            self._phases.clear()

    def report(self, logger, reset=False):
        """DEBUG-log the per-phase breakdown (the reference's
        report_timing shape)."""
        for phase, s in sorted(self.summary().items()):
            logger.debug(
                "%s: %.6gs total / %d calls / %.6gs mean / "
                "%.6gs p50 / %.6gs p99",
                phase,
                s["total_s"],
                s["count"],
                s["mean_s"],
                s["p50_s"],
                s["p99_s"],
            )
        if reset:
            self.reset()
