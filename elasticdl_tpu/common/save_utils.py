"""Worker-side checkpoint save/restore (local & AllReduce strategies).

The PS strategy checkpoints server-side (ps/checkpoint.py, the reference's
PS-side scheme); for strategies whose state lives in the worker this module
saves the trainer's (variables, version) as an .npz of wire-named arrays —
the analog of the reference's CheckpointSaver + SavedModel export hand-off
(/root/reference/elasticdl/python/common/save_utils.py:151-282,
master/callbacks.py:38-66).
"""

import os

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.pytree_utils import flatten_params, unflatten_like

logger = get_logger("common.save_utils")


def _normalize(path):
    """np.savez appends '.npz' itself; normalize so the logged path, the
    saved file, and a later restore all agree."""
    return path if path.endswith(".npz") else path + ".npz"


def save_trainer_checkpoint(trainer, path):
    exported = trainer.export_variables()
    if exported is None or exported.get("variables") is None:
        # E.g. a relaunched worker that only picked up the train-end export
        # task: failing here reports the task back to the master, which
        # re-queues it for a worker that actually holds trained state.
        raise ValueError("trainer has no exportable state")
    path = _normalize(path)
    named, _ = flatten_params(exported["variables"])
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez(
        path[: -len(".npz")],
        __version__=np.int64(exported["version"]),
        **{name: np.asarray(leaf) for name, leaf in named.items()},
    )
    logger.info("Saved model checkpoint to %s", path)


def restore_trainer_checkpoint(trainer, path):
    """Restore into an ALREADY-INITIALIZED trainer (variables define the
    pytree to fill)."""
    with np.load(_normalize(path)) as data:
        named = {k: data[k] for k in data.files if k != "__version__"}
        version = int(data["__version__"])
    exported = trainer.export_variables()
    exported["variables"] = unflatten_like(exported["variables"], named)
    exported["version"] = version
    trainer.restore_variables(exported)
    logger.info("Restored model checkpoint from %s (version %d)", path, version)


class ExportModelCallback:
    """Train-end callback writing the final model (reference
    SavedModelExporter.on_train_end, master/callbacks.py:38-66)."""

    def __init__(self, output_path):
        self._path = output_path

    def on_train_end(self, trainer):
        save_trainer_checkpoint(trainer, self._path)
