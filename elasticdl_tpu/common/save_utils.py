"""Worker-side checkpoint save/restore (local & AllReduce strategies).

The PS strategy checkpoints server-side (ps/checkpoint.py, the reference's
PS-side scheme); for strategies whose state lives in the worker this module
saves the trainer's (variables, opt_state, rng, version) as an .npz of
wire-named arrays (train-end model exports carry weights only) —
the analog of the reference's CheckpointSaver + SavedModel export hand-off
(/root/reference/elasticdl/python/common/save_utils.py:151-282,
master/callbacks.py:38-66).
"""

import os

import jax
import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.pytree_utils import flatten_params, unflatten_like

logger = get_logger("common.save_utils")


def _normalize(path):
    """np.savez appends '.npz' itself; normalize so the logged path, the
    saved file, and a later restore all agree."""
    return path if path.endswith(".npz") else path + ".npz"


_OPT_PREFIX = "__opt__"
_OPT_SPEC_KEY = "__opt_spec__"
_RNG_KEY = "__rng__"


def save_trainer_checkpoint(trainer, path, include_training_state=True):
    exported = trainer.export_variables()
    if exported is None or exported.get("variables") is None:
        # E.g. a relaunched worker that only picked up the train-end export
        # task: failing here reports the task back to the master, which
        # re-queues it for a worker that actually holds trained state.
        raise ValueError("trainer has no exportable state")
    path = _normalize(path)
    named, _ = flatten_params(exported["variables"])
    arrays = {name: np.asarray(leaf) for name, leaf in named.items()}
    # Optimizer state is an optax pytree of NamedTuples — no stable dict
    # paths, so leaves go in flatten order; the restoring trainer supplies
    # the treedef (same optimizer spec) to rebuild it. Adding these keys is
    # what makes a kill-and-resume Adam run bitwise-identical to an
    # uninterrupted one instead of resetting the moments.
    if include_training_state and exported.get("opt_state") is not None:
        for i, leaf in enumerate(
            jax.tree_util.tree_leaves(exported["opt_state"])
        ):
            arrays["%s%06d" % (_OPT_PREFIX, i)] = np.asarray(leaf)
        spec = getattr(trainer, "_optimizer_spec", None)
        if spec is not None:
            arrays[_OPT_SPEC_KEY] = np.bytes_(spec.name.encode())
    if include_training_state and exported.get("rng") is not None:
        arrays[_RNG_KEY] = np.asarray(exported["rng"])
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    np.savez(
        path[: -len(".npz")],
        __version__=np.int64(exported["version"]),
        **arrays,
    )
    logger.info("Saved model checkpoint to %s", path)


def restore_trainer_checkpoint(trainer, path):
    """Restore into an ALREADY-INITIALIZED trainer (variables define the
    pytree to fill)."""
    with np.load(_normalize(path)) as data:
        meta_keys = {"__version__", _RNG_KEY, _OPT_SPEC_KEY}
        named = {
            k: data[k]
            for k in data.files
            if k not in meta_keys and not k.startswith(_OPT_PREFIX)
        }
        opt_leaves = [
            data[k]
            for k in sorted(data.files)
            if k.startswith(_OPT_PREFIX) and k != _OPT_SPEC_KEY
        ]
        saved_spec = (
            bytes(data[_OPT_SPEC_KEY]).decode()
            if _OPT_SPEC_KEY in data.files
            else None
        )
        rng = data[_RNG_KEY] if _RNG_KEY in data.files else None
        version = int(data["__version__"])
    exported = trainer.export_variables()
    exported["variables"] = unflatten_like(exported["variables"], named)
    exported["version"] = version
    exported["rng"] = rng
    cur_spec = getattr(trainer, "_optimizer_spec", None)
    if opt_leaves and exported.get("opt_state") is not None:
        cur_leaves, treedef = jax.tree_util.tree_flatten(
            exported["opt_state"]
        )
        # Structural match alone can't tell adam moments from another
        # optimizer's identically-shaped slots, so the spec name is
        # compared too when both sides carry one.
        spec_ok = (
            saved_spec is None
            or cur_spec is None
            or saved_spec == cur_spec.name
        )
        compatible = spec_ok and len(cur_leaves) == len(opt_leaves) and all(
            tuple(np.shape(cur)) == tuple(np.shape(saved))
            # .dtype avoids np.asarray, which would pull device leaves to
            # host just to read their dtype.
            and np.dtype(getattr(cur, "dtype", type(cur))) == saved.dtype
            for cur, saved in zip(cur_leaves, opt_leaves)
        )
        if compatible:
            exported["opt_state"] = jax.tree_util.tree_unflatten(
                treedef, opt_leaves
            )
        else:
            logger.warning(
                "Checkpoint optimizer state (%d leaves) does not match the "
                "current optimizer's structure/shapes (%d leaves; optimizer "
                "spec changed?); re-initializing optimizer state",
                len(opt_leaves),
                len(cur_leaves),
            )
            exported["opt_state"] = None
    else:
        exported["opt_state"] = None
    trainer.restore_variables(exported)
    logger.info("Restored model checkpoint from %s (version %d)", path, version)


class ExportModelCallback:
    """Train-end callback writing the final model (reference
    SavedModelExporter.on_train_end, master/callbacks.py:38-66)."""

    def __init__(self, output_path):
        self._path = output_path

    def on_train_end(self, trainer):
        # A model export, not a resume point: ship weights only (Adam
        # moments would triple the artifact).
        save_trainer_checkpoint(
            trainer, self._path, include_training_state=False
        )
