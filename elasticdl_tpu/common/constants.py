"""Framework-wide constants (reference:
/root/reference/elasticdl/python/common/constants.py,
elasticdl_client/common/constants.py:15)."""


class DistributionStrategy:
    LOCAL = "Local"
    PARAMETER_SERVER = "ParameterServerStrategy"
    ALLREDUCE = "AllreduceStrategy"


class JobType:
    TRAINING_ONLY = "training_only"
    EVALUATION_ONLY = "evaluation_only"
    PREDICTION_ONLY = "prediction_only"
    TRAINING_WITH_EVALUATION = "training_with_evaluation"


class PodStatus:
    INITIAL = "Initial"
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DELETED = "Deleted"


# Per-minibatch retry cap (reference worker/worker.py:37).
DEFAULT_MAX_MINIBATCH_RETRY_NUM = 64

# Per-task retry cap in the dispatcher (reference master/task_dispatcher.py).
MAX_TASK_RETRIES = 3

# Membership re-check cadence in AllReduce training, in steps
# (reference worker/allreduce_trainer.py:141-148).
COMM_WORLD_CHECK_STEPS = 20

# Allreduce communication retry cap (reference allreduce_trainer.py:125-139).
MAX_ALLREDUCE_RETRY_NUM = 5

# Width of the jax.distributed coordination-port rotation window: across
# membership epochs rank 0 binds coordinator_port + (epoch % width), so the
# job reserves the block [coordinator_port, coordinator_port + width - 1]
# (master/membership.py:get_comm_rank; validate_args keeps master_port out).
COORDINATOR_PORT_ROTATION = 16
