"""Streaming evaluation metrics, framework-free.

The reference accumulates worker-reported raw model outputs + labels into
Keras metric objects on the master (/root/reference/elasticdl/python/common/
evaluation_utils.py:20-110). Here metrics are small numpy accumulator objects
with update(outputs, labels) / result() so the master needs no ML framework.
The model-zoo contract's eval_metrics_fn returns {name: metric}, where a
metric is either one of these objects or a plain fn(outputs, labels) ->
per-example values (averaged automatically).
"""

import numpy as np


class MeanMetric:
    """Averages fn(outputs, labels) per-example values across updates."""

    def __init__(self, fn):
        self._fn = fn
        self._total = 0.0
        self._count = 0

    def update(self, outputs, labels):
        values = np.asarray(self._fn(outputs, labels), dtype=np.float64)
        self._total += float(values.sum())
        self._count += int(values.size)

    def result(self):
        return self._total / max(self._count, 1)

    def reset(self):
        self._total, self._count = 0.0, 0


def accuracy_metric():
    return MeanMetric(
        lambda outputs, labels: (
            np.argmax(outputs, axis=-1) == np.asarray(labels).reshape(-1)
        ).astype(np.float64)
    )


class AUCMetric:
    """Streaming ROC AUC via fixed-threshold confusion buckets (the same
    approach as Keras' AUC metric, 200 thresholds)."""

    def __init__(self, num_thresholds=200):
        self._thresholds = np.linspace(0.0, 1.0, num_thresholds)
        self._tp = np.zeros(num_thresholds)
        self._fp = np.zeros(num_thresholds)
        self._tn = np.zeros(num_thresholds)
        self._fn = np.zeros(num_thresholds)

    def update(self, outputs, labels):
        scores = np.asarray(outputs, dtype=np.float64).reshape(-1)
        labels = np.asarray(labels).reshape(-1).astype(bool)
        pred_pos = scores[None, :] >= self._thresholds[:, None]
        self._tp += (pred_pos & labels[None, :]).sum(axis=1)
        self._fp += (pred_pos & ~labels[None, :]).sum(axis=1)
        self._fn += (~pred_pos & labels[None, :]).sum(axis=1)
        self._tn += (~pred_pos & ~labels[None, :]).sum(axis=1)

    def result(self):
        tpr = self._tp / np.maximum(self._tp + self._fn, 1e-9)
        fpr = self._fp / np.maximum(self._fp + self._tn, 1e-9)
        # Thresholds ascend -> fpr/tpr descend; integrate with trapezoids.
        return float(np.trapezoid(tpr[::-1], fpr[::-1]))

    def reset(self):
        for acc in (self._tp, self._fp, self._tn, self._fn):
            acc[:] = 0


def as_metric(obj):
    """Normalize a zoo-provided metric (object or callable) to the
    update/result protocol."""
    if hasattr(obj, "update") and hasattr(obj, "result"):
        return obj
    return MeanMetric(obj)


CHUNK_SIZE = 4096


def update_metrics_chunked(metrics, outputs, labels):
    """Feed large eval payloads to metrics in chunks (reference
    evaluation_utils.py:96-110 uses the same trick to bound memory)."""
    n = len(labels)
    multi_output = isinstance(outputs, (list, tuple))
    for begin in range(0, n, CHUNK_SIZE):
        sl = slice(begin, min(begin + CHUNK_SIZE, n))
        chunk = [o[sl] for o in outputs] if multi_output else outputs[sl]
        for metric in metrics.values():
            metric.update(chunk, labels[sl])
