"""Pod resource / volume / priority spec parsing for the k8s backend.

Reference counterparts: /root/reference/elasticdl_client/common/
k8s_resource.py:51 ("cpu=250m,memory=32Mi,gpu=1" -> resource dict with
validation), k8s_volume.py:29-151 ("host_path=...,mount_path=...;
claim_name=...,mount_path=...") and the worker-priority fraction syntax
("high=0.5" -> the first half of workers get the high priority class,
master/k8s_instance_manager.py:28-50). TPU-first addition: a bare `tpu=N`
resource maps to the google.com/tpu device resource the way `gpu=N` maps
to nvidia.com/gpu.

Everything here is plain dict/string manipulation — no kubernetes import —
so manifests can be built and validated anywhere (tests, --yaml dumps);
only the k8s client turns them into API objects.
"""

import re

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.k8s_resource")

# Kubernetes quantities allow decimals ("1.5Gi", "0.5G").
_MEM_RE = re.compile(
    r"^(0|[1-9][0-9]*)(\.[0-9]+)?(E|P|T|G|M|K|Ei|Pi|Ti|Gi|Mi|Ki)?$"
)
_CPU_MILLI_RE = re.compile(r"^[1-9][0-9]*m$")
_DEVICE_DOMAIN_RE = re.compile(
    r"^[a-zA-Z\d-]{1,63}(\.[a-zA-Z\d-]{1,63})*/(gpu|tpu)$"
)

_MEMORY_KINDS = ("memory", "disk", "ephemeral-storage")


def _numeric(s):
    try:
        float(s)
        return True
    except ValueError:
        return False


def parse_resource_spec(spec):
    """'cpu=250m,memory=32Mi,gpu=1,tpu=4' -> k8s resource dict.

    gpu/tpu shorthands expand to their canonical device-plugin resource
    names; full vendor names (amd.com/gpu=1) pass through validated."""
    resources = {}
    if not spec:
        return resources
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"malformed resource entry {part!r}")
        name, value = (x.strip() for x in part.split("=", 1))
        if name in _MEMORY_KINDS:
            if not _MEM_RE.match(value):
                raise ValueError(
                    f"invalid {name} quantity {value!r} "
                    "(expected e.g. 4096Mi, 2Gi)"
                )
            # 'disk' is the reference's CLI shorthand; the API server only
            # knows ephemeral-storage.
            key = "ephemeral-storage" if name == "disk" else name
        elif name == "cpu":
            if not (_CPU_MILLI_RE.match(value) or _numeric(value)):
                raise ValueError(f"invalid cpu quantity {value!r}")
            key = "cpu"
        elif name == "gpu":
            if not value.isdigit():
                raise ValueError(f"invalid gpu count {value!r}")
            key = "nvidia.com/gpu"
        elif name == "tpu":
            if not value.isdigit():
                raise ValueError(f"invalid tpu count {value!r}")
            key = "google.com/tpu"
        elif _DEVICE_DOMAIN_RE.match(name):
            if not value.isdigit():
                raise ValueError(f"invalid device count {value!r}")
            key = name
        else:
            raise ValueError(f"unknown resource type {name!r}")
        resources[key] = value
    return resources


def parse_volume_spec(spec):
    """'host_path=/data,mount_path=/data;claim_name=c1,mount_path=/m1'
    -> list of {"kind": "host_path"|"pvc", "source": ..., "mount_path":
    ..., "sub_path": optional}. Volumes sharing a source are deduplicated
    by the manifest builder (one volume, many mounts)."""
    volumes = []
    if not spec:
        return volumes
    for group in spec.split(";"):
        group = group.strip()
        if not group:
            continue
        fields = {}
        for part in group.split(","):
            if "=" not in part:
                raise ValueError(f"malformed volume entry {part!r}")
            k, v = (x.strip() for x in part.split("=", 1))
            fields[k] = v
        if "mount_path" not in fields:
            raise ValueError(f"volume spec {group!r} missing mount_path")
        if "claim_name" in fields:
            volumes.append(
                {
                    "kind": "pvc",
                    "source": fields["claim_name"],
                    "mount_path": fields["mount_path"],
                    **(
                        {"sub_path": fields["sub_path"]}
                        if "sub_path" in fields
                        else {}
                    ),
                }
            )
        elif "host_path" in fields:
            volumes.append(
                {
                    "kind": "host_path",
                    "source": fields["host_path"],
                    "mount_path": fields["mount_path"],
                }
            )
        else:
            raise ValueError(
                f"volume spec {group!r} needs host_path or claim_name"
            )
    return volumes


def group_volume_manifests(volume_dicts):
    """Parsed volume dicts -> (pod volume manifests, container mount
    manifests) in plain k8s JSON form, deduplicated by source (one volume,
    many mounts). The ONLY place the grouping/branching lives: the master
    manifest uses these dicts verbatim and the kubernetes client converts
    them to V1 objects."""
    volumes, mounts, by_source = [], [], {}
    for vd in volume_dicts:
        key = (vd["kind"], vd["source"])
        name = by_source.get(key)
        if name is None:
            name = f"edl-vol-{len(volumes)}"
            by_source[key] = name
            if vd["kind"] == "pvc":
                volumes.append(
                    {
                        "name": name,
                        "persistentVolumeClaim": {
                            "claimName": vd["source"],
                            "readOnly": False,
                        },
                    }
                )
            else:
                volumes.append(
                    {"name": name, "hostPath": {"path": vd["source"]}}
                )
        mount = {"name": name, "mountPath": vd["mount_path"]}
        if "sub_path" in vd:
            mount["subPath"] = vd["sub_path"]
        mounts.append(mount)
    return volumes, mounts


def parse_worker_priority(spec, num_workers):
    """Per-worker priority classes. 'high=0.5' gives the first half of the
    workers the 'high' class and the rest 'low' (the reference's fraction
    syntax); any other non-empty string applies to every worker."""
    if not spec:
        return {i: None for i in range(num_workers)}
    if spec.startswith("high="):
        try:
            fraction = float(spec.split("=", 1)[1])
        except ValueError:
            raise ValueError(
                f"bad worker priority spec {spec!r}: the fraction form "
                "is 'high=<fraction>', e.g. high=0.5"
            )
        high = int(num_workers * fraction)
        return {
            i: ("high" if i < high else "low")
            for i in range(num_workers)
        }
    if "=" in spec:
        # Anything else containing '=' is a malformed fraction spec, NOT
        # a literal class name ('low=0.3' is never a valid k8s
        # priorityClassName) — fail at parse time, not pod creation.
        raise ValueError(
            f"bad worker priority spec {spec!r}: the fraction form is "
            "'high=<fraction>'; otherwise give a plain priority class "
            "name"
        )
    return {i: spec for i in range(num_workers)}
