"""Persistent XLA compilation cache wiring (recompile-free elasticity).

The compile tracker proved that compile IS the elastic rejoin: every
worker relaunch — the common preemption case — cold-compiled a step this
host had already compiled, minutes of accumulated dead time at
production pod-churn rates. jax ships a content-addressed persistent
compilation cache (HLO-keyed executables on disk); this module is the
one place the framework turns it on, from the registered
`ELASTICDL_COMPILE_CACHE_DIR` knob, so that:

- a RELAUNCHED worker rehydrates its step executables from disk and
  pays only trace+lower on its first minibatch (the `compile_cache_hit`
  event in observability/profiling.py, not a cold `compile`);
- a multi-host regroup that re-initializes jax.distributed (tearing
  down every live executable) re-lowers into warm disk entries;
- SPECULATIVE world compiles (worker/world_speculator.py) persist: even
  when the guessed executable object dies with a backend re-init, its
  disk entry survives for the re-lowering on the other side.

Both instance managers stamp the knob into every child's environment,
so one `edl train` invocation warms a single cache for the whole job
(all ranks lower the same SPMD program — one rank's miss is every
later rank's hit).

Thresholds are zeroed (`min_compile_time_secs`, `min_entry_size`):
elasticity cares about the many small programs around the step (eval
forwards, broadcast zero-templates), not only the headline compile.
"""

import os
import threading

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.compile_cache")

CACHE_DIR_ENV = "ELASTICDL_COMPILE_CACHE_DIR"

_lock = threading.Lock()
_configured = None  # dir string once wired, "" once checked-and-disabled


def ensure_compile_cache():
    """Idempotently point jax at the persistent compilation cache
    directory named by ELASTICDL_COMPILE_CACHE_DIR. Returns the dir, or
    None when the knob is unset (or jax lacks the config surface). Safe
    to call from every trainer/bench/role entrypoint — the first caller
    wins, later calls are a lock + string compare."""
    global _configured
    with _lock:
        if _configured is not None:
            return _configured or None
        cache_dir = knobs.get_str(CACHE_DIR_ENV)
        if not cache_dir:
            _configured = ""
            return None
        try:
            os.makedirs(cache_dir, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # Cache EVERYTHING: the defaults skip sub-second compiles
            # and small executables, which is exactly the long tail a
            # relaunched worker re-pays (eval forward, state templates).
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1
            )
        except Exception:
            logger.warning(
                "Could not enable the persistent compilation cache at "
                "%s; compiles will not survive relaunches",
                cache_dir,
                exc_info=True,
            )
            _configured = ""
            return None
        _configured = cache_dir
        logger.info("Persistent compilation cache at %s", cache_dir)
        return cache_dir


def reset_for_tests():
    """Drop the memoized wiring so a test can re-point the cache."""
    global _configured
    with _lock:
        _configured = None
