"""Pytree <-> wire-name mapping shared by the PS client path, worker
checkpoints, and state broadcast."""

import jax


def _path_name(path):
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path
    )


def flatten_params(params):
    """params pytree -> ({wire_name: leaf}, [names in leaf order]). Names
    are '/'-joined dict paths ('Dense_0/kernel'), stable across workers."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    named = {}
    names = []
    for path, leaf in flat:
        name = _path_name(path)
        named[name] = leaf
        names.append(name)
    return named, names


def unflatten_like(params, named):
    """Rebuild a params-shaped pytree taking leaves from `named` by wire
    name (missing names keep the existing leaf)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves = []
    for path, leaf in flat:
        leaves.append(named.get(_path_name(path), leaf))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def walk_dict(tree, path=()):
    """Yield (path_tuple, leaf) over a nested mapping (dict or flax
    FrozenDict)."""
    for k, v in tree.items():
        if hasattr(v, "items"):
            yield from walk_dict(v, path + (k,))
        else:
            yield path + (k,), v


def nest_at(paths_to_values):
    """{path_tuple: value} -> nested dict."""
    nested = {}
    for path, value in paths_to_values.items():
        node = nested
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = value
    return nested


def get_at(tree, path):
    node = tree
    for k in path:
        node = node[k]
    return node
