"""Logger factory with per-module levels (reference:
/root/reference/elasticdl/python/common/log_utils.py:33).

Environment knobs (read once, at first get_logger; `configure(force=True)`
re-reads them):

  ELASTICDL_LOG_LEVEL    DEBUG/INFO/WARNING/ERROR (or a number); default INFO
  ELASTICDL_LOG_FORMAT   "json" switches to one JSON object per line with
                         job/pod identity, machine-parseable alongside the
                         observability event log; anything else keeps the
                         human format.

Identity (job name, role) is stamped into JSON records; it comes from
set_identity() (called by observability.setup) or the ELASTICDL_JOB_NAME /
ELASTICDL_ROLE environment variables the master sets for spawned instances.
"""

import json
import logging
import sys

from elasticdl_tpu.common import knobs

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"
_configured = False
_identity = {}


def set_identity(job="", role=""):
    """Attach job/pod identity to subsequent JSON log records."""
    if job:
        _identity["job"] = job
    if role:
        _identity["role"] = role


class JsonFormatter(logging.Formatter):
    def format(self, record):
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "line": f"{record.filename}:{record.lineno}",
            "msg": record.getMessage(),
        }
        out.update(_identity)
        if not _identity:
            job = knobs.get_str("ELASTICDL_JOB_NAME")
            role = knobs.get_str("ELASTICDL_ROLE")
            if job:
                out["job"] = job
            if role:
                out["role"] = role
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, separators=(",", ":"))


def _resolve_level():
    raw = knobs.get_str("ELASTICDL_LOG_LEVEL").strip()
    if not raw:
        return logging.INFO
    if raw.isdigit():
        return int(raw)
    return getattr(logging, raw.upper(), logging.INFO)


def configure(force=False):
    """(Re)configure the package root logger from the environment."""
    global _configured
    if _configured and not force:
        return
    root = logging.getLogger("elasticdl_tpu")
    for handler in list(root.handlers):
        root.removeHandler(handler)
    handler = logging.StreamHandler(sys.stderr)
    if knobs.get_str("ELASTICDL_LOG_FORMAT").lower() == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(_FORMAT))
    root.addHandler(handler)
    root.propagate = False
    root.setLevel(_resolve_level())
    _configured = True


def get_logger(name: str, level=None) -> logging.Logger:
    configure()
    logger = logging.getLogger(f"elasticdl_tpu.{name}")
    if level is not None:
        logger.setLevel(level)
    return logger
