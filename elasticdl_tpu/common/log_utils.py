"""Logger factory with per-module levels (reference:
/root/reference/elasticdl/python/common/log_utils.py:33)."""

import logging
import sys

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"
_configured = False


def _configure_root():
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    root = logging.getLogger("elasticdl_tpu")
    root.addHandler(handler)
    root.propagate = False
    root.setLevel(logging.INFO)
    _configured = True


def get_logger(name: str, level=None) -> logging.Logger:
    _configure_root()
    logger = logging.getLogger(f"elasticdl_tpu.{name}")
    if level is not None:
        logger.setLevel(level)
    return logger
