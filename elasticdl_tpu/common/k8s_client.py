"""Thin Kubernetes client for pod create/watch/delete.

Reference counterpart: /root/reference/elasticdl/python/common/
k8s_client.py:40-300 and the client-package base
(elasticdl_client/common/k8s_client.py:50-242). Import-gated: the
`kubernetes` package is an optional dependency — everything cluster-facing
lives behind this module so the rest of the framework imports cleanly
without it.
"""

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.k8s_client")

try:  # pragma: no cover - exercised only on a real cluster
    from kubernetes import client as k8s_api
    from kubernetes import config as k8s_config
    from kubernetes import watch as k8s_watch

    K8S_AVAILABLE = True
except ImportError:  # pragma: no cover
    k8s_api = k8s_config = k8s_watch = None
    K8S_AVAILABLE = False

ELASTICDL_JOB_KEY = "elasticdl-job-name"
ELASTICDL_REPLICA_TYPE_KEY = "elasticdl-replica-type"
ELASTICDL_REPLICA_INDEX_KEY = "elasticdl-replica-index"


def build_volumes(volume_dicts):
    """Parsed volume dicts (common/k8s_resource.parse_volume_spec) ->
    (V1Volume list, V1VolumeMount list). The grouping/dedup logic lives
    in k8s_resource.group_volume_manifests (shared with the master-pod
    manifest builder); this only converts dict manifests to V1 objects."""
    if not volume_dicts:
        return [], []
    require_k8s()
    from elasticdl_tpu.common.k8s_resource import group_volume_manifests

    vol_manifests, mount_manifests = group_volume_manifests(volume_dicts)
    volumes = []
    for v in vol_manifests:
        if "persistentVolumeClaim" in v:
            pvc = v["persistentVolumeClaim"]
            volumes.append(
                k8s_api.V1Volume(
                    name=v["name"],
                    persistent_volume_claim=(
                        k8s_api.V1PersistentVolumeClaimVolumeSource(
                            claim_name=pvc["claimName"],
                            read_only=pvc["readOnly"],
                        )
                    ),
                )
            )
        else:
            volumes.append(
                k8s_api.V1Volume(
                    name=v["name"],
                    host_path=k8s_api.V1HostPathVolumeSource(
                        path=v["hostPath"]["path"]
                    ),
                )
            )
    mounts = [
        k8s_api.V1VolumeMount(
            name=m["name"],
            mount_path=m["mountPath"],
            sub_path=m.get("subPath"),
        )
        for m in mount_manifests
    ]
    return volumes, mounts


def require_k8s():
    if not K8S_AVAILABLE:
        raise RuntimeError(
            "the 'kubernetes' python package is not installed; "
            "K8s-backed instance management is unavailable "
            "(use the local-process backend or install kubernetes)"
        )


class Client:  # pragma: no cover - exercised only on a real cluster
    """Pod lifecycle for one job's master/worker/PS replicas."""

    def __init__(self, namespace, job_name, image_name, event_callback=None):
        require_k8s()
        try:
            k8s_config.load_incluster_config()
        except Exception:
            k8s_config.load_kube_config()
        self.namespace = namespace
        self.job_name = job_name
        self.image_name = image_name
        self._v1 = k8s_api.CoreV1Api()
        self._event_cb = event_callback
        if event_callback:
            import threading

            threading.Thread(target=self._watch, daemon=True).start()

    def _watch(self):
        w = k8s_watch.Watch()
        while True:
            try:
                for event in w.stream(
                    self._v1.list_namespaced_pod,
                    self.namespace,
                    label_selector=f"{ELASTICDL_JOB_KEY}={self.job_name}",
                ):
                    self._event_cb(event)
            except Exception:
                logger.warning("k8s watch stream reset", exc_info=True)

    def pod_name(self, replica_type, replica_index):
        return (
            f"elasticdl-{self.job_name}-{replica_type}-{replica_index}"
        )

    def create_pod(
        self,
        replica_type,
        replica_index,
        command,
        resource_requests=None,
        resource_limits=None,
        priority_class=None,
        envs=None,
        volumes=None,
        restart_policy="Never",
    ):
        env = [
            k8s_api.V1EnvVar(name=k, value=v)
            for k, v in (envs or {}).items()
        ]
        # Every replica learns its own routable address (workers advertise
        # it as their comm host; the master binds on it).
        env.append(
            k8s_api.V1EnvVar(
                name="MY_POD_IP",
                value_from=k8s_api.V1EnvVarSource(
                    field_ref=k8s_api.V1ObjectFieldSelector(
                        field_path="status.podIP"
                    )
                ),
            )
        )
        pod_volumes, mounts = build_volumes(volumes or [])
        container = k8s_api.V1Container(
            name="main",
            image=self.image_name,
            command=command,
            resources=k8s_api.V1ResourceRequirements(
                requests=resource_requests, limits=resource_limits
            ),
            env=env,
            volume_mounts=mounts or None,
        )
        pod = k8s_api.V1Pod(
            metadata=k8s_api.V1ObjectMeta(
                name=self.pod_name(replica_type, replica_index),
                labels={
                    ELASTICDL_JOB_KEY: self.job_name,
                    ELASTICDL_REPLICA_TYPE_KEY: replica_type,
                    ELASTICDL_REPLICA_INDEX_KEY: str(replica_index),
                },
            ),
            spec=k8s_api.V1PodSpec(
                containers=[container],
                restart_policy=restart_policy,
                priority_class_name=priority_class,
                volumes=pod_volumes or None,
            ),
        )
        return self._v1.create_namespaced_pod(self.namespace, pod)

    def create_pod_from_manifest(self, manifest):
        """Create a pod from a raw manifest dict (used for the master pod so
        serviceAccountName/env fieldRefs survive verbatim)."""
        return self._v1.create_namespaced_pod(self.namespace, manifest)

    def create_service(self, name, port, replica_type, replica_index):
        """Stable DNS name for a replica (PS pods get one each, reference
        common/k8s_client.py service creation)."""
        service = k8s_api.V1Service(
            metadata=k8s_api.V1ObjectMeta(
                name=name,
                labels={ELASTICDL_JOB_KEY: self.job_name},
            ),
            spec=k8s_api.V1ServiceSpec(
                selector={
                    ELASTICDL_JOB_KEY: self.job_name,
                    ELASTICDL_REPLICA_TYPE_KEY: replica_type,
                    ELASTICDL_REPLICA_INDEX_KEY: str(replica_index),
                },
                ports=[k8s_api.V1ServicePort(port=port)],
            ),
        )
        return self._v1.create_namespaced_service(self.namespace, service)

    def delete_pod(self, replica_type, replica_index):
        self._v1.delete_namespaced_pod(
            self.pod_name(replica_type, replica_index), self.namespace
        )

    def get_pod_phase(self, replica_type, replica_index):
        pod = self._v1.read_namespaced_pod(
            self.pod_name(replica_type, replica_index), self.namespace
        )
        return pod.status.phase
