"""Kubernetes client for pod create/watch/delete.

Reference counterpart: /root/reference/elasticdl/python/common/
k8s_client.py:40-300 and the client-package base
(elasticdl_client/common/k8s_client.py:50-242). Two transports behind one
surface:

- the official `kubernetes` package when importable (real clusters with a
  kubeconfig), or
- the stdlib REST transport (common/k8s_rest.py) against the in-cluster
  service account or EDL_K8S_API_SERVER — no optional dependency needed,
  and the wire path is testable against a local stub API server
  (tests/fake_k8s_server.py).

Pod/service bodies are plain manifest dicts (both transports accept them
verbatim), so what the tests assert is exactly what a cluster receives.
"""

import threading

from elasticdl_tpu.common import k8s_rest
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.k8s_client")

try:  # pragma: no cover - exercised only on a real cluster
    from kubernetes import client as k8s_api  # noqa: F401
    from kubernetes import config as k8s_config
    from kubernetes import watch as k8s_watch

    K8S_PACKAGE_AVAILABLE = True
except ImportError:  # pragma: no cover
    k8s_api = k8s_config = k8s_watch = None
    K8S_PACKAGE_AVAILABLE = False

# Backwards-compatible alias (pre-round-3 code gated on the package only).
K8S_AVAILABLE = K8S_PACKAGE_AVAILABLE

ELASTICDL_JOB_KEY = "elasticdl-job-name"
ELASTICDL_REPLICA_TYPE_KEY = "elasticdl-replica-type"
ELASTICDL_REPLICA_INDEX_KEY = "elasticdl-replica-index"


def k8s_reachable():
    return K8S_PACKAGE_AVAILABLE or k8s_rest.default_rest_api() is not None


def require_k8s():
    if not k8s_reachable():
        raise RuntimeError(
            "no Kubernetes access: the 'kubernetes' package is not "
            "installed and neither EDL_K8S_API_SERVER nor an in-cluster "
            "service account is present (use the local-process backend, "
            "install kubernetes, or point EDL_K8S_API_SERVER at an API "
            "server)"
        )


def build_pod_manifest(
    name,
    labels,
    image,
    command,
    resource_requests=None,
    resource_limits=None,
    priority_class=None,
    envs=None,
    volumes=None,
    restart_policy="Never",
):
    """One replica pod as a manifest dict (shared by both transports and
    asserted verbatim by the stub-server tests)."""
    from elasticdl_tpu.common.k8s_resource import group_volume_manifests

    env = [
        {"name": k, "value": v} for k, v in (envs or {}).items()
    ]
    # Every replica learns its own routable address (workers advertise it
    # as their comm host; the master binds on it).
    env.append(
        {
            "name": "MY_POD_IP",
            "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}},
        }
    )
    vol_manifests, mount_manifests = group_volume_manifests(volumes or [])
    container = {
        "name": "main",
        "image": image,
        "command": list(command),
        "env": env,
        "resources": {
            **(
                {"requests": resource_requests}
                if resource_requests
                else {}
            ),
            **({"limits": resource_limits} if resource_limits else {}),
        },
        **({"volumeMounts": mount_manifests} if mount_manifests else {}),
    }
    spec = {
        "containers": [container],
        "restartPolicy": restart_policy,
        **({"volumes": vol_manifests} if vol_manifests else {}),
        **(
            {"priorityClassName": priority_class} if priority_class else {}
        ),
    }
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "labels": labels},
        "spec": spec,
    }


class Client:
    """Pod lifecycle for one job's master/worker/PS replicas."""

    def __init__(self, namespace, job_name, image_name, event_callback=None,
                 rest_api=None):
        self.namespace = namespace
        self.job_name = job_name
        self.image_name = image_name
        self._event_cb = event_callback
        self._stop_watch = threading.Event()
        self._rest = None
        self._v1 = None
        if rest_api is not None:
            self._rest = rest_api
        elif K8S_PACKAGE_AVAILABLE:  # pragma: no cover - real cluster
            try:
                k8s_config.load_incluster_config()
            except Exception:
                k8s_config.load_kube_config()
            self._v1 = k8s_api.CoreV1Api()
        else:
            self._rest = k8s_rest.default_rest_api()
            if self._rest is None:
                require_k8s()
        if event_callback:
            threading.Thread(target=self._watch, daemon=True).start()

    def stop(self):
        self._stop_watch.set()

    # ---------- watch ----------

    def _watch(self):
        selector = f"{ELASTICDL_JOB_KEY}={self.job_name}"
        if self._rest is not None:
            self._rest.watch_pods(
                self.namespace,
                selector,
                self._event_cb,
                stop_event=self._stop_watch,
            )
            return
        w = k8s_watch.Watch()  # pragma: no cover - real cluster
        while not self._stop_watch.is_set():
            try:
                for event in w.stream(
                    self._v1.list_namespaced_pod,
                    self.namespace,
                    label_selector=selector,
                ):
                    self._event_cb(event)
            except Exception:
                logger.warning("k8s watch stream reset", exc_info=True)

    # ---------- pods / services ----------

    def pod_name(self, replica_type, replica_index, incarnation=0):
        """Relaunches get a fresh name (-r<N> suffix): a Failed pod still
        occupies its name on the API server, so re-creating under the same
        name is a guaranteed 409 AlreadyExists."""
        base = f"elasticdl-{self.job_name}-{replica_type}-{replica_index}"
        return base if not incarnation else f"{base}-r{incarnation}"

    def create_pod(
        self,
        replica_type,
        replica_index,
        command,
        resource_requests=None,
        resource_limits=None,
        priority_class=None,
        envs=None,
        volumes=None,
        restart_policy="Never",
        incarnation=0,
    ):
        manifest = build_pod_manifest(
            self.pod_name(replica_type, replica_index, incarnation),
            {
                ELASTICDL_JOB_KEY: self.job_name,
                ELASTICDL_REPLICA_TYPE_KEY: replica_type,
                ELASTICDL_REPLICA_INDEX_KEY: str(replica_index),
            },
            self.image_name,
            command,
            resource_requests=resource_requests,
            resource_limits=resource_limits,
            priority_class=priority_class,
            envs=envs,
            volumes=volumes,
            restart_policy=restart_policy,
        )
        return self.create_pod_from_manifest(manifest)

    def create_pod_from_manifest(self, manifest):
        """Create a pod from a raw manifest dict (the master pod keeps its
        serviceAccountName/env fieldRefs verbatim)."""
        if self._rest is not None:
            return self._rest.create_pod(self.namespace, manifest)
        return self._v1.create_namespaced_pod(self.namespace, manifest)

    def create_service(self, name, port, replica_type, replica_index):
        """Stable DNS name for a replica (PS pods get one each, reference
        common/k8s_client.py service creation)."""
        manifest = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": name,
                "labels": {ELASTICDL_JOB_KEY: self.job_name},
            },
            "spec": {
                "selector": {
                    ELASTICDL_JOB_KEY: self.job_name,
                    ELASTICDL_REPLICA_TYPE_KEY: replica_type,
                    ELASTICDL_REPLICA_INDEX_KEY: str(replica_index),
                },
                "ports": [{"port": port}],
            },
        }
        if self._rest is not None:
            return self._rest.create_service(self.namespace, manifest)
        return self._v1.create_namespaced_service(self.namespace, manifest)

    def create_tensorboard_service(self, port=6006):
        """LoadBalancer service exposing the master pod's TensorBoard
        (reference common/k8s_tensorboard_client.py:22-66): in-cluster
        jobs get an external URL for `edl tensorboard`'s server."""
        manifest = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": f"tensorboard-{self.job_name}",
                "labels": {ELASTICDL_JOB_KEY: self.job_name},
            },
            "spec": {
                "type": "LoadBalancer",
                "selector": {
                    ELASTICDL_JOB_KEY: self.job_name,
                    ELASTICDL_REPLICA_TYPE_KEY: "master",
                },
                "ports": [{"port": port, "targetPort": port}],
            },
        }
        if self._rest is not None:
            return self._rest.create_service(self.namespace, manifest)
        return self._v1.create_namespaced_service(self.namespace, manifest)

    def get_tensorboard_external_ip(self):
        """External address of the TensorBoard LoadBalancer once the cloud
        provider assigns one (None until then)."""
        name = f"tensorboard-{self.job_name}"
        if self._rest is not None:
            svc = self._rest.read_service(self.namespace, name)
            ingress = (
                ((svc.get("status") or {}).get("loadBalancer") or {}).get(
                    "ingress"
                )
                or []
            )
            return ingress[0].get("ip") if ingress else None
        svc = self._v1.read_namespaced_service(name, self.namespace)
        ingress = (
            svc.status.load_balancer.ingress
            if svc.status and svc.status.load_balancer
            else None
        )
        return ingress[0].ip if ingress else None

    def delete_pod(self, replica_type, replica_index, incarnation=0):
        name = self.pod_name(replica_type, replica_index, incarnation)
        if self._rest is not None:
            return self._rest.delete_pod(self.namespace, name)
        return self._v1.delete_namespaced_pod(name, self.namespace)

    def _read_phase(self, name):
        if self._rest is not None:
            pod = self._rest.read_pod(self.namespace, name)
            return (pod.get("status") or {}).get("phase")
        pod = self._v1.read_namespaced_pod(name, self.namespace)
        return pod.status.phase

    def get_pod_phase(self, replica_type, replica_index, incarnation=0):
        return self._read_phase(
            self.pod_name(replica_type, replica_index, incarnation)
        )

    def list_job_pod_phases(self):
        """{pod_name: phase} for every pod labeled with this job — covers
        incarnation-suffixed relaunches that fixed-name polling misses
        (monitors report what actually exists, not what was first
        launched)."""
        selector = f"{ELASTICDL_JOB_KEY}={self.job_name}"
        phases = {}
        if self._rest is not None:
            listing = self._rest.list_pods(self.namespace, selector)
            for item in listing.get("items", []):
                name = (item.get("metadata") or {}).get("name")
                if name:
                    phases[name] = (item.get("status") or {}).get("phase")
            return phases
        pods = self._v1.list_namespaced_pod(
            self.namespace, label_selector=selector
        )
        for pod in pods.items:
            phases[pod.metadata.name] = (
                pod.status.phase if pod.status else None
            )
        return phases

    def get_pod_phase_by_name(self, name):
        """Phase of an arbitrarily-named pod (e.g. the master, which lives
        outside the replica naming convention); None when the pod does
        not exist — job monitors poll with this and absence is an answer.
        Auth/network errors still raise: a monitor silently reading None
        for 10 minutes on a 403 helps nobody."""
        try:
            return self._read_phase(name)
        except Exception as e:
            # Both transports carry the HTTP status as .status
            # (k8s_rest.K8sApiError and the official ApiException).
            if getattr(e, "status", None) == 404:
                return None
            raise
