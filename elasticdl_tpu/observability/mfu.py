"""Per-step MFU (model FLOPs utilization) estimation for workers.

The trainer hands its jitted step function plus the step's example
arguments to a StepCostModel once per minibatch. The model:

- computes the step's FLOPs once per argument-shape signature via
  `jitted.lower(*args).compile().cost_analysis()` (an XLA estimate; the
  AOT lowering is a one-time cost per shape, cached forever after),
- measures the steady-state step period as the wall time BETWEEN
  successive observe() calls (which includes pulls/pushes/feed — MFU is
  utilization of the whole loop, not of the kernel in isolation), and
- exports `edl_worker_step_flops` and, when a peak-FLOPs figure is
  known, `edl_worker_mfu` gauges that the master's aggregator re-exports
  as `edl_job_mfu{worker=...}`.

Everything is guarded: a backend without cost_analysis, an un-lowerable
step, or an unknown peak simply leaves the gauges absent — never a
training failure. ELASTICDL_MFU=0 disables the lowering entirely;
ELASTICDL_PEAK_FLOPS overrides (or provides) the per-device peak.
"""

import threading
import time

from elasticdl_tpu.common import knobs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability.metrics import default_registry

logger = get_logger("observability.mfu")

MFU_ENV = "ELASTICDL_MFU"
PEAK_FLOPS_ENV = "ELASTICDL_PEAK_FLOPS"

# Dense peak FLOP/s by device kind (bf16, no sparsity), for the common
# TPU generations; anything unrecognized needs ELASTICDL_PEAK_FLOPS.
_DEVICE_PEAK_FLOPS = {
    "TPU v2": 22.5e12,
    "TPU v3": 61.25e12,  # per-chip: 2 cores x 30.6 TF/s
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
}

_REG = default_registry()
_STEP_FLOPS = _REG.gauge(
    "edl_worker_step_flops",
    "XLA-estimated FLOPs of one training step (current shape)",
)
_MFU = _REG.gauge(
    "edl_worker_mfu",
    "Estimated model FLOPs utilization (step flops / period / peak)",
)
_STEP_PERIOD = _REG.gauge(
    "edl_worker_step_period_seconds",
    "EWMA wall time between successive training steps",
)

_EWMA_ALPHA = 0.2


def enabled():
    """ELASTICDL_MFU: 1/true forces on, 0/false forces off; the default
    ("auto") activates only in processes that configured the
    observability plane (worker/PS/master entrypoints call setup()).
    Bare trainer construction — unit tests, library embedding — then
    skips the per-shape AOT lowering entirely."""
    raw = knobs.get_str(MFU_ENV).lower()
    if raw in ("0", "false", "no"):
        return False
    if raw in ("1", "true", "yes"):
        return True
    from elasticdl_tpu import observability

    return observability.current_handle() is not None


def peak_flops():
    """Per-device peak FLOP/s: env override first, then the device-kind
    table; None when unknown (MFU gauge stays absent then)."""
    override = knobs.get_float(PEAK_FLOPS_ENV)
    if override:
        return override
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    for name, peak in _DEVICE_PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak
    return None


def shape_key(args):
    """Hashable (shape, dtype) signature of a step's argument pytree."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    return tuple(
        (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", "")))
        for l in leaves
    )


def _analyzed_flops(jitted, spec):
    """FLOPs from XLA's compiled-cost analysis; None when unavailable.
    `spec` is a ShapeDtypeStruct pytree (AOT lowering needs shapes only —
    never live buffers, which the real step may have donated by the time
    the analysis thread runs). cost_analysis() returns a dict (newer jax)
    or a list of per-module dicts (this image's 0.4.x) — handle both."""
    analysis = jitted.lower(*spec).compile().cost_analysis()
    if analysis is None:
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not analysis:
        return None
    flops = analysis.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


_PENDING = object()  # analysis in flight on the background thread


class StepCostModel:
    """Caches per-shape step FLOPs and tracks the step period EWMA."""

    def __init__(self):
        self._enabled = enabled()
        self._peak = peak_flops() if self._enabled else None
        # shape key -> float (analyzed) | None (failed) | _PENDING
        self._flops = {}
        self._last_ts = None
        self._last_key = None
        self._period_ewma = None

    def observe(self, jitted, args, key_args=None):
        """Record one about-to-run (or just-dispatched) training step.

        Call once per train_minibatch with the jitted step callable and
        the exact argument tuple it runs with. `key_args` (default: all
        of args) is the subtree whose shapes key the cache — trainers
        pass the (features, labels) batch so the hot path never flattens
        the full parameter tree; FLOPs for secondary shape variation
        (e.g. per-batch embedding row counts) reuse the first sighting's
        estimate. The AOT lowering itself runs on a daemon thread against
        a ShapeDtypeStruct spec, so the training loop never blocks on the
        analysis compile."""
        if not self._enabled or jitted is None:
            return
        now = time.perf_counter()
        try:
            key = shape_key(args if key_args is None else key_args)
        except Exception:
            return
        if key not in self._flops:
            self._flops[key] = _PENDING
            try:
                import jax

                spec = jax.tree_util.tree_map(
                    lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
                    args,
                )
            except Exception:
                # Missing cost analysis degrades to absent gauges.
                self._flops[key] = None
            else:
                threading.Thread(
                    target=self._analyze,
                    args=(jitted, spec, key),
                    name="edl-mfu-analysis",
                    daemon=True,
                ).start()
        flops = self._flops[key]
        if not isinstance(flops, float):
            flops = None
        if (
            self._last_ts is not None
            and self._last_key == key
            and now > self._last_ts
        ):
            period = now - self._last_ts
            self._period_ewma = (
                period
                if self._period_ewma is None
                else _EWMA_ALPHA * period
                + (1 - _EWMA_ALPHA) * self._period_ewma
            )
            _STEP_PERIOD.set(self._period_ewma)
            if flops is not None:
                _STEP_FLOPS.set(flops)
                if self._peak:
                    _MFU.set(
                        flops / (self._period_ewma * self._peak)
                    )
        self._last_ts = now
        self._last_key = key

    def _analyze(self, jitted, spec, key):
        try:
            self._flops[key] = _analyzed_flops(jitted, spec)
        except Exception:
            logger.info(
                "Step cost analysis unavailable; MFU gauges disabled "
                "for this shape",
                exc_info=True,
            )
            self._flops[key] = None
